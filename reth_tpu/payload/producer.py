"""Continuous block production: a standing hot candidate over the pool.

Reference analogue: the payload-builder service's improvement loop
(crates/payload/basic) fused with the pool's event listeners
(crates/transaction-pool/src/pool/events.rs) — but where the reference
re-runs `try_build` from scratch on every tick, this producer keeps ONE
hot candidate per parent and refreshes it *incrementally*:

- The greedy selection pass over ``pool.best_transactions`` is recorded
  as a **considered trace** — one ``(tx, verdict, sender)`` entry per
  stream position with verdicts ``sel`` / ``skip`` / ``invalid`` that
  mirror the serial builder's loop (builder.py) decision for decision.
- On a pool event (add / replace / drop / canon) the producer re-reads
  the best stream, finds the longest position-wise common prefix with
  the trace, and re-executes ONLY from the divergence point: the EVM
  state is restored from the nearest selected-rank **checkpoint**
  (a cheap structural fork of :class:`EvmState` — Accounts are replaced
  functionally, so shallow dict copies suffice), the known-good selected
  prefix beyond the checkpoint is replayed, and the greedy loop resumes
  on the new stream tail. A tx landing below every pooled tip costs one
  execution; a new best tx costs a rebuild — exactly the serial
  semantics, paid lazily.
- The candidate rides the import pipeline's **commit window**
  (engine/block_pipeline.py): when block N is committing, the producer
  builds N+1's candidate against N's frozen overlay layers so payload
  build overlaps state-root/commit — the producer-side twin of PR 17's
  cross-block import pipeline. Sealing waits for the window to close
  (the state-root job must anchor on committed layers); a failed window
  discards the candidate.

Invariant the whole design hangs on (asserted by the txflow bench and
the differential tests): at pool-sequence parity, ``candidate.selected``
is bit-identical to what one serial ``build_payload`` greedy pass over
the same pool would select.
"""

from __future__ import annotations

import threading
import time

from ..evm import BlockExecutor
from ..evm.executor import InvalidTransaction, ProviderStateSource
from ..evm.state import BlockChanges, EvmState
from ..primitives.types import Receipt, Transaction
from ..storage.overlay import OverlayTx
from ..storage.provider import DatabaseProvider
from .builder import PayloadAttributes, _MiniOutput, _seal, payload_env


def _fork_state(state: EvmState) -> EvmState:
    """Independent copy of the cross-tx world state, safe to execute on.

    Account objects are immutable (replaced via ``with_()``), so account
    maps copy shallowly; per-address storage dicts mutate in place and
    need one level of copy. Per-tx fields (journal, warm sets, refund)
    are left fresh — forks are only taken at tx boundaries, where
    ``begin_tx`` would reset them anyway.
    """
    out = EvmState(state.source)
    out._accounts = dict(state._accounts)
    out._storage = {a: dict(s) for a, s in state._storage.items()}
    out._code = dict(state._code)
    out.changes = BlockChanges(
        accounts=dict(state.changes.accounts),
        storage={a: dict(s) for a, s in state.changes.storage.items()},
        wiped_storage=set(state.changes.wiped_storage),
        new_bytecodes=dict(state.changes.new_bytecodes),
    )
    out._touched = set(state._touched)
    out._selfdestructs = set(state._selfdestructs)
    out._pending_destructs = set(state._pending_destructs)
    out._logs = list(state._logs)
    return out


class _Considered:
    """One greedy-loop decision: how the pass treated one stream entry."""

    __slots__ = ("tx", "verdict", "sender")

    def __init__(self, tx: Transaction, verdict: str, sender: bytes | None):
        self.tx = tx
        self.verdict = verdict  # "sel" | "skip" | "invalid"
        self.sender = sender


class _Candidate:
    """Hot candidate for one (parent, attrs) slot."""

    def __init__(self, parent_hash, parent, attrs, gas_ceiling, overlay,
                 env, base_fee, cancun, excess_blob, blob_params, window):
        self.parent_hash = parent_hash
        self.parent = parent
        self.attrs = attrs
        self.gas_ceiling = gas_ceiling
        self.overlay = overlay
        self.env = env
        self.base_fee = base_fee
        self.cancun = cancun
        self.excess_blob = excess_blob
        self.blob_params = blob_params
        self.window = window              # CommitWindow riding, or None
        self.executor = None              # set by the producer
        self.state: EvmState | None = None
        self.considered: list[_Considered] = []
        self.selected: list[Transaction] = []
        self.receipts: list[Receipt] = []
        self.cum_gas = 0
        self.blob_gas = 0
        self.fees = 0
        self.pool_seq = -1                # pool.event_seq this trace matches
        # selected-rank -> (state fork, cum_gas, blob_gas, fees)
        self.checkpoints: dict[int, tuple] = {}
        self.built_at = time.monotonic()


class BlockProducer:
    """Standing producer thread maintaining the hot candidate.

    ``take()`` is the consumer API: the dev miner and the payload-job
    service call it to seal the current candidate (building synchronously
    on a cache miss), so a hot hit turns getPayload/mine into a pure
    seal — no execution on the critical path.
    """

    def __init__(self, tree, pool, lock=None, block_time: int = 12,
                 fee_recipient: bytes = b"\x00" * 20,
                 checkpoint_every: int = 16, interval: float = 0.05,
                 ride_windows: bool = True):
        self.tree = tree
        self.pool = pool
        self.block_time = max(1, int(block_time))
        self.fee_recipient = fee_recipient
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.interval = interval
        self.ride_windows = ride_windows
        self._lock = lock or threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.candidate: _Candidate | None = None
        self._pinned: tuple[bytes, PayloadAttributes] | None = None
        # plain-assignment flag set from pool/canon listener threads (no
        # lock: lock-order with pool._lock must stay one-directional)
        self._stale_since: float | None = None
        # counters (mirrored into producer_metrics)
        self.refreshes = 0
        self.full_rebuilds = 0
        self.window_builds = 0
        self.reexec_ranks = 0
        self.exec_ranks = 0
        self.invalidated = 0
        self.hits = 0
        self.misses = 0
        self.sealed = 0
        self.errors = 0
        self.last_refresh_wall = 0.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.pool.add_listener(self._on_pool_event)
        self.tree.canon_listeners.append(self._on_canon)
        if self.ride_windows and getattr(self.tree, "pipeline", None) is not None:
            self.tree.pipeline.open_listeners.append(self._on_window)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="block-producer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.pool.remove_listener(self._on_pool_event)
        if self._on_canon in self.tree.canon_listeners:
            self.tree.canon_listeners.remove(self._on_canon)
        pipe = getattr(self.tree, "pipeline", None)
        if pipe is not None and self._on_window in pipe.open_listeners:
            pipe.open_listeners.remove(self._on_window)

    # listener callbacks run on foreign threads (pool lock / insert
    # thread held) — they only flag and wake, never take self._lock
    def _on_pool_event(self, ev: dict) -> None:
        if self._stale_since is None:
            self._stale_since = time.monotonic()
        self._wake.set()

    def _on_canon(self, chain) -> None:
        if self._stale_since is None:
            self._stale_since = time.monotonic()
        self._wake.set()

    def _on_window(self, win) -> None:
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                with self._lock:
                    self._ensure_locked()
            except Exception:  # noqa: BLE001 — a poisoned candidate must
                # never kill the producer: drop it, rebuild next tick
                self.errors += 1
                with self._lock:
                    self.candidate = None
                time.sleep(0.05)
            try:
                from ..metrics import producer_metrics

                producer_metrics.set_staleness(self.staleness())
            except Exception:  # noqa: BLE001
                pass

    # -- target selection ----------------------------------------------------

    def _target_window(self):
        if not self.ride_windows:
            return None
        pipe = getattr(self.tree, "pipeline", None)
        if pipe is None:
            return None
        win = pipe.current_window()
        if win is not None and not win.done.is_set():
            return win
        return None

    def _ensure_locked(self) -> None:
        cand = self.candidate
        # a window-parented candidate whose window resolved: adopt (parent
        # is canonical in-memory now) or discard (parent never lands)
        if cand is not None and cand.window is not None and cand.window.done.is_set():
            if cand.window.ok:
                cand.window = None
            else:
                cand = self.candidate = None
        win = self._target_window()
        parent_hash = win.block_hash if win is not None else self.tree.head_hash
        if cand is None or cand.parent_hash != parent_hash:
            self._rebuild_locked(parent_hash, win)
        else:
            self._refresh_locked()

    def _attrs_for(self, parent_hash: bytes, parent) -> PayloadAttributes:
        if self._pinned is not None and self._pinned[0] == parent_hash:
            return self._pinned[1]
        # same timestamp rule as the dev miner: strictly increasing
        return PayloadAttributes(
            timestamp=max(parent.timestamp + self.block_time,
                          parent.timestamp + 1),
            suggested_fee_recipient=self.fee_recipient,
        )

    def _rebuild_locked(self, parent_hash: bytes, win=None,
                        attrs: PayloadAttributes | None = None,
                        gas_ceiling: int | None = None) -> None:
        """Fresh candidate for ``parent_hash`` (greedy pass runs via the
        refresh path against an empty trace)."""
        if win is not None:
            # ride the commit window: N's frozen layers serve N+1's reads
            # while N's state root is still being committed
            parent = win.block.header
            overlay = DatabaseProvider(OverlayTx(
                self.tree.factory.db.tx(),
                list(win.parent_layers) + [win.exec_layer], {}))
            self.window_builds += 1
        else:
            overlay = self.tree.overlay_provider(parent_hash)
            parent = overlay.header_by_number(overlay.block_number(parent_hash))
        if attrs is None:
            attrs = self._attrs_for(parent_hash, parent)
        env, base_fee, cancun, excess_blob, blob_params = payload_env(
            self.tree, parent, attrs, gas_ceiling)
        cand = _Candidate(parent_hash, parent, attrs, gas_ceiling, overlay,
                          env, base_fee, cancun, excess_blob, blob_params,
                          win)
        cand.executor = BlockExecutor(ProviderStateSource(overlay),
                                      self.tree.config)
        cand.state = EvmState(cand.executor.source)
        cand.checkpoints[0] = (_fork_state(cand.state), 0, 0, 0)
        self.candidate = cand
        self.full_rebuilds += 1
        self._refresh_locked()

    # -- the incremental refresh ----------------------------------------------

    def _refresh_locked(self) -> None:
        cand = self.candidate
        pool = self.pool
        t0 = time.monotonic()
        with pool._lock:
            seq = pool.event_seq
            if seq == cand.pool_seq:
                self._stale_since = None
                return
            # anchor check: the pool's executable stream is computed
            # against the CANONICAL head's state. Refreshing a candidate
            # parented elsewhere (a commit landed between target
            # resolution and this refresh) would execute head-N+1 nonces
            # on head-N state and wrongly evict valid txs as invalid —
            # abort and let the run loop rebuild on the new parent. A
            # window-parented candidate is exempt: its overlay is AHEAD
            # of the pool's view, so spurious evictions there are
            # nonce-too-low txs the in-flight block already mined.
            if cand.window is None and self.tree.head_hash != cand.parent_hash:
                return
            stream = list(pool.best_transactions(cand.base_fee))
        # longest position-wise common prefix of stream vs trace. Entries
        # with verdict "invalid" never match (remove_invalid evicted them
        # from the pool), so an eviction truncates the trace there — which
        # is exactly serial semantics: a fresh pass would not see the
        # evicted tx, and its sender must NOT stay in failed_senders.
        considered = cand.considered
        j = 0
        while (j < len(stream) and j < len(considered)
               and stream[j].hash == considered[j].tx.hash):
            j += 1
        if j == len(stream) and j == len(considered):
            cand.pool_seq = seq
            self._stale_since = None
            # a rebuild (head change) resets ``selected`` without a
            # stream-changing refresh — re-anchor the ranks gauge here or
            # it keeps the previous candidate's count
            from ..metrics import producer_metrics
            producer_metrics.sync_ranks(len(cand.selected))
            return
        self.refreshes += 1
        env, base_fee = cand.env, cand.base_fee
        executor = cand.executor
        # selected rank at the divergence point, then the nearest
        # checkpoint at-or-below it
        r = sum(1 for c in considered[:j] if c.verdict == "sel")
        ck = max(k for k in cand.checkpoints if k <= r)
        cand.checkpoints = {k: v for k, v in cand.checkpoints.items()
                            if k <= ck}
        st, cum_gas, blob_gas, total_fees = cand.checkpoints[ck]
        state = _fork_state(st)
        selected = cand.selected[:ck]
        receipts = cand.receipts[:ck]
        failed_senders = {c.sender for c in considered[:j]
                          if c.verdict == "invalid" and c.sender is not None}
        trace = considered[:j]
        # replay the known-good selected ranks between the checkpoint and
        # the divergence point (identical state in, identical receipts out)
        replay = [c for c in trace if c.verdict == "sel"][ck:]
        for c in replay:
            result = executor._execute_tx(state, env, c.tx, c.sender,
                                          env.gas_limit - cum_gas)
            cum_gas += result.gas_used
            blob_gas += c.tx.blob_gas()
            total_fees += result.gas_used * max(
                0, c.tx.effective_gas_price(base_fee) - base_fee)
            selected.append(c.tx)
            receipts.append(Receipt(
                tx_type=c.tx.tx_type, success=result.success,
                cumulative_gas_used=cum_gas, logs=result.receipt.logs))
            self.reexec_ranks += 1
            if len(selected) % self.checkpoint_every == 0:
                cand.checkpoints[len(selected)] = (
                    _fork_state(state), cum_gas, blob_gas, total_fees)
        # greedy continuation over the new stream tail — decision for
        # decision the serial loop in builder.build_payload
        own_events = 0
        for tx in stream[j:]:
            if cum_gas + tx.gas_limit > env.gas_limit:
                trace.append(_Considered(tx, "skip", None))
                continue
            if tx.blob_gas() and (
                not cand.cancun
                or blob_gas + tx.blob_gas() > cand.blob_params.max_gas
            ):
                trace.append(_Considered(tx, "skip", None))
                continue
            try:
                sender = tx.recover_sender()
                if sender in failed_senders:
                    trace.append(_Considered(tx, "skip", sender))
                    continue
                result = executor._execute_tx(state, env, tx, sender,
                                              env.gas_limit - cum_gas)
            except (InvalidTransaction, ValueError):
                try:
                    sender = tx.recover_sender()
                    failed_senders.add(sender)
                except ValueError:
                    sender = None
                with pool._lock:
                    s0 = pool.event_seq
                    pool.remove_invalid(tx.hash)
                    own_events += pool.event_seq - s0
                trace.append(_Considered(tx, "invalid", sender))
                self.invalidated += 1
                continue
            cum_gas += result.gas_used
            blob_gas += tx.blob_gas()
            total_fees += result.gas_used * max(
                0, tx.effective_gas_price(base_fee) - base_fee)
            selected.append(tx)
            receipts.append(Receipt(
                tx_type=tx.tx_type, success=result.success,
                cumulative_gas_used=cum_gas, logs=result.receipt.logs))
            trace.append(_Considered(tx, "sel", sender))
            self.exec_ranks += 1
            if len(selected) % self.checkpoint_every == 0:
                cand.checkpoints[len(selected)] = (
                    _fork_state(state), cum_gas, blob_gas, total_fees)
        cand.considered = trace
        cand.selected = selected
        cand.receipts = receipts
        cand.state = state
        cand.cum_gas = cum_gas
        cand.blob_gas = blob_gas
        cand.fees = total_fees
        # remove_invalid above bumped the seq; the trace accounts for those
        # evictions already (they are "invalid" entries), so fold exactly
        # OUR eviction events into the parity stamp — and no more: a
        # concurrent add landing mid-refresh must leave pool_seq behind
        # the live seq so the next pass picks it up instead of silently
        # skipping it until the next unrelated event
        cand.pool_seq = seq + own_events
        self._stale_since = None
        self.last_refresh_wall = time.monotonic() - t0
        try:
            from ..metrics import producer_metrics

            producer_metrics.record_refresh(
                self.last_refresh_wall, ranks=len(selected),
                reexec=len(replay), fresh=len(stream) - j)
        except Exception:  # noqa: BLE001
            pass

    # -- consumption ----------------------------------------------------------

    def prepare(self, parent_hash: bytes, attrs: PayloadAttributes) -> None:
        """Pin explicit attributes for a parent (engine FCU-with-attrs
        path) and wake the producer to build toward them."""
        with self._lock:
            self._pinned = (parent_hash, attrs)
            cand = self.candidate
            if cand is not None and cand.parent_hash == parent_hash \
                    and cand.attrs != attrs:
                self.candidate = None
        self._wake.set()

    def take(self, parent_hash: bytes | None = None,
             attrs: PayloadAttributes | None = None,
             extra_data: bytes = b"", gas_ceiling: int | None = None,
             timeout: float = 30.0):
        """Seal the hot candidate for ``parent_hash`` (default: canonical
        head); returns ``(block, total_priority_fees)``. A matching hot
        candidate is refreshed to pool parity and sealed; anything else
        (cold start, different parent/attrs/gas ceiling) builds
        synchronously first. The candidate itself stays hot — sealing
        does not consume it."""
        with self._lock:
            want = parent_hash if parent_hash is not None else self.tree.head_hash
            if attrs is not None:
                self._pinned = (want, attrs)
            cand = self.candidate
            stale = (
                cand is None
                or cand.parent_hash != want
                or (attrs is not None and cand.attrs != attrs)
                or (gas_ceiling is not None and cand.gas_ceiling != gas_ceiling)
            )
            if not stale and cand.window is not None:
                # the state-root job in _seal anchors on committed layers:
                # wait out the window (its close is the pipelined commit
                # this candidate overlapped with)
                if not cand.window.done.wait(timeout):
                    raise TimeoutError("commit window did not close")
                if cand.window.ok:
                    cand.window = None
                else:
                    self.candidate = None
                    stale = True
            if stale:
                self.misses += 1
                self._rebuild_locked(want, None, attrs=attrs,
                                     gas_ceiling=gas_ceiling)
                cand = self.candidate
            else:
                self.hits += 1
                self._refresh_locked()
            return self._seal_locked(cand, extra_data)

    def _seal_locked(self, cand: _Candidate, extra_data: bytes):
        state = _fork_state(cand.state)
        for w in cand.attrs.withdrawals:
            if w.amount:
                state._capture_account_change(w.address)
                state.add_balance(w.address, w.amount * 10**9)
        post_accounts, post_storage = state.final_state()
        out = _MiniOutput(state.changes, post_accounts, post_storage,
                          list(cand.receipts))
        # re-anchor on the tree's own overlay: the frozen window overlay
        # served execution reads, but sealing needs the committed chain
        overlay = self.tree.overlay_provider(cand.parent_hash)
        block, fees = _seal(self.tree, overlay, cand.parent_hash, cand.attrs,
                            cand.env, extra_data, list(cand.selected), out,
                            cand.cum_gas, cand.blob_gas, cand.excess_blob,
                            cand.cancun, cand.base_fee, cand.fees)
        self.sealed += 1
        return block, fees

    # -- introspection ---------------------------------------------------------

    def staleness(self) -> float:
        """Seconds the hot candidate has lagged the pool (0 when in
        sync). Feeds the producer-staleness SLO."""
        since = self._stale_since
        return 0.0 if since is None else max(0.0, time.monotonic() - since)

    def snapshot(self) -> dict:
        cand = self.candidate
        return {
            "parent": cand.parent_hash.hex() if cand is not None else None,
            "ranks": len(cand.selected) if cand is not None else 0,
            "considered": len(cand.considered) if cand is not None else 0,
            "gas": cand.cum_gas if cand is not None else 0,
            "fees": cand.fees if cand is not None else 0,
            "window": bool(cand is not None and cand.window is not None),
            "pool_seq": cand.pool_seq if cand is not None else -1,
            "refreshes": self.refreshes,
            "full_rebuilds": self.full_rebuilds,
            "window_builds": self.window_builds,
            "exec_ranks": self.exec_ranks,
            "reexec_ranks": self.reexec_ranks,
            "invalidated": self.invalidated,
            "hits": self.hits,
            "misses": self.misses,
            "sealed": self.sealed,
            "errors": self.errors,
            "staleness_s": round(self.staleness(), 3),
            "last_refresh_wall_s": round(self.last_refresh_wall, 6),
        }
