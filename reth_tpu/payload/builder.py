"""Block assembly from pool transactions.

Reference analogue: `EthereumPayloadBuilder::try_build`
(crates/ethereum/payload/src/lib.rs) — pull `best_transactions`, execute
greedily under the gas limit, skip invalid txs, seal with real roots.
The built block is re-validated when the CL returns it via newPayload
(same trust model as the reference).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..consensus.validation import calc_next_base_fee
from ..engine.tree import EngineTree
from ..evm import BlockExecutor, EvmConfig
from ..evm.executor import InvalidTransaction, ProviderStateSource
from ..evm.interpreter import BlockEnv
from ..evm.state import EvmState
from ..primitives.rlp import rlp_encode
from ..primitives.types import Block, Header, Receipt, Transaction, Withdrawal, logs_bloom
from ..storage.overlay import OverlayTx
from ..storage.provider import DatabaseProvider
from ..trie.state_root import ordered_trie_root


@dataclass
class PayloadAttributes:
    """engine_forkchoiceUpdated payload attributes (V2/V3 shape)."""

    timestamp: int
    prev_randao: bytes = b"\x00" * 32
    suggested_fee_recipient: bytes = b"\x00" * 20
    withdrawals: tuple[Withdrawal, ...] = ()
    parent_beacon_block_root: bytes | None = None


def build_payload(
    tree: EngineTree,
    pool,
    parent_hash: bytes,
    attrs: PayloadAttributes,
) -> Block:
    """Assemble a sealed block on top of ``parent_hash``."""
    from ..evm.executor import MAX_BLOB_GAS_PER_BLOCK, blob_base_fee, next_excess_blob_gas

    overlay = tree.overlay_provider(parent_hash)
    parent_num = overlay.block_number(parent_hash)
    parent = overlay.header_by_number(parent_num)
    base_fee = calc_next_base_fee(parent)
    # EIP-4844: blob fields continue once the parent carries them
    cancun = parent.excess_blob_gas is not None
    excess_blob = (
        next_excess_blob_gas(parent.excess_blob_gas, parent.blob_gas_used or 0)
        if cancun else 0
    )
    env = BlockEnv(
        number=parent.number + 1,
        timestamp=attrs.timestamp,
        coinbase=attrs.suggested_fee_recipient,
        gas_limit=parent.gas_limit,
        base_fee=base_fee,
        prev_randao=attrs.prev_randao,
        chain_id=tree.config.chain_id,
        blob_base_fee=blob_base_fee(excess_blob),
    )
    executor = BlockExecutor(ProviderStateSource(overlay), tree.config)
    state = EvmState(executor.source)
    selected: list[Transaction] = []
    receipts: list[Receipt] = []
    cumulative_gas = 0
    blob_gas_used = 0
    for tx in pool.best_transactions(base_fee):
        if cumulative_gas + tx.gas_limit > env.gas_limit:
            continue
        if tx.blob_gas() and (
            not cancun or blob_gas_used + tx.blob_gas() > MAX_BLOB_GAS_PER_BLOCK
        ):
            continue
        try:
            sender = tx.recover_sender()
            result = executor._execute_tx(
                state, env, tx, sender, env.gas_limit - cumulative_gas
            )
        except (InvalidTransaction, ValueError):
            continue  # skip; pool maintenance will evict later
        cumulative_gas += result.gas_used
        blob_gas_used += tx.blob_gas()
        selected.append(tx)
        receipts.append(Receipt(
            tx_type=tx.tx_type, success=result.success,
            cumulative_gas_used=cumulative_gas, logs=result.receipt.logs,
        ))
    # withdrawals
    for w in attrs.withdrawals:
        if w.amount:
            state._capture_account_change(w.address)
            state.add_balance(w.address, w.amount * 10**9)

    # state root over a scratch overlay (not retained; newPayload re-derives)
    post_accounts, post_storage = state.final_state()
    out = _MiniOutput(state.changes, post_accounts, post_storage, receipts)
    scratch = DatabaseProvider(OverlayTx(tree.factory.db.tx(),
                                         tree._chain_layers(parent_hash), {}))
    root = tree._state_root_job(scratch, out)

    header = Header(
        parent_hash=parent_hash,
        beneficiary=attrs.suggested_fee_recipient,
        state_root=root,
        transactions_root=ordered_trie_root([t.encode() for t in selected], tree.committer),
        receipts_root=ordered_trie_root([r.encode_2718() for r in receipts], tree.committer),
        logs_bloom=logs_bloom([l for r in receipts for l in r.logs]),
        number=parent.number + 1,
        gas_limit=env.gas_limit,
        gas_used=cumulative_gas,
        timestamp=attrs.timestamp,
        mix_hash=attrs.prev_randao,
        base_fee_per_gas=base_fee,
        withdrawals_root=ordered_trie_root(
            [rlp_encode(w.rlp_fields()) for w in attrs.withdrawals], tree.committer
        ),
        blob_gas_used=blob_gas_used if cancun else None,
        excess_blob_gas=excess_blob if cancun else None,
        parent_beacon_block_root=attrs.parent_beacon_block_root,
    )
    return Block(header, tuple(selected), (), tuple(attrs.withdrawals))


@dataclass
class _MiniOutput:
    changes: object
    post_accounts: dict
    post_storage: dict
    receipts: list


class PayloadBuilderService:
    """payload_id → built block store (reference PayloadBuilderService).

    Bounded: only the newest ``MAX_JOBS`` payloads are retained (reference
    jobs resolve/expire; a CL issues one per slot)."""

    MAX_JOBS = 16

    def __init__(self, tree: EngineTree, pool):
        self.tree = tree
        self.pool = pool
        self.jobs: dict[bytes, Block] = {}

    def new_payload_job(self, parent_hash: bytes, attrs: PayloadAttributes) -> bytes:
        payload_id = os.urandom(8)
        self.jobs[payload_id] = build_payload(self.tree, self.pool, parent_hash, attrs)
        while len(self.jobs) > self.MAX_JOBS:
            self.jobs.pop(next(iter(self.jobs)))
        return payload_id

    def get_payload(self, payload_id: bytes) -> Block | None:
        return self.jobs.get(payload_id)
