"""Block assembly from pool transactions.

Reference analogue: `EthereumPayloadBuilder::try_build`
(crates/ethereum/payload/src/lib.rs) — pull `best_transactions`, execute
greedily under the gas limit, skip invalid txs, seal with real roots.
The built block is re-validated when the CL returns it via newPayload
(same trust model as the reference).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from ..consensus.validation import calc_next_base_fee
from ..engine.tree import EngineTree
from ..evm import BlockExecutor, EvmConfig
from ..evm.executor import InvalidTransaction, ProviderStateSource
from ..evm.interpreter import BlockEnv
from ..evm.state import EvmState
from ..primitives.rlp import rlp_encode
from ..primitives.types import Block, Header, Receipt, Transaction, Withdrawal, logs_bloom
from ..storage.overlay import OverlayTx
from ..storage.provider import DatabaseProvider
from ..trie.state_root import ordered_trie_root


@dataclass
class PayloadAttributes:
    """engine_forkchoiceUpdated payload attributes (V2/V3 shape)."""

    timestamp: int
    prev_randao: bytes = b"\x00" * 32
    suggested_fee_recipient: bytes = b"\x00" * 20
    withdrawals: tuple[Withdrawal, ...] = ()
    parent_beacon_block_root: bytes | None = None


def payload_env(tree: EngineTree, parent: Header, attrs: PayloadAttributes,
                gas_ceiling: int | None = None):
    """Fee-market + block-env context for a child of ``parent``; returns
    ``(env, base_fee, cancun, excess_blob, blob_params)``. Shared by the
    one-shot builder below and the continuous producer (producer.py),
    which must price candidates identically or its incremental candidate
    diverges from the serial greedy build."""
    from ..evm.executor import blob_base_fee, next_excess_blob_gas

    base_fee = calc_next_base_fee(parent)
    blob_params = tree.config.blob_params_for(parent.number + 1, attrs.timestamp)
    # EIP-4844: blob fields continue once the parent carries them
    cancun = parent.excess_blob_gas is not None
    excess_blob = (
        next_excess_blob_gas(parent.excess_blob_gas, parent.blob_gas_used or 0,
                             blob_params.target_gas)
        if cancun else 0
    )
    # gas target moves toward the miner's ceiling by at most 1/1024 per
    # block (protocol rule the reference's gas-limit knob follows)
    gas_limit = parent.gas_limit
    if gas_ceiling is not None and gas_ceiling != gas_limit:
        step = max(1, parent.gas_limit // 1024 - 1)
        gas_limit = (min(gas_limit + step, gas_ceiling) if gas_ceiling > gas_limit
                     else max(gas_limit - step, gas_ceiling, 5000))
    env = BlockEnv(
        number=parent.number + 1,
        timestamp=attrs.timestamp,
        coinbase=attrs.suggested_fee_recipient,
        gas_limit=gas_limit,
        base_fee=base_fee,
        prev_randao=attrs.prev_randao,
        chain_id=tree.config.chain_id,
        blob_base_fee=blob_base_fee(excess_blob, blob_params.update_fraction),
    )
    return env, base_fee, cancun, excess_blob, blob_params


def build_payload(
    tree: EngineTree,
    pool,
    parent_hash: bytes,
    attrs: PayloadAttributes,
    extra_data: bytes = b"",
    gas_ceiling: int | None = None,
) -> Block:
    """Assemble a sealed block on top of ``parent_hash``; returns
    (block, total priority fees). ``pool=None`` builds the empty-payload
    fallback (reference BasicPayloadJob's pre-built empty payload)."""
    overlay = tree.overlay_provider(parent_hash)
    parent_num = overlay.block_number(parent_hash)
    parent = overlay.header_by_number(parent_num)
    env, base_fee, cancun, excess_blob, blob_params = payload_env(
        tree, parent, attrs, gas_ceiling)
    executor = BlockExecutor(ProviderStateSource(overlay), tree.config)
    state = EvmState(executor.source)
    selected: list[Transaction] = []
    receipts: list[Receipt] = []
    cumulative_gas = 0
    blob_gas_used = 0
    total_fees = 0
    # --parallel-exec: execute the candidate list through the optimistic
    # scheduler (engine/optimistic.py payload mode) — speculative parallel
    # first attempts, in-order validation, builder-semantics skips for
    # unexecutable candidates. Any scheduler failure falls back to the
    # serial greedy loop below (same selection, just slower).
    if pool is not None and getattr(tree, "parallel_exec", False):
        built = _build_parallel(tree, pool, overlay, env, base_fee,
                                cancun, blob_params, attrs)
        if built is not None:
            selected, out_mini, cumulative_gas, blob_gas_used, total_fees = built
            return _seal(tree, overlay, parent_hash, attrs, env, extra_data,
                         selected, out_mini, cumulative_gas, blob_gas_used,
                         excess_blob, cancun, base_fee, total_fees)
    failed_senders: set[bytes] = set()
    txs_iter = pool.best_transactions(base_fee) if pool is not None else ()
    for tx in txs_iter:
        if cumulative_gas + tx.gas_limit > env.gas_limit:
            continue
        if tx.blob_gas() and (
            not cancun or blob_gas_used + tx.blob_gas() > blob_params.max_gas
        ):
            continue
        try:
            sender = tx.recover_sender()
            if sender in failed_senders:
                continue  # descendant of an evicted tx: nonce-gapped now
            result = executor._execute_tx(
                state, env, tx, sender, env.gas_limit - cumulative_gas
            )
        except (InvalidTransaction, ValueError):
            # provably unexecutable against this state: evict it (reference
            # mark_invalid), or an instant-seal miner re-selects it forever;
            # later nonces of the same sender are skipped but kept pooled
            try:
                failed_senders.add(tx.recover_sender())
            except ValueError:
                pass
            if pool is not None:
                pool.remove_invalid(tx.hash)
            continue
        cumulative_gas += result.gas_used
        blob_gas_used += tx.blob_gas()
        total_fees += result.gas_used * max(0, tx.effective_gas_price(base_fee) - base_fee)
        selected.append(tx)
        receipts.append(Receipt(
            tx_type=tx.tx_type, success=result.success,
            cumulative_gas_used=cumulative_gas, logs=result.receipt.logs,
        ))
    # withdrawals
    for w in attrs.withdrawals:
        if w.amount:
            state._capture_account_change(w.address)
            state.add_balance(w.address, w.amount * 10**9)

    post_accounts, post_storage = state.final_state()
    out = _MiniOutput(state.changes, post_accounts, post_storage, receipts)
    return _seal(tree, overlay, parent_hash, attrs, env, extra_data,
                 selected, out, cumulative_gas, blob_gas_used, excess_blob,
                 cancun, base_fee, total_fees)


def _build_parallel(tree, pool, overlay, env, base_fee, cancun, blob_params,
                    attrs):
    """Candidate selection through the optimistic scheduler; returns
    ``(selected, mini_output, cumulative_gas, blob_gas_used, total_fees)``
    or None (caller falls back to the serial greedy loop)."""
    try:
        from ..engine.optimistic import execute_candidates_optimistic
        from ..primitives.types import recover_senders

        candidates = list(pool.best_transactions(base_fee))
        if len(candidates) < 4:
            return None
        rec = recover_senders(candidates)
        txs, senders = [], []
        for tx, s in zip(candidates, rec):
            if s is None:
                pool.remove_invalid(tx.hash)
                continue
            txs.append(tx)
            senders.append(s)
        out, committed, evicted, blob_gas_used, _stats = \
            execute_candidates_optimistic(
                ProviderStateSource(overlay), env, txs, senders,
                tree.config, max_workers=getattr(tree, "exec_workers", None),
                withdrawals=attrs.withdrawals,
                blob_cap=blob_params.max_gas if cancun else None)
        for i in evicted:
            pool.remove_invalid(txs[i].hash)
        selected = [txs[i] for i in committed]
        total_fees = 0
        prev = 0
        for i, r in zip(committed, out.receipts):
            gas_used = r.cumulative_gas_used - prev
            prev = r.cumulative_gas_used
            total_fees += gas_used * max(
                0, txs[i].effective_gas_price(base_fee) - base_fee)
        mini = _MiniOutput(out.changes, out.post_accounts, out.post_storage,
                           out.receipts)
        return selected, mini, out.gas_used, blob_gas_used, total_fees
    except Exception:  # noqa: BLE001 — the serial loop is the fallback
        return None


def _seal(tree, overlay, parent_hash, attrs, env, extra_data, selected, out,
          cumulative_gas, blob_gas_used, excess_blob, cancun, base_fee,
          total_fees):
    """State root + header assembly shared by the serial and parallel
    selection paths (the sealed block is identical either way)."""
    parent_num = overlay.block_number(parent_hash)
    parent = overlay.header_by_number(parent_num)
    receipts = out.receipts
    # state root over a scratch overlay (not retained; newPayload re-derives)
    scratch = DatabaseProvider(OverlayTx(tree.factory.db.tx(),
                                         tree._chain_layers(parent_hash), {}))
    root = tree._state_root_job(scratch, out)

    # payload-build hashing rides its own hash-service lane (below live,
    # above rebuild/proof): an improvement-loop rebuild coalesces with but
    # never delays the canonical tip's root job
    committer = (tree.committer.for_lane("payload")
                 if hasattr(tree.committer, "for_lane") else tree.committer)
    header = Header(
        parent_hash=parent_hash,
        beneficiary=attrs.suggested_fee_recipient,
        state_root=root,
        transactions_root=ordered_trie_root([t.encode() for t in selected], committer),
        receipts_root=ordered_trie_root([r.encode_2718() for r in receipts], committer),
        logs_bloom=logs_bloom([l for r in receipts for l in r.logs]),
        number=parent.number + 1,
        gas_limit=env.gas_limit,
        gas_used=cumulative_gas,
        timestamp=attrs.timestamp,
        extra_data=extra_data,
        mix_hash=attrs.prev_randao,
        base_fee_per_gas=base_fee,
        withdrawals_root=ordered_trie_root(
            [rlp_encode(w.rlp_fields()) for w in attrs.withdrawals], committer
        ),
        blob_gas_used=blob_gas_used if cancun else None,
        excess_blob_gas=excess_blob if cancun else None,
        parent_beacon_block_root=attrs.parent_beacon_block_root,
    )
    return Block(header, tuple(selected), (), tuple(attrs.withdrawals)), total_fees


@dataclass
class _MiniOutput:
    changes: object
    post_accounts: dict
    post_storage: dict
    receipts: list


class PayloadJob:
    """One deadline-driven payload build (reference BasicPayloadJob,
    crates/payload/basic/src/lib.rs:366).

    The first FULL build happens synchronously (so an immediate
    getPayload already carries transactions); an improvement loop then
    re-builds until the deadline and swaps in a payload ONLY when it
    pays more fees. If the full build fails, the empty-payload fallback
    keeps the job resolvable (a slot must never go blockless)."""

    def __init__(self, tree, pool, parent_hash, attrs, lock, deadline: float,
                 interval: float, extra_data: bytes = b"",
                 gas_ceiling: int | None = None, producer=None):
        self.tree = tree
        self.pool = pool
        self.parent_hash = parent_hash
        self.attrs = attrs
        self.lock = lock
        self.deadline = time.monotonic() + deadline
        self.interval = interval
        self.extra_data = extra_data
        self.gas_ceiling = gas_ceiling
        self.producer = producer
        self.best: Block | None = None
        self.best_fees: int = -1
        self.rebuilds = 0
        self._resolved = threading.Event()
        with self.lock:
            try:
                self.best, self.best_fees = self._build_once()
            except Exception:  # noqa: BLE001 — fall back to an empty payload
                self.best, self.best_fees = build_payload(
                    tree, None, parent_hash, attrs,
                    extra_data=extra_data, gas_ceiling=gas_ceiling,
                )
        self._thread = threading.Thread(target=self._improve_loop, daemon=True)
        self._thread.start()

    def _build_once(self):
        """One full build: seal the continuous producer's hot candidate
        when one is attached (incremental refresh, no re-execution on a
        hot hit), else the one-shot serial/parallel builder."""
        if self.producer is not None:
            try:
                return self.producer.take(
                    self.parent_hash, self.attrs, extra_data=self.extra_data,
                    gas_ceiling=self.gas_ceiling)
            except Exception:  # noqa: BLE001 — the one-shot builder is
                pass           # always the fallback
        return build_payload(self.tree, self.pool, self.parent_hash,
                             self.attrs, extra_data=self.extra_data,
                             gas_ceiling=self.gas_ceiling)

    def rebuild(self) -> bool:
        """One re-build; swaps only a strictly better payload. Returns
        whether the swap happened."""
        with self.lock:
            if self._resolved.is_set():
                return False
            try:
                block, fees = self._build_once()
            except Exception:  # noqa: BLE001 — keep the current best
                return False
            self.rebuilds += 1
            if fees > self.best_fees:
                self.best, self.best_fees = block, fees
                return True
            return False

    def _improve_loop(self) -> None:
        while not self._resolved.is_set() and time.monotonic() < self.deadline:
            if self._resolved.wait(self.interval):
                return
            self.rebuild()

    def resolve(self) -> Block | None:
        self._resolved.set()
        return self.best


class PayloadBuilderService:
    """payload_id → deadline-driven job (reference PayloadBuilderService).

    Bounded: only the newest ``MAX_JOBS`` jobs are retained (reference
    jobs resolve/expire; a CL issues one per slot)."""

    MAX_JOBS = 16

    def __init__(self, tree: EngineTree, pool, lock=None,
                 deadline: float = 2.0, interval: float = 0.25,
                 producer=None):
        self.tree = tree
        self.pool = pool
        self.lock = lock or threading.RLock()
        self.deadline = deadline
        self.interval = interval
        self.producer = producer
        # miner_ knobs (rpc/miner.py): stamped into every subsequent job
        self.extra_data: bytes = b""
        self.gas_ceiling: int | None = None
        self.jobs: dict[bytes, PayloadJob] = {}

    def new_payload_job(self, parent_hash: bytes, attrs: PayloadAttributes) -> bytes:
        payload_id = os.urandom(8)
        self.jobs[payload_id] = PayloadJob(
            self.tree, self.pool, parent_hash, attrs, self.lock,
            self.deadline, self.interval,
            extra_data=self.extra_data, gas_ceiling=self.gas_ceiling,
            producer=self.producer,
        )
        while len(self.jobs) > self.MAX_JOBS:
            self.jobs.pop(next(iter(self.jobs))).resolve()
        return payload_id

    def get_payload(self, payload_id: bytes) -> Block | None:
        block, _fees = self.get_payload_with_fees(payload_id)
        return block

    def get_payload_with_fees(self, payload_id: bytes) -> tuple[Block | None, int]:
        """Resolve the job: (best block, its total priority fees) — the
        fees become the engine response's blockValue."""
        job = self.jobs.get(payload_id)
        if job is None:
            return None, 0
        return job.resolve(), max(job.best_fees, 0)
