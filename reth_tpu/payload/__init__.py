"""Payload building: assemble blocks from the pool for the Engine API.

Reference analogue: crates/payload — `PayloadBuilderService`/`PayloadJob`
(builder/src/service.rs), `BasicPayloadJobGenerator`
(basic/src/lib.rs:57), `EthereumPayloadBuilder` (crates/ethereum/payload).
"""

from .builder import PayloadAttributes, PayloadBuilderService, build_payload
from .producer import BlockProducer

__all__ = ["BlockProducer", "PayloadAttributes", "PayloadBuilderService",
           "build_payload"]
