"""secp256k1 ECDSA: sign + public-key recovery (sender recovery).

Reference analogue: the C secp256k1 library (reference Cargo.toml:592), used
for `SenderRecoveryStage` and ECIES. This is a portable pure-Python
implementation (Jacobian point arithmetic, RFC-6979 deterministic nonces);
the batched/NATIVE fast path belongs to the C++ runtime layer in a later
milestone — interfaces here are the stable seam.
"""

from __future__ import annotations

import hashlib
import hmac

from .keccak import keccak256

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

_JPoint = tuple[int, int, int]  # Jacobian (X, Y, Z); Z=0 → infinity
_INF: _JPoint = (1, 1, 0)


def _jdouble(p: _JPoint) -> _JPoint:
    x, y, z = p
    if z == 0 or y == 0:
        return _INF
    s = (4 * x * y * y) % P
    m = (3 * x * x) % P  # a = 0 for secp256k1
    x3 = (m * m - 2 * s) % P
    y3 = (m * (s - x3) - 8 * y * y * y * y) % P
    z3 = (2 * y * z) % P
    return (x3, y3, z3)


def _jadd(p: _JPoint, q: _JPoint) -> _JPoint:
    if p[2] == 0:
        return q
    if q[2] == 0:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return _INF
        return _jdouble(p)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    h2 = h * h % P
    h3 = h2 * h % P
    x3 = (r * r - h3 - 2 * u1 * h2) % P
    y3 = (r * (u1 * h2 - x3) - s1 * h3) % P
    z3 = h * z1 * z2 % P
    return (x3, y3, z3)


def _jmul(p: _JPoint, k: int) -> _JPoint:
    k %= N
    result = _INF
    addend = p
    while k:
        if k & 1:
            result = _jadd(result, addend)
        addend = _jdouble(addend)
        k >>= 1
    return result


def _to_affine(p: _JPoint) -> tuple[int, int]:
    x, y, z = p
    if z == 0:
        raise ValueError("point at infinity")
    zinv = pow(z, P - 2, P)
    zinv2 = zinv * zinv % P
    return (x * zinv2 % P, y * zinv2 * zinv % P)


_G: _JPoint = (GX, GY, 1)


def random_priv() -> int:
    """Uniform nonzero scalar (rejection sampling, no mod bias)."""
    import os

    while True:
        k = int.from_bytes(os.urandom(32), "big")
        if 1 <= k < N:
            return k


def pubkey_from_priv(priv: int) -> tuple[int, int]:
    return _to_affine(_jmul(_G, priv))


def address_from_pubkey(pub: tuple[int, int]) -> bytes:
    raw = pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
    return keccak256(raw)[12:]


def address_from_priv(priv: int) -> bytes:
    return address_from_pubkey(pubkey_from_priv(priv))


def pubkey_to_bytes(pub: tuple[int, int]) -> bytes:
    """Uncompressed 64-byte X||Y (devp2p node-id / ECIES encoding)."""
    return pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")


def pubkey_from_bytes(raw: bytes) -> tuple[int, int]:
    """64-byte X||Y -> validated curve point."""
    if len(raw) != 64:
        raise ValueError("public key must be 64 bytes")
    x = int.from_bytes(raw[:32], "big")
    y = int.from_bytes(raw[32:], "big")
    if not (0 < x < P and 0 < y < P) or (y * y - (x * x * x + 7)) % P != 0:
        raise ValueError("point not on secp256k1")
    return (x, y)


def compress_pubkey(pub: tuple[int, int]) -> bytes:
    """SEC1 compressed form: 02/03 parity prefix + 32-byte X (the ENR
    "secp256k1" value and discv5 ephemeral-key encoding)."""
    return bytes([2 + (pub[1] & 1)]) + pub[0].to_bytes(32, "big")


def decompress_pubkey(raw: bytes) -> tuple[int, int]:
    """SEC1 compressed (33 B) or uncompressed 04-prefixed (65 B) -> point."""
    if len(raw) == 65 and raw[0] == 4:
        return pubkey_from_bytes(raw[1:])
    if len(raw) != 33 or raw[0] not in (2, 3):
        raise ValueError("bad compressed public key")
    x = int.from_bytes(raw[1:], "big")
    if not 0 < x < P:
        raise ValueError("x out of range")
    y = pow((x * x * x + 7) % P, (P + 1) // 4, P)
    if (y * y) % P != (x * x * x + 7) % P:
        raise ValueError("point not on secp256k1")
    if (y & 1) != (raw[0] & 1):
        y = P - y
    return (x, y)


def ecdh_x(priv: int, pub: tuple[int, int]) -> bytes:
    """ECDH shared secret: x-coordinate of priv * pub (32 bytes big-endian).

    The devp2p/ECIES convention (reference crates/net/ecies): only the x
    coordinate feeds the KDF."""
    x, _y = _to_affine(_jmul((pub[0], pub[1], 1), priv))
    return x.to_bytes(32, "big")


def _rfc6979_k(msg_hash: bytes, priv: int):
    """Deterministic nonce candidates per RFC 6979 with HMAC-SHA256.

    Yields successive candidates (sec 3.2 step h retry) so callers can pull
    another nonce if r or s comes out zero, without touching the message.
    """
    x = priv.to_bytes(32, "big")
    h1 = msg_hash
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            yield cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(msg_hash: bytes, priv: int) -> tuple[int, int, int]:
    """ECDSA sign → (y_parity, r, s) with low-s normalisation (EIP-2)."""
    z = int.from_bytes(msg_hash, "big")
    for k in _rfc6979_k(msg_hash, priv):
        rx, ry = _to_affine(_jmul(_G, k))
        r = rx % N
        if r == 0:
            continue  # next RFC-6979 candidate
        s = pow(k, N - 2, N) * (z + r * priv) % N
        if s == 0:
            continue
        parity = ry & 1
        if s > N // 2:
            s = N - s
            parity ^= 1
        return (parity, r, s)
    raise AssertionError("unreachable: RFC-6979 generator is infinite")


_NATIVE = None
_NATIVE_TRIED = False


def _native_lib():
    """ctypes handle to native/secp256k1.cpp (None when unavailable)."""
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    try:
        import ctypes
        import os
        import subprocess
        import threading
        from pathlib import Path

        src = Path(__file__).resolve().parent.parent.parent / "native" / "secp256k1.cpp"
        so = src.parent / "build" / "libsecp.so"
        stale = src.exists() and (
            not so.exists() or so.stat().st_mtime < src.stat().st_mtime
        )
        if stale:
            so.parent.mkdir(parents=True, exist_ok=True)
            # build atomically: concurrent processes must never interleave
            # writes into the final path (a corrupt .so would silently pin
            # the slow fallback forever)
            tmp = so.with_suffix(f".tmp{os.getpid()}")
            proc = subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", str(src),
                 "-o", str(tmp)],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                return None
            os.replace(tmp, so)
        if not so.exists():
            return None
        lib = ctypes.CDLL(str(so))
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rtsecp_recover_batch.argtypes = [
            u8p, u8p, u8p, u8p, ctypes.c_uint64, u8p, u8p, ctypes.c_int,
        ]
        _NATIVE = lib
    except Exception:  # noqa: BLE001 — native is an accelerator, never a dep
        _NATIVE = None
    return _NATIVE


def ecrecover_batch(items, allow_high_s: bool = False) -> list[bytes | None]:
    """Batch address recovery: ``items`` = (msg_hash, y_parity, r, s) tuples;
    returns one 20-byte address (or None for invalid signatures) per item.

    The hot path is the native threaded C++ engine (native/secp256k1.cpp,
    the reference's C-secp256k1 + rayon analogue); scalar validation and
    u1/u2 = (-z, s) * r^-1 mod n stay in Python big ints. Falls back to
    the pure-Python point math when the native build is unavailable."""
    lib = _native_lib()
    if lib is None:
        out = []
        for h, y, r, s in items:
            try:
                out.append(ecrecover(h, y, r, s, allow_high_s=allow_high_s))
            except ValueError:
                out.append(None)
        return out
    import ctypes

    n = len(items)
    r_buf = bytearray(32 * n)
    parity = bytearray(n)
    u1_buf = bytearray(32 * n)
    u2_buf = bytearray(32 * n)
    valid = bytearray(n)  # python-side validation verdict
    for i, (h, y, r, s) in enumerate(items):
        if not (1 <= r < N and 1 <= s < N) or y not in (0, 1):
            continue
        if s > N // 2 and not allow_high_s:
            continue
        z = int.from_bytes(h, "big")
        r_inv = pow(r, -1, N)
        u1 = (-z) * r_inv % N
        u2 = s * r_inv % N
        r_buf[32 * i : 32 * i + 32] = r.to_bytes(32, "big")
        parity[i] = y
        u1_buf[32 * i : 32 * i + 32] = u1.to_bytes(32, "big")
        u2_buf[32 * i : 32 * i + 32] = u2.to_bytes(32, "big")
        valid[i] = 1
    out_buf = bytearray(64 * n)
    status = bytearray(n)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    as_p = lambda b: (ctypes.c_uint8 * len(b)).from_buffer(b)  # noqa: E731
    lib.rtsecp_recover_batch(
        ctypes.cast(as_p(r_buf), u8p), ctypes.cast(as_p(parity), u8p),
        ctypes.cast(as_p(u1_buf), u8p), ctypes.cast(as_p(u2_buf), u8p),
        n, ctypes.cast(as_p(out_buf), u8p), ctypes.cast(as_p(status), u8p), 0,
    )
    out: list[bytes | None] = []
    for i in range(n):
        if not valid[i] or status[i] != 0:
            out.append(None)
            continue
        out.append(keccak256(bytes(out_buf[64 * i : 64 * i + 64]))[12:])
    return out


def ecrecover(msg_hash: bytes, y_parity: int, r: int, s: int,
              allow_high_s: bool = False, return_pubkey: bool = False) -> bytes:
    """Recover the signer's address (or 64-byte pubkey) from a signature.

    Raises ValueError on invalid signatures (reference rejects these during
    sender recovery and tx validation). ``allow_high_s`` relaxes the EIP-2
    low-s rule for the ecrecover PRECOMPILE, which accepts any s in range.
    ``return_pubkey`` yields X||Y instead of the address (the RLPx
    handshake recovers the peer's EPHEMERAL public key this way).
    """
    if y_parity not in (0, 1):
        raise ValueError("invalid recovery id")
    if not (1 <= r < N and 1 <= s < N):
        raise ValueError("signature out of range")
    # EIP-2 (homestead): high-s signatures are invalid for tx senders.
    if s > N // 2 and not allow_high_s:
        raise ValueError("high-s signature")
    x = r
    y_sq = (pow(x, 3, P) + 7) % P
    y = pow(y_sq, (P + 1) // 4, P)
    if y * y % P != y_sq:
        raise ValueError("invalid r: not on curve")
    if y & 1 != y_parity:
        y = P - y
    z = int.from_bytes(msg_hash, "big")
    r_inv = pow(r, N - 2, N)
    # Q = r^-1 (s*R - z*G)
    point = _jadd(_jmul((x, y, 1), s), _jmul(_G, (-z) % N))
    q = _to_affine(_jmul(point, r_inv))
    if return_pubkey:
        return q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")
    return address_from_pubkey(q)
