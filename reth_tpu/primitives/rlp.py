"""RLP (recursive length prefix) encode/decode.

Reference analogue: the external alloy-rlp crate (reference Cargo.toml:336).
Items are ``bytes`` or (possibly nested) lists of items. Integers are
encoded via ``encode_int`` — big-endian minimal, 0 ↦ empty string — matching
Ethereum consensus encoding.
"""

from __future__ import annotations

Item = bytes | list  # recursive: list[Item]


def encode_int(v: int) -> bytes:
    """Minimal big-endian integer payload (0 encodes as empty string)."""
    if v == 0:
        return b""
    return v.to_bytes((v.bit_length() + 7) // 8, "big")


def decode_int(b: bytes) -> int:
    if b and b[0] == 0:
        raise ValueError("leading zero in RLP integer")
    return int.from_bytes(b, "big")


def _encode_length(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    lb = encode_int(length)
    return bytes([offset + 55 + len(lb)]) + lb


def rlp_encode(item: Item) -> bytes:
    if isinstance(item, (bytes, bytearray, memoryview)):
        b = bytes(item)
        if len(b) == 1 and b[0] < 0x80:
            return b
        return _encode_length(len(b), 0x80) + b
    if isinstance(item, (list, tuple)):
        payload = b"".join(rlp_encode(x) for x in item)
        return _encode_length(len(payload), 0xC0) + payload
    if isinstance(item, int):
        return rlp_encode(encode_int(item))
    raise TypeError(f"cannot RLP-encode {type(item)}")


def rlp_encode_list(items: list[Item]) -> bytes:
    return rlp_encode(list(items))


def _decode_at(data: bytes, pos: int) -> tuple[Item, int]:
    if pos >= len(data):
        raise ValueError("RLP: out of bounds")
    b0 = data[pos]
    if b0 < 0x80:
        return bytes([b0]), pos + 1
    if b0 < 0xB8:  # short string
        ln = b0 - 0x80
        end = pos + 1 + ln
        s = data[pos + 1 : end]
        if len(s) != ln:
            raise ValueError("RLP: truncated string")
        if ln == 1 and s[0] < 0x80:
            raise ValueError("RLP: non-canonical single byte")
        return s, end
    if b0 < 0xC0:  # long string
        lln = b0 - 0xB7
        ln = decode_int(data[pos + 1 : pos + 1 + lln])
        if ln < 56:
            raise ValueError("RLP: non-canonical long string")
        start = pos + 1 + lln
        end = start + ln
        if end > len(data):
            raise ValueError("RLP: truncated string")
        return data[start:end], end
    if b0 < 0xF8:  # short list
        ln = b0 - 0xC0
        end = pos + 1 + ln
        if end > len(data):
            raise ValueError("RLP: truncated list")
        return _decode_list_payload(data, pos + 1, end), end
    # long list
    lln = b0 - 0xF7
    ln = decode_int(data[pos + 1 : pos + 1 + lln])
    if ln < 56:
        raise ValueError("RLP: non-canonical long list")
    start = pos + 1 + lln
    end = start + ln
    if end > len(data):
        raise ValueError("RLP: truncated list")
    return _decode_list_payload(data, start, end), end


def _decode_list_payload(data: bytes, start: int, end: int) -> list:
    out = []
    pos = start
    while pos < end:
        item, pos = _decode_at(data, pos)
        out.append(item)
    if pos != end:
        raise ValueError("RLP: list payload overrun")
    return out


def rlp_decode_prefix(data: bytes) -> tuple[Item, int]:
    """Decode the FIRST RLP item, tolerating trailing bytes; returns
    (item, consumed). EIP-8 handshake payloads carry random padding after
    the RLP body, which strict decoding rejects."""
    item, end = _decode_at(bytes(data), 0)
    return item, end


def rlp_decode(data: bytes) -> Item:
    item, end = _decode_at(bytes(data), 0)
    if end != len(data):
        raise ValueError("RLP: trailing bytes")
    return item
