"""Minimal BLS12-381 arithmetic for the EIP-2537 precompiles.

G1ADD (0x0b) / G2ADD (0x0d): Fp / Fp2 field ops and affine point addition
on y^2 = x^3 + 4 (G1) and y^2 = x^3 + 4(1+i) (G2). Per EIP-2537, ADD
inputs must be valid field encodings on the curve but do NOT require a
subgroup check; the point at infinity encodes as all zeros.

G1MSM (0x0c) / G2MSM (0x0e): multi-scalar multiplication built from
double-and-add over the SAME affine addition (the chord-tangent formula
handles doubling), so the group law lives in exactly one place. MSM
inputs DO require the subgroup check (EIP-2537: "subgroup check is
required" for MSM but not ADD), enforced by multiplying by the prime
subgroup order ``R`` — slow in python, but these precompiles are rare
enough on mainnet that constant-factor speed is irrelevant, while the
encode/validate rules are consensus-critical.

PAIRING (0x0f): the product-of-pairings check over the repo's own
pairing engine (primitives/pairing.py, reduced Tate pairing with one
final exponentiation for the whole product); every input point is
curve- AND subgroup-checked.

MAP_FP_TO_G1 (0x10) / MAP_FP2_TO_G2 (0x11): the RFC 9380 simplified-SWU
map to the isogenous curve E' followed by the 11-/3-isogeny back to the
BLS curve and effective-cofactor clearing. The isogeny rational maps
are NOT transcribed from the RFC appendix: they were re-derived offline
from first principles (the normalized isogeny satisfies the ODE
``(x^3 + A'x + B') F'^2 = F^3 + B_cod`` — solve it as a power series at
infinity, Padé-reconstruct the degree-11/10 rational map, then solve for
the unique codomain model admitting an exact solution) and the baked
constants are pinned two independent ways: the exact polynomial isogeny
identity (tests/test_precompiles.py) and end-to-end RFC 9380 J.9.1/J.10.1
hash-to-curve vectors, both of which any single-constant typo breaks.
"""

from __future__ import annotations

# the BLS12-381 base field prime
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# the prime order of the G1/G2 subgroups (the scalar field)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

_B1 = 4            # G1 curve constant: y^2 = x^3 + 4
_B2 = (4, 4)       # G2 curve constant: 4 * (1 + i) in Fp2


class BlsError(ValueError):
    """Invalid EIP-2537 input (length, padding, range, or off-curve)."""


# -- Fp -----------------------------------------------------------------------


def _fp_decode(b: bytes) -> int:
    """One 64-byte padded field element: top 16 bytes zero, value < P."""
    if len(b) != 64:
        raise BlsError(f"field element must be 64 bytes, got {len(b)}")
    if b[:16] != b"\x00" * 16:
        raise BlsError("field element padding is not zero")
    v = int.from_bytes(b[16:], "big")
    if v >= P:
        raise BlsError("field element not in canonical range")
    return v


def _fp_encode(v: int) -> bytes:
    return b"\x00" * 16 + v.to_bytes(48, "big")


def _fp_inv(v: int) -> int:
    return pow(v, P - 2, P)


# -- Fp2 (c0 + c1*i with i^2 = -1) -------------------------------------------


def _fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _fp2_mul(a, b):
    return ((a[0] * b[0] - a[1] * b[1]) % P, (a[0] * b[1] + a[1] * b[0]) % P)


def _fp2_inv(a):
    norm = _fp_inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * norm % P, (-a[1]) * norm % P)


# -- affine point addition (shared shape over both fields) -------------------


def _add_affine(p1, p2, *, add, sub, mul, inv, zero):
    """Affine chord-tangent addition; ``None`` is the point at infinity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if add(y1, y2) == zero:  # P + (-P), including doubling a y=0 point
            return None
        # doubling: lambda = 3 x^2 / 2 y
        x_sq = mul(x1, x1)
        lam = mul(add(add(x_sq, x_sq), x_sq), inv(add(y1, y1)))
    else:
        lam = mul(sub(y2, y1), inv(sub(x2, x1)))
    x3 = sub(sub(mul(lam, lam), x1), x2)
    y3 = sub(mul(lam, sub(x1, x3)), y1)
    return (x3, y3)


def _g1_ops():
    return dict(add=lambda a, b: (a + b) % P, sub=lambda a, b: (a - b) % P,
                mul=lambda a, b: (a * b) % P, inv=_fp_inv, zero=0)


def _g2_ops():
    return dict(add=_fp2_add, sub=_fp2_sub, mul=_fp2_mul, inv=_fp2_inv,
                zero=(0, 0))


# -- G1 -----------------------------------------------------------------------


def decode_g1(b: bytes):
    """128-byte G1 point (x||y); all-zero = infinity. On-curve checked
    (EIP-2537 ADD semantics: curve check yes, subgroup check no)."""
    if len(b) != 128:
        raise BlsError(f"G1 point must be 128 bytes, got {len(b)}")
    x = _fp_decode(b[:64])
    y = _fp_decode(b[64:])
    if x == 0 and y == 0:
        return None
    if (y * y - (x * x * x + _B1)) % P != 0:
        raise BlsError("G1 point not on curve")
    return (x, y)


def encode_g1(pt) -> bytes:
    if pt is None:
        return b"\x00" * 128
    return _fp_encode(pt[0]) + _fp_encode(pt[1])


def g1_add(p1, p2):
    return _add_affine(p1, p2, **_g1_ops())


def g1add_precompile(data: bytes) -> bytes:
    """EIP-2537 G1ADD: 256-byte input (two G1 points), 128-byte output."""
    if len(data) != 256:
        raise BlsError(f"G1ADD input must be 256 bytes, got {len(data)}")
    return encode_g1(g1_add(decode_g1(data[:128]), decode_g1(data[128:])))


# -- scalar multiplication / MSM (shared over both groups) --------------------


def _mul_scalar(pt, k: int, add):
    """Double-and-add via the affine group law (``add(p, p)`` doubles)."""
    acc = None
    while k:
        if k & 1:
            acc = add(acc, pt)
        pt = add(pt, pt)
        k >>= 1
    return acc


def _check_subgroup(pt, add, what: str) -> None:
    """EIP-2537 MSM semantics: every input point must lie in the prime
    subgroup (infinity trivially does). Order-R multiplication is the
    definitionally-correct check — no endomorphism shortcuts to get wrong."""
    if pt is not None and _mul_scalar(pt, R, add) is not None:
        raise BlsError(f"{what} point not in the prime subgroup")


def _msm(data: bytes, pair_len: int, point_len: int, decode, encode, add,
         what: str) -> bytes:
    """Shared EIP-2537 MSM body: k (point, 32-byte scalar) pairs, every
    point curve- AND subgroup-checked, scalars unreduced big-endian ints
    (multiplication handles any magnitude). Empty input is invalid."""
    if len(data) == 0 or len(data) % pair_len != 0:
        raise BlsError(
            f"{what} input must be a positive multiple of {pair_len} bytes, "
            f"got {len(data)}")
    acc = None
    for off in range(0, len(data), pair_len):
        pt = decode(data[off:off + point_len])
        _check_subgroup(pt, add, what)
        scalar = int.from_bytes(data[off + point_len:off + pair_len], "big")
        acc = add(acc, _mul_scalar(pt, scalar, add))
    return encode(acc)


def g1_mul(pt, k: int):
    return _mul_scalar(pt, k, g1_add)


def g1msm_precompile(data: bytes) -> bytes:
    """EIP-2537 G1MSM: k*(G1 point ++ 32-byte scalar) -> 128-byte point."""
    return _msm(data, 160, 128, decode_g1, encode_g1, g1_add, "G1MSM")


# -- G2 -----------------------------------------------------------------------


def decode_g2(b: bytes):
    """256-byte G2 point (x_c0||x_c1||y_c0||y_c1); all-zero = infinity."""
    if len(b) != 256:
        raise BlsError(f"G2 point must be 256 bytes, got {len(b)}")
    x = (_fp_decode(b[0:64]), _fp_decode(b[64:128]))
    y = (_fp_decode(b[128:192]), _fp_decode(b[192:256]))
    if x == (0, 0) and y == (0, 0):
        return None
    rhs = _fp2_add(_fp2_mul(_fp2_mul(x, x), x), _B2)
    if _fp2_sub(_fp2_mul(y, y), rhs) != (0, 0):
        raise BlsError("G2 point not on curve")
    return (x, y)


def encode_g2(pt) -> bytes:
    if pt is None:
        return b"\x00" * 256
    (x, y) = pt
    return (_fp_encode(x[0]) + _fp_encode(x[1])
            + _fp_encode(y[0]) + _fp_encode(y[1]))


def g2_add(p1, p2):
    return _add_affine(p1, p2, **_g2_ops())


def g2add_precompile(data: bytes) -> bytes:
    """EIP-2537 G2ADD: 512-byte input (two G2 points), 256-byte output."""
    if len(data) != 512:
        raise BlsError(f"G2ADD input must be 512 bytes, got {len(data)}")
    return encode_g2(g2_add(decode_g2(data[:256]), decode_g2(data[256:])))


def g2_mul(pt, k: int):
    return _mul_scalar(pt, k, g2_add)


def g2msm_precompile(data: bytes) -> bytes:
    """EIP-2537 G2MSM: k*(G2 point ++ 32-byte scalar) -> 256-byte point."""
    return _msm(data, 288, 256, decode_g2, encode_g2, g2_add, "G2MSM")


# EIP-2537 MSM pricing: cost = k * multiplication_cost * discount(k) / 1000
# with the per-k discount table below (index k-1, capped at k=128). The
# table is transcribed from the EIP's final (Pectra) parameter set.
MSM_MULTIPLIER = 1000
G1MSM_BASE_GAS = 12000   # G1 multiplication cost
G2MSM_BASE_GAS = 22500   # G2 multiplication cost

G1_MSM_DISCOUNT = (
    1000, 949, 848, 797, 764, 750, 738, 728, 719, 712, 705, 698, 692, 687,
    682, 677, 673, 669, 665, 661, 658, 654, 651, 648, 645, 642, 640, 637,
    635, 632, 630, 627, 625, 623, 621, 619, 617, 615, 613, 611, 609, 608,
    606, 604, 603, 601, 599, 598, 596, 595, 593, 592, 591, 589, 588, 586,
    585, 584, 582, 581, 580, 579, 577, 576, 575, 574, 573, 572, 570, 569,
    568, 567, 566, 565, 564, 563, 562, 561, 560, 559, 558, 557, 556, 555,
    554, 553, 552, 551, 550, 549, 548, 547, 547, 546, 545, 544, 543, 542,
    541, 540, 540, 539, 538, 537, 536, 536, 535, 534, 533, 532, 532, 531,
    530, 529, 528, 528, 527, 526, 525, 525, 524, 523, 522, 522, 521, 520,
    520, 519,
)
G2_MSM_DISCOUNT = (
    1000, 1000, 923, 884, 855, 832, 812, 796, 782, 770, 759, 749, 740, 732,
    724, 717, 711, 704, 699, 693, 688, 683, 679, 674, 670, 666, 663, 659,
    655, 652, 649, 646, 643, 640, 637, 634, 632, 629, 627, 624, 622, 620,
    618, 615, 613, 611, 609, 607, 606, 604, 602, 600, 598, 597, 595, 593,
    592, 590, 589, 587, 586, 584, 583, 582, 580, 579, 578, 576, 575, 574,
    573, 571, 570, 569, 568, 567, 566, 565, 563, 562, 561, 560, 559, 558,
    557, 556, 555, 554, 553, 552, 552, 551, 550, 549, 548, 547, 546, 545,
    545, 544, 543, 542, 541, 541, 540, 539, 538, 537, 537, 536, 535, 535,
    534, 533, 532, 532, 531, 530, 530, 529, 528, 528, 527, 526, 526, 525,
    524, 524,
)


def msm_gas(k: int, base: int, discounts: tuple[int, ...]) -> int:
    """EIP-2537 MSM gas for k pairs (k >= 1)."""
    if k == 0:
        return 0
    d = discounts[min(k, len(discounts)) - 1]
    return (k * base * d) // MSM_MULTIPLIER


def g1msm_gas(k: int) -> int:
    return msm_gas(k, G1MSM_BASE_GAS, G1_MSM_DISCOUNT)


def g2msm_gas(k: int) -> int:
    return msm_gas(k, G2MSM_BASE_GAS, G2_MSM_DISCOUNT)


# the standard generators (draft-irtf-cfrg-bls-signature / EIP-2537 test
# vectors use them); exported for tests
G1_GENERATOR = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GENERATOR = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


# -- EIP-2537 PAIRING (0x0f) --------------------------------------------------

PAIRING_BASE_GAS = 37700
PAIRING_PAIR_GAS = 32600


def pairing_gas(k: int) -> int:
    return PAIRING_BASE_GAS + k * PAIRING_PAIR_GAS


def pairing_precompile(data: bytes) -> bytes:
    """EIP-2537 PAIRING: k*(G1 point ++ G2 point) -> 32-byte 0/1.

    Every point must be on its curve AND in the prime subgroup (unlike
    ADD, like MSM); the point at infinity is valid and contributes the
    identity. Empty input is invalid per the EIP (unlike EIP-197 bn254).
    The check itself — prod e(Pi, Qi) == 1 — runs on the repo's generic
    pairing engine with ONE final exponentiation for the whole product.
    """
    if len(data) == 0 or len(data) % 384 != 0:
        raise BlsError(
            f"PAIRING input must be a positive multiple of 384 bytes, "
            f"got {len(data)}")
    pairs = []
    for off in range(0, len(data), 384):
        p1 = decode_g1(data[off:off + 128])
        _check_subgroup(p1, g1_add, "PAIRING G1")
        q2 = decode_g2(data[off + 128:off + 384])
        _check_subgroup(q2, g2_add, "PAIRING G2")
        if p1 is not None and q2 is not None:
            pairs.append((p1, q2))
    from .pairing import BLS12_381, pairing_product_is_one

    ok = pairing_product_is_one(pairs, BLS12_381)
    return (1 if ok else 0).to_bytes(32, "big")


# -- EIP-2537 MAP_FP_TO_G1 / MAP_FP2_TO_G2 (0x10 / 0x11) ----------------------
#
# RFC 9380 simplified SWU onto the isogenous curve E', the 11-/3-isogeny
# back onto the BLS curve, then effective-cofactor clearing. See the
# module docstring for how the isogeny constants below were derived and
# how they are pinned.

MAP_FP_TO_G1_GAS = 5500
MAP_FP2_TO_G2_GAS = 23800

# G1 SSWU target curve E1': y^2 = x^3 + ISO1_A x + ISO1_B, Z = 11
ISO1_A = 0x144698A3B8E9433D693A02C96D4982B0EA985383EE66A8D8E8981AEFD881AC98936F8DA0E0F97F5CF428082D584C1D
ISO1_B = 0x12E2908D11688030018B12E8753EEE3B2016C1F0F24F4070A0B9C14FCEF35EF55A23215A316CEAA5D1CC48E98E172BE0
ISO1_Z = 11
# normalized 11-isogeny E1' -> y^2 = x^3 + ISO1_BCOD: x |-> N(x)/D(x)
# (monic-leading N over monic D), y |-> y * (N'D - ND')/D^2; the model is
# rescaled onto y^2 = x^3 + 4 by x *= ISO1_C (= s^2), y *= ISO1_S3 (= s^3)
ISO1_BCOD = 0x6C20A4
ISO1_C = 0x6E08C248E260E70BD1E962381EDEE3D31D79D7E22C837BC23C0BF1BC24C6B68C24B1B80B64D391FA9C8BA2E8BA2D229
ISO1_S3 = 0x15E6BE4E990F03CE4EA50B3B42DF2EB5CB181D8F84965A3957ADD4FA95AF01B2B665027EFEC01C7704B456BE69C8B604
# x-map numerator, index = degree (degree 11, monic)
ISO1_N = (
    0x753E5B010B5C2AED6CE5BA4AA4CF117B975DFEF6FF2C0A82E8D47835D0591EDAD4178B01E37966FBA894887C542CB9,
    0x1413C543388686BC391125039A3D376FA96FC987A0B99952DBC05E4A373FF99C5106B174C8985431036FF03DFB54EDEA,
    0x71D592BC054E3B8BFFC75B81AEFAFA0A97F03B9114CD1363513AECFEB7610341A16B39EC1F2DA1DF687186972AF9C6,
    0x5B098E05C2AABF1E6143C24142C25324C6DCC53AD565D704DE934AA345920B145B4FE75D201AEF640487751FE98AB0A,
    0x183F63E4654B1979AD4A84532F7E099D6D92B7C6EFC1D8B2FAA622E45E37EC2BFB991CE5556A9BDCA5545A728CA528D0,
    0x69E074638EEAB73A3B7B2E2FA9FC54B33B081FDBD70EF8B8D6758948AC6D2D388A13B2B8E7FE14E18BD96CAA6F2F41E,
    0xD20F79145EE9F35035EB4485A8940705E481DE8641F0C42165FDAD250DF0A5D84105C94491B1DF3CF4F73C93475EDFA,
    0x990B39B1545D7F3990CA675E6C070C715AF1AC4F6F9AAB95CD52B05E28FA1B119F5FE26C973A01F3089B1C3BCF375A4,
    0xC1A3784B0B69F918C6576E46B265C603ADC96424813AE770555D3D09DEC9EDB34FCDFD99B8024AAD8D60A58ABD6AB28,
    0x4E191198FB0B670F56E5BB36434C322563036138E4314008ACE68587DDB0A83824A49AF4209A889CE74C108E919F68B,
    0x95FC13AB9E92AD4476D6E3EB3A56680F682B4EE96F7D03776DF533978F31C1593174E4B4B7865002D6384D168ECDD0A,
    1,
)
# x-map denominator, index = degree (degree 10, monic)
ISO1_D = (
    0x8CA8D548CFF19AE18B2E62F4BD3FA6F01D5EF4BA35B48BA9C9588617FC8AC62B558D681BE343DF8993CF9FA40D21B1C,
    0x12561A5DEB559C4348B4711298E536367041E8CA0CF0800C0126C2588C48BF5713DAA8846CB026E9E5C8276EC82B3BFF,
    0xB2962FE57A3225E8137E629BFF2991F6F89416F5A718CD1FCA64E00B11ACEACD6A3D0967C94FEDCFCC239BA5CB83E19,
    0x3425581A58AE2FEC83AAFEF7C40EB545B08243F16B1655154CCA8ABC28D6FD04976D5243EECF5C4130DE8938DC62CD8,
    0x13A8E162022914A80A6F1D5F43E7A07DFFDFC759A12062BB8D6B44E833B306DA9BD29BA81F35781D539D395B3532A21E,
    0xE7355F8E4E667B955390F7F0506C6E9395735E9CE9CAD4D0A43BCEF24B8982F7400D24BC4228F11C02DF9A29F6304A5,
    0x772CAACF16936190F3E0C63E0596721570F5799AF53A1894E2E073062AEDE9CEA73B3538F0DE06CEC2574496EE84A3A,
    0x14A7AC2A9D64A8B230B3F5B074CF01996E7F63C21BCA68A81996E1CDF9822C580FA5B9489D11E2D311F7D99BBDCC5A5E,
    0xA10ECF6ADA54F825E920B3DAFC7A3CCE07F8D1D7161366B74100DA67F39883503826692ABBA43704776EC3A79A1D641,
    0x95FC13AB9E92AD4476D6E3EB3A56680F682B4EE96F7D03776DF533978F31C1593174E4B4B7865002D6384D168ECDD0A,
    1,
)
# G1 effective cofactor (RFC 9380 8.8.1: h_eff = 1 - x_BLS)
H_EFF_G1 = 0xD201000000010001

# G2 SSWU target curve E2': y^2 = x^3 + ISO2_A x + ISO2_B over Fp2,
# Z = -(2 + i)
ISO2_A = (0, 240)
ISO2_B = (1012, 1012)
ISO2_Z = (P - 2, P - 1)
# normalized 3-isogeny E2' -> y^2 = x^3 + ISO2_BCOD (= 4(1+i) * 3^6),
# rescaled onto y^2 = x^3 + 4(1+i) by ISO2_C / ISO2_S3 (both in Fp)
ISO2_BCOD = (0xB64, 0xB64)
ISO2_C = (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0)
ISO2_S3 = (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0)
ISO2_N = (
    (0x130, 0x130),
    (0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA93),
    (0xC, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),
)
ISO2_D = (
    (0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),
)
# G2 effective cofactor (RFC 9380 8.8.2, Budroni-Pintore)
H_EFF_G2 = int(
    "bc69f08f2ee75b3584c6a0ea91b352888e2a8e9145ad7689986ff031508ffe13"
    "29c2f178731db956d82bf015d1212b02ec0ec69d7477c1ae954cbc06689f6a35"
    "9894c0adebbf6b4e8020005aaa95551", 16)


def _fp_sqrt(a: int) -> int | None:
    """Principal square root in Fp (p = 3 mod 4), or None."""
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a % P else None


def _fp2_sqrt(a) -> tuple | None:
    """Square root in Fp2 (complex method for p = 3 mod 4), or None.
    The final squaring check makes the algorithm self-verifying."""
    if a == (0, 0):
        return (0, 0)
    a1 = _fp2_pow(a, (P - 3) // 4)
    x0 = _fp2_mul(a1, a)
    alpha = _fp2_mul(a1, x0)
    if alpha == ((P - 1) % P, 0):
        x = _fp2_mul((0, 1), x0)
    else:
        b = _fp2_pow(_fp2_add((1, 0), alpha), (P - 1) // 2)
        x = _fp2_mul(b, x0)
    return x if _fp2_mul(x, x) == a else None


def _fp2_pow(a, e: int):
    r = (1, 0)
    while e:
        if e & 1:
            r = _fp2_mul(r, a)
        a = _fp2_mul(a, a)
        e >>= 1
    return r


def _sgn0_fp(x: int) -> int:
    return x % 2


def _sgn0_fp2(x) -> int:
    """RFC 9380 sgn0 for m=2: sign of x0, falling back to x1 when x0=0."""
    return x[0] % 2 if x[0] != 0 else x[1] % 2


def _sswu(u, A, B, Z, *, add, sub, mul, inv, sqrt, sgn0, neg, zero, one):
    """RFC 9380 6.6.2 simplified SWU: field element -> point on the
    isogenous curve y^2 = x^3 + Ax + B (never infinity)."""
    uu = mul(u, u)
    tv1 = add(mul(mul(mul(Z, Z), uu), uu), mul(Z, uu))
    if tv1 == zero:
        x = mul(B, inv(mul(Z, A)))
    else:
        x = mul(mul(neg(B), inv(A)), add(one, inv(tv1)))
    gx = add(add(mul(mul(x, x), x), mul(A, x)), B)
    y = sqrt(gx)
    if y is None:
        x = mul(mul(Z, uu), x)
        gx = add(add(mul(mul(x, x), x), mul(A, x)), B)
        y = sqrt(gx)
        # gx1 * gx2 = Z^3 u^6 gx1^2: with Z a non-square exactly one of
        # the two candidates is square, so this sqrt cannot fail
    if sgn0(u) != sgn0(y):
        y = neg(y)
    return x, y


def _poly_eval(coeffs, x, *, add, mul, zero):
    r = zero
    for c in reversed(coeffs):
        r = add(mul(r, x), c)
    return r


def _iso_map(pt, N, D, c, s3, *, add, sub, mul, inv, zero, int_):
    """Apply the normalized isogeny x -> N(x)/D(x), y -> y (N'D - ND')/D^2
    then rescale onto the BLS curve model (x *= c, y *= s3). A zero
    denominator means the input sits over the isogeny kernel -> infinity."""
    x, y = pt
    dv = _poly_eval(D, x, add=add, mul=mul, zero=zero)
    if dv == zero:
        return None
    nv = _poly_eval(N, x, add=add, mul=mul, zero=zero)
    ndiff = [mul(int_(i), co) for i, co in enumerate(N)][1:]
    ddiff = [mul(int_(i), co) for i, co in enumerate(D)][1:]
    w = sub(mul(_poly_eval(ndiff, x, add=add, mul=mul, zero=zero), dv),
            mul(nv, _poly_eval(ddiff, x, add=add, mul=mul, zero=zero)))
    xe = mul(mul(c, nv), inv(dv))
    ye = mul(mul(mul(y, s3), w), inv(mul(dv, dv)))
    return xe, ye


def _g1_map_ops():
    return dict(add=lambda a, b: (a + b) % P, sub=lambda a, b: (a - b) % P,
                mul=lambda a, b: (a * b) % P, inv=_fp_inv, sqrt=_fp_sqrt,
                sgn0=_sgn0_fp, neg=lambda a: (-a) % P, zero=0, one=1)


def _g2_map_ops():
    return dict(add=_fp2_add, sub=_fp2_sub, mul=_fp2_mul, inv=_fp2_inv,
                sqrt=_fp2_sqrt, sgn0=_sgn0_fp2,
                neg=lambda a: ((-a[0]) % P, (-a[1]) % P),
                zero=(0, 0), one=(1, 0))


def map_fp_to_g1(u: int):
    """RFC 9380 map_to_curve + clear_cofactor for G1: Fp element ->
    point in the G1 subgroup (affine, None = infinity)."""
    ops = _g1_map_ops()
    pt = _sswu(u, ISO1_A, ISO1_B, ISO1_Z, **ops)
    pt = _iso_map(pt, ISO1_N, ISO1_D, ISO1_C, ISO1_S3,
                  add=ops["add"], sub=ops["sub"], mul=ops["mul"],
                  inv=ops["inv"], zero=0, int_=lambda k: k % P)
    return _mul_scalar(pt, H_EFF_G1, g1_add)


def map_fp2_to_g2(u):
    """RFC 9380 map_to_curve + clear_cofactor for G2: Fp2 element ->
    point in the G2 subgroup (affine, None = infinity)."""
    ops = _g2_map_ops()
    pt = _sswu(u, ISO2_A, ISO2_B, ISO2_Z, **ops)
    pt = _iso_map(pt, ISO2_N, ISO2_D, ISO2_C, ISO2_S3,
                  add=ops["add"], sub=ops["sub"], mul=ops["mul"],
                  inv=ops["inv"], zero=(0, 0), int_=lambda k: (k % P, 0))
    return _mul_scalar(pt, H_EFF_G2, g2_add)


def map_fp_to_g1_precompile(data: bytes) -> bytes:
    """EIP-2537 MAP_FP_TO_G1: one 64-byte padded Fp element -> G1 point."""
    if len(data) != 64:
        raise BlsError(
            f"MAP_FP_TO_G1 input must be 64 bytes, got {len(data)}")
    return encode_g1(map_fp_to_g1(_fp_decode(data)))


def map_fp2_to_g2_precompile(data: bytes) -> bytes:
    """EIP-2537 MAP_FP2_TO_G2: one 128-byte Fp2 element (c0 || c1) ->
    G2 point."""
    if len(data) != 128:
        raise BlsError(
            f"MAP_FP2_TO_G2 input must be 128 bytes, got {len(data)}")
    u = (_fp_decode(data[:64]), _fp_decode(data[64:]))
    return encode_g2(map_fp2_to_g2(u))
