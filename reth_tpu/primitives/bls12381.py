"""Minimal BLS12-381 arithmetic for the EIP-2537 precompiles.

G1ADD (0x0b) / G2ADD (0x0d): Fp / Fp2 field ops and affine point addition
on y^2 = x^3 + 4 (G1) and y^2 = x^3 + 4(1+i) (G2). Per EIP-2537, ADD
inputs must be valid field encodings on the curve but do NOT require a
subgroup check; the point at infinity encodes as all zeros.

G1MSM (0x0c) / G2MSM (0x0e): multi-scalar multiplication built from
double-and-add over the SAME affine addition (the chord-tangent formula
handles doubling), so the group law lives in exactly one place. MSM
inputs DO require the subgroup check (EIP-2537: "subgroup check is
required" for MSM but not ADD), enforced by multiplying by the prime
subgroup order ``R`` — slow in python, but these precompiles are rare
enough on mainnet that constant-factor speed is irrelevant, while the
encode/validate rules are consensus-critical.

The remaining EIP-2537 operations (pairing check, map-to-curve) need
the Fp12 tower / SWU isogeny constants, which this repo cannot verify
offline — their precompiles raise loudly instead of silently
misbehaving (see evm/interpreter.py PrecompileNotImplemented).
"""

from __future__ import annotations

# the BLS12-381 base field prime
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# the prime order of the G1/G2 subgroups (the scalar field)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

_B1 = 4            # G1 curve constant: y^2 = x^3 + 4
_B2 = (4, 4)       # G2 curve constant: 4 * (1 + i) in Fp2


class BlsError(ValueError):
    """Invalid EIP-2537 input (length, padding, range, or off-curve)."""


# -- Fp -----------------------------------------------------------------------


def _fp_decode(b: bytes) -> int:
    """One 64-byte padded field element: top 16 bytes zero, value < P."""
    if len(b) != 64:
        raise BlsError(f"field element must be 64 bytes, got {len(b)}")
    if b[:16] != b"\x00" * 16:
        raise BlsError("field element padding is not zero")
    v = int.from_bytes(b[16:], "big")
    if v >= P:
        raise BlsError("field element not in canonical range")
    return v


def _fp_encode(v: int) -> bytes:
    return b"\x00" * 16 + v.to_bytes(48, "big")


def _fp_inv(v: int) -> int:
    return pow(v, P - 2, P)


# -- Fp2 (c0 + c1*i with i^2 = -1) -------------------------------------------


def _fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _fp2_mul(a, b):
    return ((a[0] * b[0] - a[1] * b[1]) % P, (a[0] * b[1] + a[1] * b[0]) % P)


def _fp2_inv(a):
    norm = _fp_inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * norm % P, (-a[1]) * norm % P)


# -- affine point addition (shared shape over both fields) -------------------


def _add_affine(p1, p2, *, add, sub, mul, inv, zero):
    """Affine chord-tangent addition; ``None`` is the point at infinity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if add(y1, y2) == zero:  # P + (-P), including doubling a y=0 point
            return None
        # doubling: lambda = 3 x^2 / 2 y
        x_sq = mul(x1, x1)
        lam = mul(add(add(x_sq, x_sq), x_sq), inv(add(y1, y1)))
    else:
        lam = mul(sub(y2, y1), inv(sub(x2, x1)))
    x3 = sub(sub(mul(lam, lam), x1), x2)
    y3 = sub(mul(lam, sub(x1, x3)), y1)
    return (x3, y3)


def _g1_ops():
    return dict(add=lambda a, b: (a + b) % P, sub=lambda a, b: (a - b) % P,
                mul=lambda a, b: (a * b) % P, inv=_fp_inv, zero=0)


def _g2_ops():
    return dict(add=_fp2_add, sub=_fp2_sub, mul=_fp2_mul, inv=_fp2_inv,
                zero=(0, 0))


# -- G1 -----------------------------------------------------------------------


def decode_g1(b: bytes):
    """128-byte G1 point (x||y); all-zero = infinity. On-curve checked
    (EIP-2537 ADD semantics: curve check yes, subgroup check no)."""
    if len(b) != 128:
        raise BlsError(f"G1 point must be 128 bytes, got {len(b)}")
    x = _fp_decode(b[:64])
    y = _fp_decode(b[64:])
    if x == 0 and y == 0:
        return None
    if (y * y - (x * x * x + _B1)) % P != 0:
        raise BlsError("G1 point not on curve")
    return (x, y)


def encode_g1(pt) -> bytes:
    if pt is None:
        return b"\x00" * 128
    return _fp_encode(pt[0]) + _fp_encode(pt[1])


def g1_add(p1, p2):
    return _add_affine(p1, p2, **_g1_ops())


def g1add_precompile(data: bytes) -> bytes:
    """EIP-2537 G1ADD: 256-byte input (two G1 points), 128-byte output."""
    if len(data) != 256:
        raise BlsError(f"G1ADD input must be 256 bytes, got {len(data)}")
    return encode_g1(g1_add(decode_g1(data[:128]), decode_g1(data[128:])))


# -- scalar multiplication / MSM (shared over both groups) --------------------


def _mul_scalar(pt, k: int, add):
    """Double-and-add via the affine group law (``add(p, p)`` doubles)."""
    acc = None
    while k:
        if k & 1:
            acc = add(acc, pt)
        pt = add(pt, pt)
        k >>= 1
    return acc


def _check_subgroup(pt, add, what: str) -> None:
    """EIP-2537 MSM semantics: every input point must lie in the prime
    subgroup (infinity trivially does). Order-R multiplication is the
    definitionally-correct check — no endomorphism shortcuts to get wrong."""
    if pt is not None and _mul_scalar(pt, R, add) is not None:
        raise BlsError(f"{what} point not in the prime subgroup")


def _msm(data: bytes, pair_len: int, point_len: int, decode, encode, add,
         what: str) -> bytes:
    """Shared EIP-2537 MSM body: k (point, 32-byte scalar) pairs, every
    point curve- AND subgroup-checked, scalars unreduced big-endian ints
    (multiplication handles any magnitude). Empty input is invalid."""
    if len(data) == 0 or len(data) % pair_len != 0:
        raise BlsError(
            f"{what} input must be a positive multiple of {pair_len} bytes, "
            f"got {len(data)}")
    acc = None
    for off in range(0, len(data), pair_len):
        pt = decode(data[off:off + point_len])
        _check_subgroup(pt, add, what)
        scalar = int.from_bytes(data[off + point_len:off + pair_len], "big")
        acc = add(acc, _mul_scalar(pt, scalar, add))
    return encode(acc)


def g1_mul(pt, k: int):
    return _mul_scalar(pt, k, g1_add)


def g1msm_precompile(data: bytes) -> bytes:
    """EIP-2537 G1MSM: k*(G1 point ++ 32-byte scalar) -> 128-byte point."""
    return _msm(data, 160, 128, decode_g1, encode_g1, g1_add, "G1MSM")


# -- G2 -----------------------------------------------------------------------


def decode_g2(b: bytes):
    """256-byte G2 point (x_c0||x_c1||y_c0||y_c1); all-zero = infinity."""
    if len(b) != 256:
        raise BlsError(f"G2 point must be 256 bytes, got {len(b)}")
    x = (_fp_decode(b[0:64]), _fp_decode(b[64:128]))
    y = (_fp_decode(b[128:192]), _fp_decode(b[192:256]))
    if x == (0, 0) and y == (0, 0):
        return None
    rhs = _fp2_add(_fp2_mul(_fp2_mul(x, x), x), _B2)
    if _fp2_sub(_fp2_mul(y, y), rhs) != (0, 0):
        raise BlsError("G2 point not on curve")
    return (x, y)


def encode_g2(pt) -> bytes:
    if pt is None:
        return b"\x00" * 256
    (x, y) = pt
    return (_fp_encode(x[0]) + _fp_encode(x[1])
            + _fp_encode(y[0]) + _fp_encode(y[1]))


def g2_add(p1, p2):
    return _add_affine(p1, p2, **_g2_ops())


def g2add_precompile(data: bytes) -> bytes:
    """EIP-2537 G2ADD: 512-byte input (two G2 points), 256-byte output."""
    if len(data) != 512:
        raise BlsError(f"G2ADD input must be 512 bytes, got {len(data)}")
    return encode_g2(g2_add(decode_g2(data[:256]), decode_g2(data[256:])))


def g2_mul(pt, k: int):
    return _mul_scalar(pt, k, g2_add)


def g2msm_precompile(data: bytes) -> bytes:
    """EIP-2537 G2MSM: k*(G2 point ++ 32-byte scalar) -> 256-byte point."""
    return _msm(data, 288, 256, decode_g2, encode_g2, g2_add, "G2MSM")


# EIP-2537 MSM pricing: cost = k * multiplication_cost * discount(k) / 1000
# with the per-k discount table below (index k-1, capped at k=128). The
# table is transcribed from the EIP's final (Pectra) parameter set.
MSM_MULTIPLIER = 1000
G1MSM_BASE_GAS = 12000   # G1 multiplication cost
G2MSM_BASE_GAS = 22500   # G2 multiplication cost

G1_MSM_DISCOUNT = (
    1000, 949, 848, 797, 764, 750, 738, 728, 719, 712, 705, 698, 692, 687,
    682, 677, 673, 669, 665, 661, 658, 654, 651, 648, 645, 642, 640, 637,
    635, 632, 630, 627, 625, 623, 621, 619, 617, 615, 613, 611, 609, 608,
    606, 604, 603, 601, 599, 598, 596, 595, 593, 592, 591, 589, 588, 586,
    585, 584, 582, 581, 580, 579, 577, 576, 575, 574, 573, 572, 570, 569,
    568, 567, 566, 565, 564, 563, 562, 561, 560, 559, 558, 557, 556, 555,
    554, 553, 552, 551, 550, 549, 548, 547, 547, 546, 545, 544, 543, 542,
    541, 540, 540, 539, 538, 537, 536, 536, 535, 534, 533, 532, 532, 531,
    530, 529, 528, 528, 527, 526, 525, 525, 524, 523, 522, 522, 521, 520,
    520, 519,
)
G2_MSM_DISCOUNT = (
    1000, 1000, 923, 884, 855, 832, 812, 796, 782, 770, 759, 749, 740, 732,
    724, 717, 711, 704, 699, 693, 688, 683, 679, 674, 670, 666, 663, 659,
    655, 652, 649, 646, 643, 640, 637, 634, 632, 629, 627, 624, 622, 620,
    618, 615, 613, 611, 609, 607, 606, 604, 602, 600, 598, 597, 595, 593,
    592, 590, 589, 587, 586, 584, 583, 582, 580, 579, 578, 576, 575, 574,
    573, 571, 570, 569, 568, 567, 566, 565, 563, 562, 561, 560, 559, 558,
    557, 556, 555, 554, 553, 552, 552, 551, 550, 549, 548, 547, 546, 545,
    545, 544, 543, 542, 541, 541, 540, 539, 538, 537, 537, 536, 535, 535,
    534, 533, 532, 532, 531, 530, 530, 529, 528, 528, 527, 526, 526, 525,
    524, 524,
)


def msm_gas(k: int, base: int, discounts: tuple[int, ...]) -> int:
    """EIP-2537 MSM gas for k pairs (k >= 1)."""
    if k == 0:
        return 0
    d = discounts[min(k, len(discounts)) - 1]
    return (k * base * d) // MSM_MULTIPLIER


def g1msm_gas(k: int) -> int:
    return msm_gas(k, G1MSM_BASE_GAS, G1_MSM_DISCOUNT)


def g2msm_gas(k: int) -> int:
    return msm_gas(k, G2MSM_BASE_GAS, G2_MSM_DISCOUNT)


# the standard generators (draft-irtf-cfrg-bls-signature / EIP-2537 test
# vectors use them); exported for tests
G1_GENERATOR = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GENERATOR = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)
