"""Core chain types: Account, Header, Transaction, Receipt, Block.

Reference analogue: alloy-consensus types + `EthPrimitives`
(reference crates/ethereum/primitives, external reth-primitives-traits).
Encodings follow Ethereum consensus RLP, post-merge through Cancun/Prague
(trailing-optional header fields included only when set).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from .keccak import keccak256
from .rlp import rlp_encode, rlp_decode, encode_int, decode_int

# keccak256(rlp(b"")) — root of the empty trie.
EMPTY_ROOT_HASH = keccak256(rlp_encode(b""))
# keccak256(b"") — code hash of an EOA / empty code.
KECCAK_EMPTY = keccak256(b"")
EMPTY_CODE_HASH = KECCAK_EMPTY
# keccak256(rlp([])) — ommers hash of an empty ommer list.
EMPTY_OMMER_ROOT_HASH = keccak256(rlp_encode([]))

B256_ZERO = b"\x00" * 32
ADDRESS_ZERO = b"\x00" * 20


@dataclass(frozen=True)
class Account:
    """An Ethereum account (reference: alloy `TrieAccount` / reth `Account`)."""

    nonce: int = 0
    balance: int = 0
    storage_root: bytes = EMPTY_ROOT_HASH
    code_hash: bytes = KECCAK_EMPTY

    def trie_encode(self) -> bytes:
        """RLP leaf value as stored in the state trie."""
        return rlp_encode([
            encode_int(self.nonce),
            encode_int(self.balance),
            self.storage_root,
            self.code_hash,
        ])

    @classmethod
    def trie_decode(cls, data: bytes) -> "Account":
        nonce, balance, storage_root, code_hash = rlp_decode(data)
        return cls(decode_int(nonce), decode_int(balance), storage_root, code_hash)

    @property
    def is_empty(self) -> bool:
        """EIP-161 emptiness: nonce==0, balance==0, no code."""
        return self.nonce == 0 and self.balance == 0 and self.code_hash == KECCAK_EMPTY

    def with_(self, **kw) -> "Account":
        return replace(self, **kw)


@dataclass(frozen=True)
class Withdrawal:
    index: int
    validator_index: int
    address: bytes
    amount: int  # gwei

    def rlp_fields(self) -> list:
        return [
            encode_int(self.index),
            encode_int(self.validator_index),
            self.address,
            encode_int(self.amount),
        ]


@dataclass(frozen=True)
class Header:
    """Block header (reference: alloy-consensus `Header`)."""

    parent_hash: bytes = B256_ZERO
    ommers_hash: bytes = EMPTY_OMMER_ROOT_HASH
    beneficiary: bytes = ADDRESS_ZERO
    state_root: bytes = EMPTY_ROOT_HASH
    transactions_root: bytes = EMPTY_ROOT_HASH
    receipts_root: bytes = EMPTY_ROOT_HASH
    logs_bloom: bytes = b"\x00" * 256
    difficulty: int = 0
    number: int = 0
    gas_limit: int = 30_000_000
    gas_used: int = 0
    timestamp: int = 0
    extra_data: bytes = b""
    mix_hash: bytes = B256_ZERO
    nonce: bytes = b"\x00" * 8
    base_fee_per_gas: int | None = None
    withdrawals_root: bytes | None = None
    blob_gas_used: int | None = None
    excess_blob_gas: int | None = None
    parent_beacon_block_root: bytes | None = None
    requests_hash: bytes | None = None

    def rlp_fields(self) -> list:
        fields: list = [
            self.parent_hash,
            self.ommers_hash,
            self.beneficiary,
            self.state_root,
            self.transactions_root,
            self.receipts_root,
            self.logs_bloom,
            encode_int(self.difficulty),
            encode_int(self.number),
            encode_int(self.gas_limit),
            encode_int(self.gas_used),
            encode_int(self.timestamp),
            self.extra_data,
            self.mix_hash,
            self.nonce,
        ]
        # Trailing optionals: include a field iff it or any later field is set.
        opts = [
            None if self.base_fee_per_gas is None else encode_int(self.base_fee_per_gas),
            self.withdrawals_root,
            None if self.blob_gas_used is None else encode_int(self.blob_gas_used),
            None if self.excess_blob_gas is None else encode_int(self.excess_blob_gas),
            self.parent_beacon_block_root,
            self.requests_hash,
        ]
        last_set = -1
        for i, v in enumerate(opts):
            if v is not None:
                last_set = i
        for i in range(last_set + 1):
            v = opts[i]
            if v is None:
                raise ValueError("non-contiguous optional header fields")
            fields.append(v)
        return fields

    def encode(self) -> bytes:
        return rlp_encode(self.rlp_fields())

    @classmethod
    def decode_fields(cls, f: list) -> "Header":
        h = cls(
            parent_hash=f[0], ommers_hash=f[1], beneficiary=f[2], state_root=f[3],
            transactions_root=f[4], receipts_root=f[5], logs_bloom=f[6],
            difficulty=decode_int(f[7]), number=decode_int(f[8]),
            gas_limit=decode_int(f[9]), gas_used=decode_int(f[10]),
            timestamp=decode_int(f[11]), extra_data=f[12], mix_hash=f[13], nonce=f[14],
        )
        extra = f[15:]
        kw: dict = {}
        if len(extra) > 0:
            kw["base_fee_per_gas"] = decode_int(extra[0])
        if len(extra) > 1:
            kw["withdrawals_root"] = extra[1]
        if len(extra) > 2:
            kw["blob_gas_used"] = decode_int(extra[2])
        if len(extra) > 3:
            kw["excess_blob_gas"] = decode_int(extra[3])
        if len(extra) > 4:
            kw["parent_beacon_block_root"] = extra[4]
        if len(extra) > 5:
            kw["requests_hash"] = extra[5]
        return replace(h, **kw)

    @classmethod
    def decode(cls, data: bytes) -> "Header":
        return cls.decode_fields(rlp_decode(data))

    @property
    def hash(self) -> bytes:
        return keccak256(self.encode())


LEGACY_TX_TYPE = 0
EIP2930_TX_TYPE = 1
EIP1559_TX_TYPE = 2
EIP4844_TX_TYPE = 3
EIP7702_TX_TYPE = 4

SETCODE_MAGIC = b"\x05"              # EIP-7702 authorization signing domain
DELEGATION_PREFIX = b"\xef\x01\x00"  # EIP-7702 delegation designator


@dataclass(frozen=True)
class Authorization:
    """EIP-7702 set-code authorization tuple (signed by the authority)."""

    chain_id: int
    address: bytes
    nonce: int
    y_parity: int = 0
    r: int = 0
    s: int = 0

    def signing_hash(self) -> bytes:
        return keccak256(SETCODE_MAGIC + rlp_encode([
            encode_int(self.chain_id), self.address, encode_int(self.nonce),
        ]))

    def recover_authority(self) -> bytes:
        from .secp256k1 import ecrecover
        return ecrecover(self.signing_hash(), self.y_parity, self.r, self.s)

    def rlp_fields(self) -> list:
        return [encode_int(self.chain_id), self.address, encode_int(self.nonce),
                encode_int(self.y_parity), encode_int(self.r), encode_int(self.s)]

    @classmethod
    def from_fields(cls, f) -> "Authorization":
        if len(f[1]) != 20:
            raise ValueError("authorization address must be 20 bytes")
        return cls(chain_id=decode_int(f[0]), address=f[1], nonce=decode_int(f[2]),
                   y_parity=decode_int(f[3]), r=decode_int(f[4]), s=decode_int(f[5]))


@dataclass(frozen=True)
class Transaction:
    """Signed transaction envelope: legacy (0), EIP-2930 (1), EIP-1559 (2),
    EIP-4844 blob (3), EIP-7702 set-code (4).

    Reference: alloy-consensus `TxEnvelope`; reth recovers senders in
    `SenderRecoveryStage` (crates/stages/stages/src/stages/sender_recovery.rs).
    """

    tx_type: int = LEGACY_TX_TYPE
    chain_id: int | None = None
    nonce: int = 0
    gas_price: int = 0                # legacy/2930; for 1559+ use max_fee fields
    max_priority_fee_per_gas: int = 0
    max_fee_per_gas: int = 0
    gas_limit: int = 21_000
    to: bytes | None = None           # None = contract creation
    value: int = 0
    data: bytes = b""
    access_list: tuple = ()            # ((address, (slot32, ...)), ...)
    max_fee_per_blob_gas: int = 0      # type 3
    blob_versioned_hashes: tuple = ()  # type 3
    authorization_list: tuple = ()     # type 4: (Authorization, ...)
    # signature
    y_parity: int = 0
    r: int = 0
    s: int = 0

    def _to_field(self) -> bytes:
        return self.to if self.to is not None else b""

    def _access_list_fields(self) -> list:
        return [[addr, list(slots)] for addr, slots in self.access_list]

    def _auth_fields(self) -> list:
        return [a.rlp_fields() for a in self.authorization_list]

    def _typed_payload_fields(self) -> list:
        """Unsigned field list for typed txs (1/2/3/4)."""
        if self.tx_type == EIP2930_TX_TYPE:
            return [
                encode_int(self.chain_id or 0), encode_int(self.nonce),
                encode_int(self.gas_price), encode_int(self.gas_limit),
                self._to_field(), encode_int(self.value), self.data,
                self._access_list_fields(),
            ]
        fields = [
            encode_int(self.chain_id or 0), encode_int(self.nonce),
            encode_int(self.max_priority_fee_per_gas), encode_int(self.max_fee_per_gas),
            encode_int(self.gas_limit), self._to_field(),
            encode_int(self.value), self.data, self._access_list_fields(),
        ]
        if self.tx_type == EIP4844_TX_TYPE:
            fields += [encode_int(self.max_fee_per_blob_gas),
                       list(self.blob_versioned_hashes)]
        elif self.tx_type == EIP7702_TX_TYPE:
            fields += [self._auth_fields()]
        return fields

    def signing_hash(self) -> bytes:
        if self.tx_type == LEGACY_TX_TYPE:
            fields = [
                encode_int(self.nonce), encode_int(self.gas_price),
                encode_int(self.gas_limit), self._to_field(),
                encode_int(self.value), self.data,
            ]
            if self.chain_id is not None:  # EIP-155
                fields += [encode_int(self.chain_id), b"", b""]
            return keccak256(rlp_encode(fields))
        if self.tx_type in (EIP2930_TX_TYPE, EIP1559_TX_TYPE, EIP4844_TX_TYPE,
                            EIP7702_TX_TYPE):
            return keccak256(bytes([self.tx_type])
                             + rlp_encode(self._typed_payload_fields()))
        raise ValueError(f"unsupported tx type {self.tx_type}")

    def encode(self) -> bytes:
        """Network/consensus encoding (typed txs prefixed with their type byte)."""
        if self.tx_type == LEGACY_TX_TYPE:
            if self.chain_id is not None:
                v = self.chain_id * 2 + 35 + self.y_parity
            else:
                v = 27 + self.y_parity
            return rlp_encode([
                encode_int(self.nonce), encode_int(self.gas_price),
                encode_int(self.gas_limit), self._to_field(),
                encode_int(self.value), self.data,
                encode_int(v), encode_int(self.r), encode_int(self.s),
            ])
        if self.tx_type in (EIP2930_TX_TYPE, EIP1559_TX_TYPE, EIP4844_TX_TYPE,
                            EIP7702_TX_TYPE):
            fields = self._typed_payload_fields() + [
                encode_int(self.y_parity), encode_int(self.r), encode_int(self.s),
            ]
            return bytes([self.tx_type]) + rlp_encode(fields)
        raise ValueError(f"unsupported tx type {self.tx_type}")

    @classmethod
    def decode(cls, data: bytes) -> "Transaction":
        data = bytes(data)
        if data and data[0] == EIP2930_TX_TYPE:
            f = rlp_decode(data[1:])
            return cls(
                tx_type=EIP2930_TX_TYPE, chain_id=decode_int(f[0]),
                nonce=decode_int(f[1]), gas_price=decode_int(f[2]),
                gas_limit=decode_int(f[3]), to=f[4] or None,
                value=decode_int(f[5]), data=f[6],
                access_list=tuple((a, tuple(slots)) for a, slots in f[7]),
                y_parity=decode_int(f[8]), r=decode_int(f[9]), s=decode_int(f[10]),
            )
        if data and data[0] in (EIP1559_TX_TYPE, EIP4844_TX_TYPE, EIP7702_TX_TYPE):
            tx_type = data[0]
            f = rlp_decode(data[1:])
            kw = dict(
                tx_type=tx_type, chain_id=decode_int(f[0]),
                nonce=decode_int(f[1]), max_priority_fee_per_gas=decode_int(f[2]),
                max_fee_per_gas=decode_int(f[3]), gas_limit=decode_int(f[4]),
                to=f[5] or None, value=decode_int(f[6]), data=f[7],
                access_list=tuple((a, tuple(slots)) for a, slots in f[8]),
            )
            i = 9
            if tx_type == EIP4844_TX_TYPE:
                kw["max_fee_per_blob_gas"] = decode_int(f[9])
                hashes = tuple(f[10])
                if any(len(h) != 32 for h in hashes):
                    raise ValueError("blob versioned hash must be 32 bytes")
                kw["blob_versioned_hashes"] = hashes
                i = 11
            elif tx_type == EIP7702_TX_TYPE:
                kw["authorization_list"] = tuple(
                    Authorization.from_fields(a) for a in f[9]
                )
                i = 10
            return cls(
                y_parity=decode_int(f[i]), r=decode_int(f[i + 1]),
                s=decode_int(f[i + 2]), **kw,
            )
        f = rlp_decode(data)
        v = decode_int(f[6])
        if v in (27, 28):
            chain_id, y_parity = None, v - 27
        elif v >= 35:
            chain_id = (v - 35) // 2
            y_parity = (v - 35) % 2
        else:
            raise ValueError(f"invalid legacy signature v: {v}")
        return cls(
            tx_type=LEGACY_TX_TYPE, chain_id=chain_id, nonce=decode_int(f[0]),
            gas_price=decode_int(f[1]), gas_limit=decode_int(f[2]), to=f[3] or None,
            value=decode_int(f[4]), data=f[5], y_parity=y_parity,
            r=decode_int(f[7]), s=decode_int(f[8]),
        )

    @property
    def hash(self) -> bytes:
        return keccak256(self.encode())

    def effective_gas_price(self, base_fee: int | None) -> int:
        if self.tx_type in (LEGACY_TX_TYPE, EIP2930_TX_TYPE):
            return self.gas_price
        if base_fee is None:
            return self.max_fee_per_gas
        return min(self.max_fee_per_gas, base_fee + self.max_priority_fee_per_gas)

    def blob_gas(self) -> int:
        return GAS_PER_BLOB * len(self.blob_versioned_hashes)

    def recover_sender(self) -> bytes:
        from .secp256k1 import ecrecover
        return ecrecover(self.signing_hash(), self.y_parity, self.r, self.s)


GAS_PER_BLOB = 1 << 17  # EIP-4844


def recover_senders(txs, allow_high_s: bool = False) -> list[bytes | None]:
    """Batched sender recovery for a transaction sequence (one threaded
    native dispatch; see primitives.secp256k1.ecrecover_batch). The single
    place that maps signature fields to recovery inputs."""
    from .secp256k1 import ecrecover_batch

    return ecrecover_batch(
        [(tx.signing_hash(), tx.y_parity, tx.r, tx.s) for tx in txs],
        allow_high_s=allow_high_s,
    )


@dataclass(frozen=True)
class Log:
    address: bytes
    topics: tuple[bytes, ...]
    data: bytes

    def rlp_fields(self) -> list:
        return [self.address, list(self.topics), self.data]


def logs_bloom(logs: list[Log]) -> bytes:
    """2048-bit bloom over log addresses and topics (yellow paper M3:2048)."""
    bloom = bytearray(256)
    items: list[bytes] = []
    for log in logs:
        items.append(log.address)
        items.extend(log.topics)
    for item in items:
        h = keccak256(item)
        for i in (0, 2, 4):
            bit = ((h[i] << 8) | h[i + 1]) & 0x7FF
            bloom[256 - 1 - bit // 8] |= 1 << (bit % 8)
    return bytes(bloom)


@dataclass(frozen=True)
class Receipt:
    """Transaction receipt (reference: reth `Receipt`).

    ``state_root`` is the pre-Byzantium form: receipts embedded the
    post-transaction state root until EIP-658 replaced it with the
    success status."""

    tx_type: int = LEGACY_TX_TYPE
    success: bool = True
    cumulative_gas_used: int = 0
    logs: tuple[Log, ...] = ()
    state_root: bytes | None = None

    def bloom(self) -> bytes:
        return logs_bloom(list(self.logs))

    def encode_2718(self) -> bytes:
        """EIP-2718 encoding as placed in the receipts trie."""
        payload = rlp_encode([
            (self.state_root if self.state_root is not None
             else encode_int(1 if self.success else 0)),
            encode_int(self.cumulative_gas_used),
            self.bloom(),
            [log.rlp_fields() for log in self.logs],
        ])
        if self.tx_type == LEGACY_TX_TYPE:
            return payload
        return bytes([self.tx_type]) + payload


def body_rlp_fields(
    transactions: tuple[Transaction, ...],
    ommers: tuple[Header, ...],
    withdrawals: tuple[Withdrawal, ...] | None,
) -> list:
    """Block-body RLP shape — the single home for it (blocks + wire bodies)."""
    fields: list = [
        [_tx_block_item(tx) for tx in transactions],
        [o.rlp_fields() for o in ommers],
    ]
    if withdrawals is not None:
        fields.append([w.rlp_fields() for w in withdrawals])
    return fields


def body_from_fields(f: list):
    """Inverse of ``body_rlp_fields`` → (txs, ommers, withdrawals)."""
    withdrawals = None
    if len(f) > 2:
        withdrawals = tuple(
            Withdrawal(decode_int(w[0]), decode_int(w[1]), w[2], decode_int(w[3]))
            for w in f[2]
        )
    return (
        tuple(_tx_from_block_item(t) for t in f[0]),
        tuple(Header.decode_fields(o) for o in f[1]),
        withdrawals,
    )


@dataclass(frozen=True)
class Block:
    header: Header
    transactions: tuple[Transaction, ...] = ()
    ommers: tuple[Header, ...] = ()
    withdrawals: tuple[Withdrawal, ...] | None = None

    def encode(self) -> bytes:
        return rlp_encode(
            [self.header.rlp_fields()]
            + body_rlp_fields(self.transactions, self.ommers, self.withdrawals)
        )

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        f = rlp_decode(data)
        header = Header.decode_fields(f[0])
        txs, ommers, withdrawals = body_from_fields(f[1:])
        return cls(header, txs, ommers, withdrawals)

    @property
    def hash(self) -> bytes:
        return self.header.hash


def _tx_block_item(tx: Transaction):
    """In a block body, typed txs appear as RLP strings, legacy as lists."""
    enc = tx.encode()
    if tx.tx_type == LEGACY_TX_TYPE:
        return rlp_decode(enc)  # as a list structure
    return enc


def _tx_from_block_item(item) -> Transaction:
    if isinstance(item, bytes):
        return Transaction.decode(item)
    return Transaction.decode(rlp_encode(item))
