"""Generic pairing engine over pairing-friendly curves (BN254, BLS12-381).

Reference analogue: the reference consumes these through native libs —
bn254 via revm's precompile crates and BLS12-381/KZG via c-kzg
(reference Cargo.toml:597). Here the math is implemented once, from the
curve equations up, parameterized by a :class:`Curve` config.

Design notes:
- Fp2 is (a, b) = a + b*u with u^2 = -1 (true for both supported curves).
- Fp12 is a FLAT polynomial basis 1, w, ..., w^11 with the single
  reduction w^12 = 2*x0*w^6 - (x0^2+1), derived from w^6 = xi = x0 + u.
  This avoids a three-level tower; multiplication is schoolbook 12x12.
- The pairing is the REDUCED TATE PAIRING with denominator elimination
  (even embedding degree): Miller loop over the 255-bit group order with
  G1 arithmetic in Fp and line evaluations at the untwisted G2 point in
  Fp12, then one final exponentiation to (p^12-1)/r.
  Correctness argument for consumers: every non-degenerate bilinear
  pairing on (G1, G2) into mu_r is a fixed power of every other, so
  product-equals-one checks (EIP-197) and pairing-equality checks (KZG)
  are invariant across pairing choices; bilinearity + non-degeneracy are
  pinned by tests/test_pairing.py property tests.
- Pure Python by design: precompile traffic is rare and correctness-
  critical; the batched hashing planes live on the device instead.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

# ---------------------------------------------------------------------------
# curve configurations
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Curve:
    name: str
    p: int                      # base field prime
    r: int                      # prime subgroup order
    b: int                      # G1: y^2 = x^3 + b
    b2: tuple[int, int]         # twist: y^2 = x^3 + b2 (over Fp2)
    x0: int                     # xi = x0 + u (w^6 = xi)
    m_twist: bool               # M-twist (untwist divides by w^2/w^3)
    g1: tuple[int, int]
    g2: tuple[tuple[int, int], tuple[int, int]]


_BN_P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
_BN_R = 21888242871839275222246405745257275088548364400416034343698204186575808495617
# 3 / (9 + u) in Fp2: (9 - u) * 3 / 82
_BN_B2 = (
    19485874751759354771024239261021720505790618469301721065564631296452457478373,
    266929791119991161246907387137283842545076965332900288569378510910307636690,
)

BN254 = Curve(
    name="bn254",
    p=_BN_P,
    r=_BN_R,
    b=3,
    b2=_BN_B2,
    x0=9,
    m_twist=False,
    g1=(1, 2),
    g2=(
        (
            10857046999023057135944570762232829481370756359578518086990519993285655852781,
            11559732032986387107991004021392285783925812861821192530917403151452391805634,
        ),
        (
            8495653923123431417604973247489272438418190587263600148770280649306958101930,
            4082367875863433681332203403145435568316851327593401208105741076214120093531,
        ),
    ),
)

_BLS_P = int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab", 16,
)
_BLS_R = int("73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001", 16)

BLS12_381 = Curve(
    name="bls12_381",
    p=_BLS_P,
    r=_BLS_R,
    b=4,
    b2=(4, 4),                  # 4 * (1 + u): M-twist
    x0=1,
    m_twist=True,
    g1=(
        int("17f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
            "6c55e83ff97a1aeffb3af00adb22c6bb", 16),
        int("08b3f481e3aaa0f1a09e30ed741d8ae4fcf5e095d5d00af600db18cb2c04b3ed"
            "d03cc744a2888ae40caa232946c5e7e1", 16),
    ),
    g2=(
        (
            int("024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647ae3d177"
                "0bac0326a805bbefd48056c8c121bdb8", 16),
            int("13e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
                "334cf11213945d57e5ac7d055d042b7e", 16),
        ),
        (
            int("0ce5d527727d6e118cc9cdc6da2e351aadfd9baa8cbdd3a76d429a695160d12c"
                "923ac9cc3baca289e193548608b82801", 16),
            int("0606c4a02ea734cc32acd2b02bc28b99cb3e287e85a763af267492ab572e99ab"
                "3f370d275cec1da1aaa9075ff05f79be", 16),
        ),
    ),
)


# ---------------------------------------------------------------------------
# Fp / Fp2 arithmetic (tuples, module functions — hot enough to stay flat)
# ---------------------------------------------------------------------------


def _inv(a: int, p: int) -> int:
    if a == 0:
        raise ZeroDivisionError("field inverse of 0")
    return pow(a, p - 2, p)


def f2_add(a, b, p):
    return ((a[0] + b[0]) % p, (a[1] + b[1]) % p)


def f2_sub(a, b, p):
    return ((a[0] - b[0]) % p, (a[1] - b[1]) % p)


def f2_mul(a, b, p):
    # (a0 + a1 u)(b0 + b1 u), u^2 = -1
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % p, (t2 - t0 - t1) % p)


def f2_sqr(a, p):
    # (a0+a1u)^2 = (a0-a1)(a0+a1) + 2a0a1 u
    return ((a[0] - a[1]) * (a[0] + a[1]) % p, 2 * a[0] * a[1] % p)


def f2_neg(a, p):
    return ((-a[0]) % p, (-a[1]) % p)


def f2_inv(a, p):
    n = _inv((a[0] * a[0] + a[1] * a[1]) % p, p)
    return (a[0] * n % p, (-a[1]) * n % p)


def f2_scalar(a, k: int, p):
    return (a[0] * k % p, a[1] * k % p)


# ---------------------------------------------------------------------------
# generic affine short-Weierstrass point ops (field ops injected)
# ---------------------------------------------------------------------------


class _Group:
    """Affine group law over a generic field (Fp as ints or Fp2 as tuples)."""

    def __init__(self, p, b, add, sub, mul, sqr, neg, inv, zero, scalar3, scalar2):
        self.p, self.b = p, b
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.neg, self.inv, self.zero = neg, inv, zero
        self.scalar3, self.scalar2 = scalar3, scalar2  # multiply by 3 / by 2

    def on_curve(self, pt) -> bool:
        if pt is None:
            return True
        x, y = pt
        lhs = self.sqr(y)
        rhs = self.add(self.mul(self.sqr(x), x), self.b)
        return lhs == rhs

    def double(self, pt):
        if pt is None:
            return None
        x, y = pt
        if y == self.zero:
            return None
        lam = self.mul(self.scalar3(self.sqr(x)), self.inv(self.scalar2(y)))
        x3 = self.sub(self.sub(self.sqr(lam), x), x)
        y3 = self.sub(self.mul(lam, self.sub(x, x3)), y)
        return (x3, y3)

    def padd(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if a[0] == b[0]:
            if a[1] == b[1]:
                return self.double(a)
            return None
        lam = self.mul(self.sub(b[1], a[1]), self.inv(self.sub(b[0], a[0])))
        x3 = self.sub(self.sub(self.sqr(lam), a[0]), b[0])
        y3 = self.sub(self.mul(lam, self.sub(a[0], x3)), a[1])
        return (x3, y3)

    def mul_scalar(self, pt, k: int):
        acc = None
        add = pt
        while k:
            if k & 1:
                acc = self.padd(acc, add)
            add = self.double(add)
            k >>= 1
        return acc


@lru_cache(maxsize=None)
def g1_group(curve: Curve) -> _Group:
    p = curve.p
    return _Group(
        p, curve.b % p,
        add=lambda a, b: (a + b) % p, sub=lambda a, b: (a - b) % p,
        mul=lambda a, b: a * b % p, sqr=lambda a: a * a % p,
        neg=lambda a: (-a) % p, inv=lambda a: _inv(a, p), zero=0,
        scalar3=lambda a: 3 * a % p, scalar2=lambda a: 2 * a % p,
    )


@lru_cache(maxsize=None)
def g2_group(curve: Curve) -> _Group:
    p = curve.p
    return _Group(
        p, (curve.b2[0] % p, curve.b2[1] % p),
        add=lambda a, b: f2_add(a, b, p), sub=lambda a, b: f2_sub(a, b, p),
        mul=lambda a, b: f2_mul(a, b, p), sqr=lambda a: f2_sqr(a, p),
        neg=lambda a: f2_neg(a, p), inv=lambda a: f2_inv(a, p), zero=(0, 0),
        scalar3=lambda a: f2_scalar(a, 3, p), scalar2=lambda a: f2_scalar(a, 2, p),
    )


# ---------------------------------------------------------------------------
# flat Fp12: 12-tuple of Fp coefficients over basis w^i,
# reduced by w^12 = 2*x0*w^6 - (x0^2 + 1)
# ---------------------------------------------------------------------------


def f12_one(curve) -> tuple:
    return (1,) + (0,) * 11


def f12_mul(a, b, curve):
    p = curve.p
    t = [0] * 23
    for i, ai in enumerate(a):
        if ai:
            for j, bj in enumerate(b):
                if bj:
                    t[i + j] += ai * bj
    c1 = 2 * curve.x0
    c0 = -(curve.x0 * curve.x0 + 1)
    for k in range(22, 11, -1):
        v = t[k]
        if v:
            t[k - 6] += v * c1
            t[k - 12] += v * c0
            t[k] = 0
    return tuple(v % p for v in t[:12])


def f12_sqr(a, curve):
    return f12_mul(a, a, curve)


def f12_scalar(a, k: int, curve):
    p = curve.p
    return tuple(v * k % p for v in a)


def f12_add(a, b, curve):
    p = curve.p
    return tuple((x + y) % p for x, y in zip(a, b))


def f12_sub(a, b, curve):
    p = curve.p
    return tuple((x - y) % p for x, y in zip(a, b))


def f12_pow(a, e: int, curve):
    result = f12_one(curve)
    base = a
    while e:
        if e & 1:
            result = f12_mul(result, base, curve)
        base = f12_sqr(base, curve)
        e >>= 1
    return result


def f12_embed2(a2, curve):
    """Fp2 element a + b*u -> flat Fp12 (u = w^6 - x0)."""
    a, b = a2
    v = [0] * 12
    v[0] = (a - curve.x0 * b) % curve.p
    v[6] = b % curve.p
    return tuple(v)


def _wshift(a, k: int, curve):
    """Multiply by w^k (k < 12) and reduce."""
    t = [0] * 23
    for i, ai in enumerate(a):
        t[i + k] = ai
    p = curve.p
    c1 = 2 * curve.x0
    c0 = -(curve.x0 * curve.x0 + 1)
    for kk in range(22, 11, -1):
        v = t[kk]
        if v:
            t[kk - 6] += v * c1
            t[kk - 12] += v * c0
            t[kk] = 0
    return tuple(v % p for v in t[:12])


@lru_cache(maxsize=None)
def _untwist_consts(curve: Curve):
    """Fp12 constants (cx, cy) with untwist(x', y') = (embed(x')*cx,
    embed(y')*cy). D-twist multiplies by w^2/w^3; M-twist divides —
    and w^-k = w^(6-k) * xi^-1 with xi^-1 a cheap Fp2 inverse."""
    p = curve.p
    if not curve.m_twist:
        cx = _wshift(f12_one(curve), 2, curve)
        cy = _wshift(f12_one(curve), 3, curve)
        return cx, cy
    xi_inv = f2_inv((curve.x0, 1), p)
    inv12 = f12_embed2(xi_inv, curve)
    cx = _wshift(inv12, 4, curve)   # w^-2 = w^4 * xi^-1
    cy = _wshift(inv12, 3, curve)   # w^-3 = w^3 * xi^-1
    return cx, cy


def untwist(q, curve):
    """Twist-curve G2 point (Fp2 affine) -> E(Fp12) affine."""
    cx, cy = _untwist_consts(curve)
    x = f12_mul(f12_embed2(q[0], curve), cx, curve)
    y = f12_mul(f12_embed2(q[1], curve), cy, curve)
    return x, y


# ---------------------------------------------------------------------------
# reduced Tate pairing (denominator elimination)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _final_exp_power(curve: Curve) -> int:
    return (curve.p ** 12 - 1) // curve.r


def miller_loop(p1, q2, curve):
    """Unreduced f_{r,P}(psi(Q)) for P in G1 (Fp affine), Q in G2 (twist
    Fp2 affine). Verticals are eliminated (wiped by the final exp)."""
    if p1 is None or q2 is None:
        return f12_one(curve)
    p = curve.p
    xq, yq = untwist(q2, curve)

    def line(t, s):
        """l_{T,S}(Q) in Fp12, or None for verticals (eliminated by the
        final exponentiation — even embedding degree)."""
        if t is None or s is None:
            return None
        xt, yt = t
        xs, ys = s
        if xt == xs and yt == ys:
            if yt == 0:
                return None
            lam = 3 * xt * xt * _inv(2 * yt, p) % p
        elif xt == xs:
            return None
        else:
            lam = (ys - yt) * _inv((xs - xt) % p, p) % p
        # l(Q) = lam*xQ - yQ + (yt - lam*xt)
        val = f12_sub(f12_scalar(xq, lam, curve), yq, curve)
        const = (yt - lam * xt) % p
        return ((val[0] + const) % p,) + val[1:]

    g = g1_group(curve)
    f = f12_one(curve)
    t = p1
    for bit in bin(curve.r)[3:]:
        f = f12_sqr(f, curve)
        l = line(t, t)
        if l is not None:
            f = f12_mul(f, l, curve)
        t = g.double(t)
        if bit == "1":
            l = line(t, p1)
            if l is not None:
                f = f12_mul(f, l, curve)
            t = g.padd(t, p1)
    return f


def pairing(p1, q2, curve) -> tuple:
    """Reduced Tate pairing e(P, Q) in mu_r (flat Fp12)."""
    return f12_pow(miller_loop(p1, q2, curve), _final_exp_power(curve), curve)


def pairing_product_is_one(pairs, curve) -> bool:
    """prod e(Pi, Qi) == 1 with a single final exponentiation."""
    f = f12_one(curve)
    for p1, q2 in pairs:
        f = f12_mul(f, miller_loop(p1, q2, curve), curve)
    return f12_pow(f, _final_exp_power(curve), curve) == f12_one(curve)


# ---------------------------------------------------------------------------
# subgroup / curve checks
# ---------------------------------------------------------------------------


def g1_valid(pt, curve) -> bool:
    """On-curve (+ subgroup when the cofactor is nontrivial, i.e. BLS)."""
    g = g1_group(curve)
    if pt is None:
        return True
    x, y = pt
    if not (0 <= x < curve.p and 0 <= y < curve.p) or not g.on_curve(pt):
        return False
    if curve.name == "bn254":
        return True  # cofactor 1
    return g.mul_scalar(pt, curve.r) is None


def g2_valid(pt, curve) -> bool:
    """On-twist-curve + r-torsion (G2 cofactors are large for both)."""
    g = g2_group(curve)
    if pt is None:
        return True
    (x0_, x1_), (y0_, y1_) = pt
    if not all(0 <= c < curve.p for c in (x0_, x1_, y0_, y1_)):
        return False
    if not g.on_curve(pt):
        return False
    return g.mul_scalar(pt, curve.r) is None
