"""Keccak-256 — CPU reference implementations.

Two host-side implementations of Ethereum's Keccak-256 (original Keccak
padding 0x01, NOT NIST SHA3's 0x06):

- ``keccak256``          — pure-Python, bit-exact reference used by tests and
  by cold host paths. Reference analogue: `alloy_primitives::keccak256`
  (the reference enables the `asm-keccak` sha3-asm fast path by default,
  reference bin/reth/Cargo.toml:94).
- ``keccak256_batch_np`` — numpy-vectorised batch version over uint64 lanes;
  this is the *CPU baseline* that the TPU kernel in
  ``reth_tpu.ops.keccak_jax`` is benchmarked against, standing in for the
  reference's 32-core rayon keccak (reference
  crates/stages/stages/src/stages/hashing_account.rs:29-32).

The permutation layout follows FIPS-202: 25 lanes of 64 bits, flat index
``idx = x + 5*y``.
"""

from __future__ import annotations

import numpy as np

RATE = 136  # bytes: keccak-256 rate (1088 bits), capacity 512

# Round constants for keccak-f[1600] (24 rounds).
RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# Rotation offsets r[x][y].
ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1


def _rotl(v: int, r: int) -> int:
    return ((v << r) | (v >> (64 - r))) & _MASK


def keccak_f1600(state: list[int]) -> list[int]:
    """One keccak-f[1600] permutation over 25 python-int lanes (pure ref)."""
    a = list(state)
    for rc in RC:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y] & _MASK)
        # iota
        a[0] ^= rc
    return a


def _pad(data: bytes) -> bytes:
    """Multi-rate keccak padding: 0x01 … 0x80 (0x81 if a single pad byte)."""
    q = RATE - (len(data) % RATE)
    if q == 1:
        return data + b"\x81"
    return data + b"\x01" + b"\x00" * (q - 2) + b"\x80"


def keccak256(data: bytes) -> bytes:
    """Ethereum Keccak-256 of ``data`` (pure-Python reference)."""
    padded = _pad(bytes(data))
    state = [0] * 25
    for off in range(0, len(padded), RATE):
        block = padded[off : off + RATE]
        for i in range(RATE // 8):
            state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        state = keccak_f1600(state)
    out = b"".join(state[i].to_bytes(8, "little") for i in range(4))
    return out


class Keccak256:
    """Incremental (streaming) keccak-256 with copyable state.

    The RLPx frame-MAC scheme (net/rlpx.py) keeps two forever-running
    keccak states (egress/ingress) and reads 16-byte digests mid-stream;
    ``digest()`` pads a COPY so the running state is unaffected."""

    def __init__(self, data: bytes = b""):
        self._state = [0] * 25
        self._buf = b""
        if data:
            self.update(data)

    def update(self, data: bytes) -> "Keccak256":
        buf = self._buf + bytes(data)
        off = 0
        while len(buf) - off >= RATE:
            block = buf[off : off + RATE]
            for i in range(RATE // 8):
                self._state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
            self._state = keccak_f1600(self._state)
            off += RATE
        self._buf = buf[off:]
        return self

    def copy(self) -> "Keccak256":
        k = Keccak256()
        k._state = list(self._state)
        k._buf = self._buf
        return k

    def digest(self) -> bytes:
        state = list(self._state)
        padded = _pad(self._buf)  # buffered remainder < RATE => one block
        for off in range(0, len(padded), RATE):
            blk = padded[off : off + RATE]
            for i in range(RATE // 8):
                state[i] ^= int.from_bytes(blk[8 * i : 8 * i + 8], "little")
            state = keccak_f1600(state)
        return b"".join(state[i].to_bytes(8, "little") for i in range(4))


# ---------------------------------------------------------------------------
# numpy-vectorised batch implementation (CPU baseline for the TPU kernel)
# ---------------------------------------------------------------------------

_RC_NP = np.array(RC, dtype=np.uint64)


def _rotl_np(v: np.ndarray, r: int) -> np.ndarray:
    if r == 0:
        return v
    return (v << np.uint64(r)) | (v >> np.uint64(64 - r))


def keccak_f1600_np(lanes: np.ndarray) -> np.ndarray:
    """Vectorised keccak-f[1600]: ``lanes`` is (N, 25) uint64."""
    a = [lanes[:, i].copy() for i in range(25)]
    for rnd in range(24):
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl_np(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = a[x + 5 * y] ^ d[x]
        b = [None] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl_np(a[x + 5 * y], ROT[x][y])
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y])
        a[0] = a[0] ^ _RC_NP[rnd]
    return np.stack(a, axis=1)


def pad_batch(
    msgs: list[bytes],
    num_blocks: int | np.ndarray,
    pad_to_blocks: int | None = None,
) -> np.ndarray:
    """Pad each message at ITS OWN final rate block, zero-extend the buffer to
    ``pad_to_blocks`` blocks; return (N, pad_to_blocks*17) uint64.

    ``num_blocks`` is each message's real block count (``num_blocks_for``) —
    a scalar for uniform buckets or a per-message array. ``pad_to_blocks``
    defaults to the max block count; blocks at index >= a message's count are
    all-zero and must NOT be absorbed (masked-absorb kernels only).
    """
    n = len(msgs)
    nb = np.broadcast_to(np.asarray(num_blocks, dtype=np.int64), (n,))
    total = (pad_to_blocks if pad_to_blocks is not None else int(nb.max() if n else 1)) * RATE
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    if lens.size and (lens > nb * RATE - 1).any():
        bad = int(np.argmax(lens > nb * RATE - 1))
        raise ValueError(f"message {bad} too long for {nb[bad]} blocks: {lens[bad]}")
    # Vectorised scatter: this runs on the host hot path feeding the device,
    # so no per-message Python work is allowed.
    flat = np.frombuffer(b"".join(msgs), dtype=np.uint8)
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
    cols = np.arange(total, dtype=np.int64)
    gather = starts[:, None] + cols[None, :]
    valid = cols[None, :] < lens[:, None]
    np.minimum(gather, max(flat.size - 1, 0), out=gather)
    buf = np.where(valid, flat[gather] if flat.size else 0, 0).astype(np.uint8)
    rows = np.arange(n)
    buf[rows, lens] ^= 0x01
    buf[rows, nb * RATE - 1] ^= 0x80
    return buf.view("<u8").reshape(n, total // 8)


def num_blocks_for(msg: bytes) -> int:
    """Rate-block count of ``msg`` after keccak padding."""
    return len(msg) // RATE + 1


def bucketed_hash(msgs: list[bytes], bucket_hasher, bucket_key=None) -> list[bytes]:
    """Shared bucketing scaffolding for batch hashers.

    Messages are grouped by ``bucket_key(num_blocks)`` (default: the exact
    block count). ``bucket_hasher(sub_msgs, key, counts)`` — where ``counts``
    is the per-message real block-count array — must return an array whose
    rows view as the 32-byte digests (``row.tobytes()`` == digest). Order of
    ``msgs`` is preserved. Both the numpy CPU baseline and the JAX device
    front-end route through this, so bucketing semantics cannot diverge.
    """
    if not msgs:
        return []
    out: list[bytes | None] = [None] * len(msgs)
    buckets: dict[int, list[int]] = {}
    for i, m in enumerate(msgs):
        nb = num_blocks_for(m)
        buckets.setdefault(bucket_key(nb) if bucket_key else nb, []).append(i)
    for key, idxs in sorted(buckets.items()):
        counts = np.fromiter(
            (num_blocks_for(msgs[i]) for i in idxs), dtype=np.int64, count=len(idxs)
        )
        digests = bucket_hasher([msgs[i] for i in idxs], key, counts)
        for row, i in enumerate(idxs):
            out[i] = digests[row].tobytes()
    return out  # type: ignore[return-value]


def keccak256_batch_np(msgs: list[bytes]) -> list[bytes]:
    """Batched keccak-256 over same-or-mixed-length messages (numpy, CPU)."""
    return bucketed_hash(
        msgs, lambda sub, nb, _counts: keccak256_words_np(pad_batch(sub, nb), nb)
    )


def keccak256_words_masked_np(
    words: np.ndarray, max_blocks: int, counts: np.ndarray
) -> np.ndarray:
    """Masked absorb (numpy twin of the device kernel): each message padded
    at its OWN final rate block and zero-extended to ``max_blocks``; blocks
    at index >= counts[i] leave message i's state untouched. Returns
    (N, 4) uint64 digest lanes."""
    n = words.shape[0]
    state = np.zeros((n, 25), dtype=np.uint64)
    for blk in range(max_blocks):
        nxt = state.copy()
        nxt[:, :17] ^= words[:, blk * 17 : (blk + 1) * 17]
        nxt = keccak_f1600_np(nxt)
        live = (blk < counts)[:, None]
        state = np.where(live, nxt, state)
    return np.ascontiguousarray(state[:, :4])


def keccak256_words_np(words: np.ndarray, num_blocks: int) -> np.ndarray:
    """Absorb ``num_blocks`` rate-blocks of pre-padded words, return (N, 4) u64.

    ``words`` is (N, num_blocks*17) uint64 little-endian as from ``pad_batch``.
    """
    n = words.shape[0]
    state = np.zeros((n, 25), dtype=np.uint64)
    for blk in range(num_blocks):
        state[:, :17] ^= words[:, blk * 17 : (blk + 1) * 17]
        state = keccak_f1600_np(state)
    return np.ascontiguousarray(state[:, :4])
