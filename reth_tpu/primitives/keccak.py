"""Keccak-256 — CPU reference implementations.

Two host-side implementations of Ethereum's Keccak-256 (original Keccak
padding 0x01, NOT NIST SHA3's 0x06):

- ``keccak256``          — pure-Python, bit-exact reference used by tests and
  by cold host paths. Reference analogue: `alloy_primitives::keccak256`
  (the reference enables the `asm-keccak` sha3-asm fast path by default,
  reference bin/reth/Cargo.toml:94).
- ``keccak256_batch_np`` — numpy-vectorised batch version over uint64 lanes;
  this is the *CPU baseline* that the TPU kernel in
  ``reth_tpu.ops.keccak_jax`` is benchmarked against, standing in for the
  reference's 32-core rayon keccak (reference
  crates/stages/stages/src/stages/hashing_account.rs:29-32).

The permutation layout follows FIPS-202: 25 lanes of 64 bits, flat index
``idx = x + 5*y``.
"""

from __future__ import annotations

import numpy as np

RATE = 136  # bytes: keccak-256 rate (1088 bits), capacity 512

# Round constants for keccak-f[1600] (24 rounds).
RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# Rotation offsets r[x][y].
ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1


def _rotl(v: int, r: int) -> int:
    return ((v << r) | (v >> (64 - r))) & _MASK


def keccak_f1600(state: list[int]) -> list[int]:
    """One keccak-f[1600] permutation over 25 python-int lanes (pure ref)."""
    a = list(state)
    for rc in RC:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y] & _MASK)
        # iota
        a[0] ^= rc
    return a


def _pad(data: bytes) -> bytes:
    """Multi-rate keccak padding: 0x01 … 0x80 (0x81 if a single pad byte)."""
    q = RATE - (len(data) % RATE)
    if q == 1:
        return data + b"\x81"
    return data + b"\x01" + b"\x00" * (q - 2) + b"\x80"


def keccak256(data: bytes) -> bytes:
    """Ethereum Keccak-256 of ``data`` (pure-Python reference)."""
    padded = _pad(bytes(data))
    state = [0] * 25
    for off in range(0, len(padded), RATE):
        block = padded[off : off + RATE]
        for i in range(RATE // 8):
            state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        state = keccak_f1600(state)
    out = b"".join(state[i].to_bytes(8, "little") for i in range(4))
    return out


# ---------------------------------------------------------------------------
# numpy-vectorised batch implementation (CPU baseline for the TPU kernel)
# ---------------------------------------------------------------------------

_RC_NP = np.array(RC, dtype=np.uint64)


def _rotl_np(v: np.ndarray, r: int) -> np.ndarray:
    if r == 0:
        return v
    return (v << np.uint64(r)) | (v >> np.uint64(64 - r))


def keccak_f1600_np(lanes: np.ndarray) -> np.ndarray:
    """Vectorised keccak-f[1600]: ``lanes`` is (N, 25) uint64."""
    a = [lanes[:, i].copy() for i in range(25)]
    for rnd in range(24):
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl_np(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = a[x + 5 * y] ^ d[x]
        b = [None] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl_np(a[x + 5 * y], ROT[x][y])
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y])
        a[0] = a[0] ^ _RC_NP[rnd]
    return np.stack(a, axis=1)


def pad_batch(msgs: list[bytes], num_blocks: int) -> np.ndarray:
    """Pad each message to ``num_blocks*RATE`` bytes, return (N, blocks*17) uint64.

    All messages must fit: ``len(m) < num_blocks*RATE`` with room for at least
    one pad byte (i.e. ``len(m) <= num_blocks*RATE - 1``).
    """
    n = len(msgs)
    total = num_blocks * RATE
    buf = np.zeros((n, total), dtype=np.uint8)
    for i, m in enumerate(msgs):
        lm = len(m)
        if lm > total - 1:
            raise ValueError(f"message {i} too long for {num_blocks} blocks: {lm}")
        buf[i, :lm] = np.frombuffer(m, dtype=np.uint8)
        buf[i, lm] ^= 0x01
        buf[i, total - 1] ^= 0x80
    return buf.view("<u8").reshape(n, total // 8)


def keccak256_batch_np(msgs: list[bytes]) -> list[bytes]:
    """Batched keccak-256 over same-or-mixed-length messages (numpy, CPU).

    Buckets messages by block count internally; order preserved.
    """
    if not msgs:
        return []
    out: list[bytes | None] = [None] * len(msgs)
    buckets: dict[int, list[int]] = {}
    for i, m in enumerate(msgs):
        nb = len(m) // RATE + 1
        buckets.setdefault(nb, []).append(i)
    for nb, idxs in buckets.items():
        words = pad_batch([msgs[i] for i in idxs], nb)
        digests = keccak256_words_np(words, nb)
        for row, i in enumerate(idxs):
            out[i] = digests[row].tobytes()
    return out  # type: ignore[return-value]


def keccak256_words_np(words: np.ndarray, num_blocks: int) -> np.ndarray:
    """Absorb ``num_blocks`` rate-blocks of pre-padded words, return (N, 4) u64.

    ``words`` is (N, num_blocks*17) uint64 little-endian as from ``pad_batch``.
    """
    n = words.shape[0]
    state = np.zeros((n, 25), dtype=np.uint64)
    for blk in range(num_blocks):
        state[:, :17] ^= words[:, blk * 17 : (blk + 1) * 17]
        state = keccak_f1600_np(state)
    return np.ascontiguousarray(state[:, :4])
