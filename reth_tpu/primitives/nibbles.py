"""Nibble paths and hex-prefix encoding for the Merkle-Patricia-Trie.

Reference analogue: `Nibbles` in crates/trie/common/src/nibbles.rs and the
hex-prefix ("compact") path encoding from the Ethereum yellow paper.

A nibble path is represented as an immutable ``bytes`` where every byte is
0..15 — simple, hashable (usable as dict key), and cheap to slice. This is
the host-side structural representation; device kernels never see nibbles.
"""

from __future__ import annotations

Nibbles = bytes  # each byte 0..15


def unpack_nibbles(key: bytes) -> Nibbles:
    """Byte key → nibble path (hi nibble first)."""
    out = bytearray(2 * len(key))
    for i, b in enumerate(key):
        out[2 * i] = b >> 4
        out[2 * i + 1] = b & 0x0F
    return bytes(out)


def pack_nibbles(nibbles: Nibbles) -> bytes:
    """Even-length nibble path → byte key."""
    if len(nibbles) % 2:
        raise ValueError("odd nibble path cannot pack to bytes")
    return bytes((nibbles[i] << 4) | nibbles[i + 1] for i in range(0, len(nibbles), 2))


def encode_path(nibbles: Nibbles, is_leaf: bool) -> bytes:
    """Hex-prefix encode a path for a leaf/extension node."""
    odd = len(nibbles) % 2
    flag = (2 if is_leaf else 0) + odd
    if odd:
        first = bytes([(flag << 4) | nibbles[0]])
        rest = nibbles[1:]
    else:
        first = bytes([flag << 4])
        rest = nibbles
    return first + pack_nibbles(rest)


def decode_path(encoded: bytes) -> tuple[Nibbles, bool]:
    """Hex-prefix decode → (nibbles, is_leaf)."""
    if not encoded:
        raise ValueError("empty hex-prefix path")
    flag = encoded[0] >> 4
    if flag > 3:
        raise ValueError(f"invalid hex-prefix flag nibble: {flag}")
    is_leaf = bool(flag & 2)
    nibs = unpack_nibbles(encoded)
    if flag & 1:  # odd: keep low nibble of first byte
        return nibs[1:], is_leaf
    if encoded[0] & 0x0F:
        raise ValueError("non-canonical hex-prefix: even path with nonzero pad nibble")
    return nibs[2:], is_leaf


def common_prefix_len(a: Nibbles, b: Nibbles) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n
