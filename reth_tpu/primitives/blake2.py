"""Blake2b compression function F — the EIP-152 precompile core.

Reference analogue: revm's blake2 precompile crate (consumed by the
reference through revm; precompile 0x09 since Istanbul). Only the raw
F function is exposed — the precompile calls it with an explicit round
count, so this is not a full blake2b hash.
"""

from __future__ import annotations

_MASK = (1 << 64) - 1

IV = (
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
)

SIGMA = (
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
    (11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4),
    (7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8),
    (9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13),
    (2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9),
    (12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11),
    (13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10),
    (6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5),
    (10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0),
)


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (64 - n))) & _MASK


def blake2f(rounds: int, h: list[int], m: list[int], t0: int, t1: int,
            final: bool) -> list[int]:
    """The F compression function: ``rounds`` rounds over state ``h``
    (8 u64) with message block ``m`` (16 u64) and offset counters."""
    v = list(h) + list(IV)
    v[12] ^= t0
    v[13] ^= t1
    if final:
        v[14] ^= _MASK

    def g(a, b, c, d, x, y):
        v[a] = (v[a] + v[b] + x) & _MASK
        v[d] = _rotr(v[d] ^ v[a], 32)
        v[c] = (v[c] + v[d]) & _MASK
        v[b] = _rotr(v[b] ^ v[c], 24)
        v[a] = (v[a] + v[b] + y) & _MASK
        v[d] = _rotr(v[d] ^ v[a], 16)
        v[c] = (v[c] + v[d]) & _MASK
        v[b] = _rotr(v[b] ^ v[c], 63)

    for r in range(rounds):
        s = SIGMA[r % 10]
        g(0, 4, 8, 12, m[s[0]], m[s[1]])
        g(1, 5, 9, 13, m[s[2]], m[s[3]])
        g(2, 6, 10, 14, m[s[4]], m[s[5]])
        g(3, 7, 11, 15, m[s[6]], m[s[7]])
        g(0, 5, 10, 15, m[s[8]], m[s[9]])
        g(1, 6, 11, 12, m[s[10]], m[s[11]])
        g(2, 7, 8, 13, m[s[12]], m[s[13]])
        g(3, 4, 9, 14, m[s[14]], m[s[15]])

    return [h[i] ^ v[i] ^ v[i + 8] for i in range(8)]
