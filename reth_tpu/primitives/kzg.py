"""KZG commitments over BLS12-381 — EIP-4844 point evaluation + blob ops.

Reference analogue: the c-kzg C library (reference Cargo.toml:597) behind
revm's point-evaluation precompile (0x0a) and the blob-sidecar validation
in the transaction pool.

Trusted setup: the mainnet KZG ceremony output is a data file the image
does not ship. The setup here is PLUGGABLE: ``load_trusted_setup(path)``
accepts the standard text format (`RETH_TPU_KZG_SETUP` env var at node
level), and absent one an INSECURE deterministic dev setup (known tau) is
generated — byte-compatible machinery, clearly unfit for mainnet, ideal
for tests which must produce and verify proofs end-to-end.
"""

from __future__ import annotations

import hashlib
import os
from functools import lru_cache

from .pairing import (
    BLS12_381,
    f2_neg,
    g1_group,
    g1_valid,
    g2_group,
    pairing_product_is_one,
)

BLS_MODULUS = BLS12_381.r
FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_BLOB = FIELD_ELEMENTS_PER_BLOB * 32
VERSIONED_HASH_VERSION_KZG = 0x01

# deterministic INSECURE dev tau (tests generate + verify with the same
# setup; mainnet requires the ceremony file via load_trusted_setup)
_DEV_TAU = int.from_bytes(hashlib.sha256(b"reth-tpu insecure dev kzg tau").digest(), "big") % BLS_MODULUS

_P = BLS12_381.p


class KzgError(ValueError):
    pass


# ---------------------------------------------------------------------------
# G1/G2 point (de)serialization — ZCash BLS12-381 compressed format
# ---------------------------------------------------------------------------


def _sqrt_fp(a: int) -> int | None:
    """Square root in Fp (p % 4 == 3)."""
    r = pow(a, (_P + 1) // 4, _P)
    return r if r * r % _P == a % _P else None


def g1_from_bytes(data: bytes):
    """48-byte compressed G1 -> affine point (or None for infinity).

    Raises KzgError for malformed encodings or off-curve/off-subgroup
    points (EIP-4844 requires full validation)."""
    if len(data) != 48:
        raise KzgError("G1 point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise KzgError("uncompressed G1 not supported")
    if flags & 0x40:
        if any(data[1:]) or flags != 0xC0:
            raise KzgError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= _P:
        raise KzgError("G1 x out of range")
    y = _sqrt_fp((x * x % _P * x + BLS12_381.b) % _P)
    if y is None:
        raise KzgError("G1 x not on curve")
    is_largest = y > (_P - 1) // 2
    if bool(flags & 0x20) != is_largest:
        y = _P - y
    pt = (x, y)
    if not g1_valid(pt, BLS12_381):
        raise KzgError("G1 point not in subgroup")
    return pt


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + b"\x00" * 47
    x, y = pt
    flags = 0x80 | (0x20 if y > (_P - 1) // 2 else 0)
    raw = x.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def _sqrt_fp2(a: tuple[int, int]) -> tuple[int, int] | None:
    """Square root in Fp2 = Fp(u), u^2 = -1, via the norm trick."""
    a0, a1 = a
    if a1 == 0:
        r = _sqrt_fp(a0)
        if r is not None:
            return (r, 0)
        # a0 = -(b1^2) => sqrt = b1 * u
        r = _sqrt_fp((-a0) % _P)
        return (0, r) if r is not None else None
    n = _sqrt_fp((a0 * a0 + a1 * a1) % _P)
    if n is None:
        return None
    for s in (n, (-n) % _P):
        t = (a0 + s) * pow(2, _P - 2, _P) % _P
        alpha = _sqrt_fp(t)
        if alpha is None or alpha == 0:
            continue
        beta = a1 * pow(2 * alpha, _P - 2, _P) % _P
        cand = (alpha, beta)
        from .pairing import f2_sqr

        if f2_sqr(cand, _P) == (a0 % _P, a1 % _P):
            return cand
    return None


def g2_from_bytes(data: bytes):
    """96-byte compressed G2 -> twist affine point (or None)."""
    if len(data) != 96:
        raise KzgError("G2 point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise KzgError("uncompressed G2 not supported")
    if flags & 0x40:
        if any(data[1:]) or flags != 0xC0:
            raise KzgError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")  # c1 first
    x0 = int.from_bytes(data[48:96], "big")
    if x1 >= _P or x0 >= _P:
        raise KzgError("G2 x out of range")
    x = (x0, x1)
    from .pairing import f2_add, f2_mul, f2_sqr

    rhs = f2_add(f2_mul(f2_sqr(x, _P), x, _P), g2_group(BLS12_381).b, _P)
    y = _sqrt_fp2(rhs)
    if y is None:
        raise KzgError("G2 x not on curve")
    # "largest" is lexicographic over (c1, c0)
    is_largest = (y[1] > (_P - 1) // 2) or (y[1] == 0 and y[0] > (_P - 1) // 2)
    if bool(flags & 0x20) != is_largest:
        y = f2_neg(y, _P)
    pt = (x, y)
    from .pairing import g2_valid

    if not g2_valid(pt, BLS12_381):
        raise KzgError("G2 point not in subgroup")
    return pt


# ---------------------------------------------------------------------------
# trusted setup
# ---------------------------------------------------------------------------


class TrustedSetup:
    """tau*G2 (verification) + monomial G1 powers (commit/prove paths)."""

    def __init__(self, tau_g2, g1_monomial: list):
        self.tau_g2 = tau_g2
        self.g1_monomial = g1_monomial  # [tau^i * G1]


@lru_cache(maxsize=1)
def dev_setup(n_g1: int = 64) -> TrustedSetup:
    """Deterministic INSECURE setup from a known tau (tests only)."""
    g1 = g1_group(BLS12_381)
    g2 = g2_group(BLS12_381)
    powers = []
    acc = 1
    for _ in range(n_g1):
        powers.append(g1.mul_scalar(BLS12_381.g1, acc))
        acc = acc * _DEV_TAU % BLS_MODULUS
    return TrustedSetup(g2.mul_scalar(BLS12_381.g2, _DEV_TAU), powers)


_active_setup: TrustedSetup | None = None


def load_trusted_setup(path: str) -> TrustedSetup:
    """Parse the standard trusted_setup.txt format: first line n_g1, second
    n_g2, then n_g1 hex G1 points (Lagrange), then n_g2 hex G2 points
    (monomial — index 1 is tau*G2)."""
    global _active_setup
    with open(path) as f:
        tokens = f.read().split()
    n1, n2 = int(tokens[0]), int(tokens[1])
    g1_pts = [g1_from_bytes(bytes.fromhex(t)) for t in tokens[2 : 2 + n1]]
    g2_pts = [g2_from_bytes(bytes.fromhex(t)) for t in tokens[2 + n1 : 2 + n1 + n2]]
    if len(g2_pts) < 2:
        raise KzgError("setup missing tau*G2")
    setup = TrustedSetup(g2_pts[1], g1_pts)
    _active_setup = setup
    return setup


def active_setup() -> TrustedSetup:
    global _active_setup
    if _active_setup is None:
        path = os.environ.get("RETH_TPU_KZG_SETUP")
        _active_setup = load_trusted_setup(path) if path else dev_setup()
    return _active_setup


# ---------------------------------------------------------------------------
# KZG verification / commitment
# ---------------------------------------------------------------------------


def verify_kzg_proof(commitment, z: int, y: int, proof) -> bool:
    """e(C - y*G1, G2) == e(proof, tau*G2 - z*G2) via one product check."""
    setup = active_setup()
    g1 = g1_group(BLS12_381)
    g2 = g2_group(BLS12_381)
    p_minus_y = g1.padd(commitment, g1.mul_scalar(BLS12_381.g1, (-y) % BLS_MODULUS))
    x_minus_z = g2.padd(setup.tau_g2, g2.mul_scalar(BLS12_381.g2, (-z) % BLS_MODULUS))
    neg_g2 = (BLS12_381.g2[0], f2_neg(BLS12_381.g2[1], _P))
    return pairing_product_is_one(
        [(p_minus_y, neg_g2), (proof, x_minus_z)], BLS12_381
    )


def commit_monomial(coeffs: list[int]) -> tuple:
    """Commitment to a polynomial given in monomial form (tests/blob ops)."""
    setup = active_setup()
    if len(coeffs) > len(setup.g1_monomial):
        raise KzgError("polynomial degree exceeds setup size")
    g1 = g1_group(BLS12_381)
    acc = None
    for c, pt in zip(coeffs, setup.g1_monomial):
        if c % BLS_MODULUS:
            acc = g1.padd(acc, g1.mul_scalar(pt, c % BLS_MODULUS))
    return acc


def prove_monomial(coeffs: list[int], z: int) -> tuple[int, tuple]:
    """(y, proof) for p(z) on a monomial-form polynomial: commit to the
    quotient q(X) = (p(X) - y) / (X - z) by synthetic division."""
    y = 0
    for c in reversed(coeffs):
        y = (y * z + c) % BLS_MODULUS
    # synthetic division of (p(X) - y) by (X - z)
    q = [0] * (len(coeffs) - 1)
    carry = 0
    for i in range(len(coeffs) - 1, 0, -1):
        carry = (coeffs[i] + carry * z) % BLS_MODULUS
        q[i - 1] = carry
    return y, commit_monomial(q)


def kzg_to_versioned_hash(commitment_bytes: bytes) -> bytes:
    return bytes([VERSIONED_HASH_VERSION_KZG]) + hashlib.sha256(commitment_bytes).digest()[1:]


# ---------------------------------------------------------------------------
# blob-level operations (EIP-4844 polynomial-in-evaluation-form)
# ---------------------------------------------------------------------------
#
# A blob is FIELD_ELEMENTS evaluations of a polynomial at the roots of
# unity in bit-reversal permutation order. The blob size tracks the active
# setup: the mainnet ceremony file gives 4096; the insecure dev setup
# commits to dev_blob_size()-element mini-blobs so tests can run the full
# commit/prove/verify cycle in pure Python.

_PRIMITIVE_ROOT = 7  # generator of the BLS scalar field's 2^32 subgroup


def _bit_reverse(n: int, bits: int) -> int:
    return int(bin(n)[2:].zfill(bits)[::-1], 2)


@lru_cache(maxsize=4)
def _roots_of_unity(n: int) -> tuple[int, ...]:
    """n-th roots of unity in BIT-REVERSAL order (the 4844 blob layout)."""
    root = pow(_PRIMITIVE_ROOT, (BLS_MODULUS - 1) // n, BLS_MODULUS)
    seq = []
    acc = 1
    for _ in range(n):
        seq.append(acc)
        acc = acc * root % BLS_MODULUS
    bits = n.bit_length() - 1
    return tuple(seq[_bit_reverse(i, bits)] for i in range(n))


def active_blob_size() -> int:
    """Field elements per blob for the ACTIVE setup (4096 on mainnet)."""
    n = len(active_setup().g1_monomial)
    # largest power of two the monomial setup can commit to
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def blob_to_fields(blob: bytes) -> list[int]:
    if len(blob) % 32:
        raise KzgError("blob length not a multiple of 32")
    fields = [int.from_bytes(blob[i : i + 32], "big") for i in range(0, len(blob), 32)]
    if any(f >= BLS_MODULUS for f in fields):
        raise KzgError("blob field element out of range")
    return fields


def _evals_to_coeffs(evals: list[int]) -> list[int]:
    """Inverse DFT over the bit-reversed roots (O(n^2): dev-sized blobs)."""
    n = len(evals)
    roots = _roots_of_unity(n)
    inv_n = pow(n, BLS_MODULUS - 2, BLS_MODULUS)
    coeffs = []
    for j in range(n):
        s = 0
        for i, e in enumerate(evals):
            s += e * pow(roots[i], (BLS_MODULUS - 1 - j) % (BLS_MODULUS - 1), BLS_MODULUS)
        coeffs.append(s % BLS_MODULUS * inv_n % BLS_MODULUS)
    return coeffs


def blob_to_kzg_commitment(blob: bytes) -> bytes:
    fields = blob_to_fields(blob)
    if len(fields) != active_blob_size():
        raise KzgError(
            f"blob must hold {active_blob_size()} field elements for this setup"
        )
    return g1_to_bytes(commit_monomial(_evals_to_coeffs(fields)))


def _evaluate_in_evaluation_form(fields: list[int], z: int) -> int:
    """p(z) via the barycentric formula (no coefficient conversion)."""
    n = len(fields)
    roots = _roots_of_unity(n)
    for i, w in enumerate(roots):
        if w == z % BLS_MODULUS:
            return fields[i]
    total = 0
    for i, w in enumerate(roots):
        total += fields[i] * w % BLS_MODULUS * pow(z - w, BLS_MODULUS - 2, BLS_MODULUS)
    zn = (pow(z, n, BLS_MODULUS) - 1) % BLS_MODULUS
    inv_n = pow(n, BLS_MODULUS - 2, BLS_MODULUS)
    return total % BLS_MODULUS * zn % BLS_MODULUS * inv_n % BLS_MODULUS


def compute_blob_kzg_proof(blob: bytes, commitment_bytes: bytes) -> bytes:
    """Proof of evaluation at the Fiat-Shamir challenge (spec scheme)."""
    fields = blob_to_fields(blob)
    z = _blob_challenge(blob, commitment_bytes)
    coeffs = _evals_to_coeffs(fields)
    _y, proof = prove_monomial(coeffs, z)
    return g1_to_bytes(proof)


def verify_blob_kzg_proof(blob: bytes, commitment_bytes: bytes,
                          proof_bytes: bytes) -> bool:
    try:
        fields = blob_to_fields(blob)
        commitment = g1_from_bytes(commitment_bytes)
        proof = g1_from_bytes(proof_bytes)
    except KzgError:
        return False
    if len(fields) != active_blob_size():
        return False
    z = _blob_challenge(blob, commitment_bytes)
    y = _evaluate_in_evaluation_form(fields, z)
    return verify_kzg_proof(commitment, z, y, proof)


def _blob_challenge(blob: bytes, commitment_bytes: bytes) -> int:
    """Fiat-Shamir evaluation point binding blob + commitment
    (consensus-specs compute_challenge: domain || degree as 16-byte
    BIG-endian || blob || commitment, hashed to a field element)."""
    n = len(blob) // 32
    data = b"FSBLOBVERIFY_V1_" + n.to_bytes(16, "big") + blob + commitment_bytes
    return int.from_bytes(hashlib.sha256(data).digest(), "big") % BLS_MODULUS
