"""Layer-0 primitives: hashing, RLP, nibbles, core chain types.

Reference analogue: the external alloy-primitives / alloy-rlp / alloy-trie /
reth-primitives-traits crates (reference Cargo.toml:324-448).
"""

from .keccak import keccak256, keccak256_batch_np
from .rlp import rlp_encode, rlp_decode, rlp_encode_list
from .nibbles import Nibbles, pack_nibbles, unpack_nibbles, encode_path
from .types import (
    Account,
    Header,
    Transaction,
    Receipt,
    Block,
    Withdrawal,
    EMPTY_ROOT_HASH,
    EMPTY_CODE_HASH,
    KECCAK_EMPTY,
)

__all__ = [
    "keccak256",
    "keccak256_batch_np",
    "rlp_encode",
    "rlp_decode",
    "rlp_encode_list",
    "Nibbles",
    "pack_nibbles",
    "unpack_nibbles",
    "encode_path",
    "Account",
    "Header",
    "Transaction",
    "Receipt",
    "Block",
    "Withdrawal",
    "EMPTY_ROOT_HASH",
    "EMPTY_CODE_HASH",
    "KECCAK_EMPTY",
]
