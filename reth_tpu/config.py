"""TOML node configuration (reth.toml analogue).

Reference analogue: crates/config — `reth.toml` with per-stage
thresholds (`StageConfig`/`MerkleConfig`, src/config.rs:22-537) and
prune settings. Read with stdlib tomllib; flags override file values.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from .prune import PruneMode, PruneModes

try:  # stdlib since 3.11; keep 3.10 importable (the mini parser below
    import tomllib  # covers this file's flat table/int/str/bool schema)
except ModuleNotFoundError:  # pragma: no cover - version-dependent
    tomllib = None


def _mini_toml(text: str) -> dict:
    """Fallback parser for the subset reth.toml actually uses: ``[a.b]``
    tables, int/float/bool/quoted-string values, ``#`` comments, and
    single-line inline tables (``k = { distance = 100 }``)."""

    def _value(raw: str):
        raw = raw.strip()
        if raw.startswith("{") and raw.endswith("}"):
            out = {}
            body = raw[1:-1].strip()
            for part in filter(None, (p.strip() for p in body.split(","))):
                k, _, v = part.partition("=")
                out[k.strip()] = _value(v)
            return out
        if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "\"'":
            return raw[1:-1]
        if raw in ("true", "false"):
            return raw == "true"
        try:
            return int(raw)
        except ValueError:
            return float(raw)

    root: dict = {}
    table = root
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            table = root
            for part in line[1:-1].split("."):
                table = table.setdefault(part.strip(), {})
            continue
        key, sep, raw = line.partition("=")
        if not sep:
            raise ValueError(f"unparseable TOML line: {line!r}")
        table[key.strip()] = _value(raw)
    return root


def _parse_toml(text: str) -> dict:
    if tomllib is not None:
        return tomllib.loads(text)
    return _mini_toml(text)


@dataclass
class MerkleConfig:
    # reference: rebuild_threshold=100_000, incremental_threshold=7_000
    rebuild_threshold: int = 50_000
    incremental_threshold: int = 7_000


@dataclass
class HashingConfig:
    clean_threshold: int = 100_000


@dataclass
class ExecutionConfig:
    max_blocks_per_commit: int = 1000


@dataclass
class StageConfig:
    merkle: MerkleConfig = field(default_factory=MerkleConfig)
    account_hashing: HashingConfig = field(default_factory=HashingConfig)
    storage_hashing: HashingConfig = field(default_factory=HashingConfig)
    execution: ExecutionConfig = field(default_factory=ExecutionConfig)


@dataclass
class RpcConfig:
    # route every transport's dispatch through the serving gateway
    # (rpc/gateway.py): admission control with priority classes,
    # in-flight coalescing of identical reads, and a head-invalidated
    # response cache (--rpc-gateway CLI equivalent)
    gateway: bool = False
    # response-cache capacity in entries (0 disables the cache while
    # keeping admission + coalescing on)
    gateway_cache: int = 1024


@dataclass
class RethTpuConfig:
    stages: StageConfig = field(default_factory=StageConfig)
    prune: PruneModes = field(default_factory=PruneModes)
    rpc: RpcConfig = field(default_factory=RpcConfig)
    persistence_threshold: int = 2
    hasher: str = "device"  # device | cpu | auto (supervised device)
    # multiplex every keccak client over the shared background hash
    # service (ops/hash_service.py): priority lanes + continuous batching
    hash_service: bool = False
    # device mesh width (--mesh CLI / RETH_TPU_MESH env equivalent): the
    # hash service + turbo committers then shard coalesced dispatches and
    # fused level windows over this many devices (parallel/mesh.py),
    # with sub-mesh rebuild leases and per-device circuit breakers.
    # 0/1 = single-device (the mesh layer stays off)
    mesh_devices: int = 0
    # device warm-up manager (--warmup CLI equivalent, ops/warmup.py):
    # "off" | "background" (serve degraded on the CPU twin while the shape
    # menu AOT-compiles, promoting shapes as they warm) | "block" (finish
    # warm-up before serving)
    warmup: str = "off"
    # persistent XLA compilation cache directory for warm-up (versioned by
    # kernel-source digest, probe-verified before enabling; corrupt entries
    # quarantined + rebuilt). Empty = <datadir>/compile-cache when warm-up
    # is on (--compile-cache-dir CLI equivalent)
    compile_cache_dir: str = ""
    # parallel sparse commit: width of the live-tip finish path's RLP
    # encode pool AND the proof-worker pool (trie/sparse.py +
    # trie/proof.py). 0 = auto (env RETH_TPU_SPARSE_WORKERS or
    # cpu-derived); 1 = pools off, cross-trie packed dispatch stays on
    sparse_workers: int = 0
    # whole-subtrie fused kernels (--subtrie-levels CLI / env
    # RETH_TPU_SUBTRIE_LEVELS): k > 1 collapses the committers' per-depth
    # device dispatch loop into ONE dispatch per k packed levels
    # (ops/fused_commit.SubtrieFusedEngine — the depth loop runs inside
    # the jitted program, digest buffer as the carry). 0/1 = per-level
    subtrie_levels: int = 0
    # optimistic parallel EVM execution on the no-BAL newPayload path
    # (--parallel-exec CLI equivalent): Block-STM-style speculation with
    # read/write-set validation, async storage prefetch, and serial
    # fallback (engine/optimistic.py). Speculation width comes from
    # RETH_TPU_EXEC_WORKERS (default cpu-derived).
    parallel_exec: bool = False
    # cross-block import pipeline depth (--pipeline-depth CLI
    # equivalent, engine/block_pipeline.py): 2 = execute block N+1 over
    # N's frozen commit window while N's fused root dispatches run;
    # 1 = strictly serial imports. Env RETH_TPU_PIPELINE_DEPTH is the
    # fallback when unset.
    pipeline_depth: int = 1
    # standing block producer (--continuous-build CLI equivalent,
    # payload/producer.py): hot candidate payload incrementally
    # refreshed on pool events and head changes; getPayload / dev
    # mining seal it instead of building from scratch
    continuous_build: bool = False
    # hot-state plane (--hot-state CLI equivalent, trie/hot_cache.py):
    # cross-block trie-node cache feeding sparse reveals without proof
    # fetches + device-resident digest arena with delta uploads
    # (ops/fused_commit.py); env RETH_TPU_HOT_STATE is the fallback
    hot_state: bool = False
    # block-lifecycle tracing (--trace-blocks CLI equivalent): record
    # per-block span timelines, export Chrome-trace JSON under the
    # datadir, and point flight-recorder dumps there (tracing.py)
    trace_blocks: bool = False
    # node health & SLO engine (--health CLI equivalent, health.py):
    # metric time-series retention + burn-rate SLO evaluation over the
    # default rule table, served at /health and the debug health RPCs
    health: bool = False
    # seconds between health sampler/evaluator passes (<= 0 disables the
    # background thread; also RETH_TPU_SLO_INTERVAL)
    slo_interval: float = 1.0
    # ring-buffer samples retained per metric series (5 min at the
    # default 1 Hz; also RETH_TPU_SLO_WINDOW)
    slo_window: int = 300
    # write-ahead log for the memdb-backed stores (--wal CLI equivalent,
    # storage/wal.py): fsync'd per-commit records + checkpoint manifest,
    # so a kill -9 loses at most persistence_threshold blocks
    wal: bool = True
    # persisted blocks between WAL checkpoints (image + manifest swap +
    # log truncation; --wal-checkpoint-blocks CLI equivalent)
    wal_checkpoint_blocks: int = 8
    # verify the recovered head's state root by recomputation through
    # the committer at startup (--no-recovery-verify opts out)
    recovery_verify_root: bool = True
    # bound of the engine tree's invalid-header LRU (--invalid-cache-size
    # CLI / RETH_TPU_INVALID_CACHE env): an invalid-payload flood
    # plateaus at this many cached rejections instead of leaking memory
    invalid_cache_size: int = 512
    # read-replica fleet mode (--fleet CLI equivalent, fleet/): witness
    # feed server + consistent-hash gateway ring over registered
    # stateless replicas, with health-driven per-replica draining
    fleet: bool = False
    # witness feed TCP port (--feed-port; 0 = ephemeral)
    feed_port: int = 0
    # heads a replica may trail the node's head before the ring sheds
    # it (--fleet-max-lag)
    fleet_max_lag: int = 4


def _prune_mode(d: dict) -> PruneMode:
    return PruneMode(distance=d.get("distance"), before=d.get("before"))


def load_config(path: str | Path | None) -> RethTpuConfig:
    cfg = RethTpuConfig()
    if path is None or not Path(path).exists():
        return cfg
    raw = _parse_toml(Path(path).read_text())
    stages = raw.get("stages", {})
    if "merkle" in stages:
        cfg.stages.merkle = MerkleConfig(**stages["merkle"])
    if "account_hashing" in stages:
        cfg.stages.account_hashing = HashingConfig(**stages["account_hashing"])
    if "storage_hashing" in stages:
        cfg.stages.storage_hashing = HashingConfig(**stages["storage_hashing"])
    if "execution" in stages:
        cfg.stages.execution = ExecutionConfig(**stages["execution"])
    prune = raw.get("prune", {})
    for seg in ("sender_recovery", "receipts", "transaction_lookup",
                "account_history", "storage_history"):
        if seg in prune:
            setattr(cfg.prune, seg, _prune_mode(prune[seg]))
    node = raw.get("node", {})
    cfg.persistence_threshold = node.get("persistence_threshold", cfg.persistence_threshold)
    cfg.hasher = node.get("hasher", cfg.hasher)
    cfg.hash_service = bool(node.get("hash_service", cfg.hash_service))
    cfg.mesh_devices = int(node.get("mesh_devices", cfg.mesh_devices))
    cfg.warmup = str(node.get("warmup", cfg.warmup))
    cfg.compile_cache_dir = str(node.get("compile_cache_dir",
                                         cfg.compile_cache_dir))
    cfg.sparse_workers = int(node.get("sparse_workers", cfg.sparse_workers))
    cfg.subtrie_levels = int(node.get("subtrie_levels", cfg.subtrie_levels))
    cfg.parallel_exec = bool(node.get("parallel_exec", cfg.parallel_exec))
    cfg.pipeline_depth = int(node.get("pipeline_depth", cfg.pipeline_depth))
    cfg.continuous_build = bool(node.get("continuous_build",
                                         cfg.continuous_build))
    cfg.hot_state = bool(node.get("hot_state", cfg.hot_state))
    cfg.trace_blocks = bool(node.get("trace_blocks", cfg.trace_blocks))
    cfg.health = bool(node.get("health", cfg.health))
    cfg.slo_interval = float(node.get("slo_interval", cfg.slo_interval))
    cfg.slo_window = int(node.get("slo_window", cfg.slo_window))
    cfg.wal = bool(node.get("wal", cfg.wal))
    cfg.wal_checkpoint_blocks = int(node.get("wal_checkpoint_blocks",
                                             cfg.wal_checkpoint_blocks))
    cfg.recovery_verify_root = bool(node.get("recovery_verify_root",
                                             cfg.recovery_verify_root))
    cfg.invalid_cache_size = int(node.get("invalid_cache_size",
                                          cfg.invalid_cache_size))
    cfg.fleet = bool(node.get("fleet", cfg.fleet))
    cfg.feed_port = int(node.get("feed_port", cfg.feed_port))
    cfg.fleet_max_lag = int(node.get("fleet_max_lag", cfg.fleet_max_lag))
    rpc = raw.get("rpc", {})
    cfg.rpc.gateway = bool(rpc.get("gateway", cfg.rpc.gateway))
    cfg.rpc.gateway_cache = int(rpc.get("gateway_cache", cfg.rpc.gateway_cache))
    return cfg
