"""TOML node configuration (reth.toml analogue).

Reference analogue: crates/config — `reth.toml` with per-stage
thresholds (`StageConfig`/`MerkleConfig`, src/config.rs:22-537) and
prune settings. Read with stdlib tomllib; flags override file values.
"""

from __future__ import annotations

import tomllib
from dataclasses import dataclass, field
from pathlib import Path

from .prune import PruneMode, PruneModes


@dataclass
class MerkleConfig:
    # reference: rebuild_threshold=100_000, incremental_threshold=7_000
    rebuild_threshold: int = 50_000
    incremental_threshold: int = 7_000


@dataclass
class HashingConfig:
    clean_threshold: int = 100_000


@dataclass
class ExecutionConfig:
    max_blocks_per_commit: int = 1000


@dataclass
class StageConfig:
    merkle: MerkleConfig = field(default_factory=MerkleConfig)
    account_hashing: HashingConfig = field(default_factory=HashingConfig)
    storage_hashing: HashingConfig = field(default_factory=HashingConfig)
    execution: ExecutionConfig = field(default_factory=ExecutionConfig)


@dataclass
class RethTpuConfig:
    stages: StageConfig = field(default_factory=StageConfig)
    prune: PruneModes = field(default_factory=PruneModes)
    persistence_threshold: int = 2
    hasher: str = "device"  # device | cpu | auto (supervised device)


def _prune_mode(d: dict) -> PruneMode:
    return PruneMode(distance=d.get("distance"), before=d.get("before"))


def load_config(path: str | Path | None) -> RethTpuConfig:
    cfg = RethTpuConfig()
    if path is None or not Path(path).exists():
        return cfg
    raw = tomllib.loads(Path(path).read_text())
    stages = raw.get("stages", {})
    if "merkle" in stages:
        cfg.stages.merkle = MerkleConfig(**stages["merkle"])
    if "account_hashing" in stages:
        cfg.stages.account_hashing = HashingConfig(**stages["account_hashing"])
    if "storage_hashing" in stages:
        cfg.stages.storage_hashing = HashingConfig(**stages["storage_hashing"])
    if "execution" in stages:
        cfg.stages.execution = ExecutionConfig(**stages["execution"])
    prune = raw.get("prune", {})
    for seg in ("sender_recovery", "receipts", "transaction_lookup",
                "account_history", "storage_history"):
        if seg in prune:
            setattr(cfg.prune, seg, _prune_mode(prune[seg]))
    node = raw.get("node", {})
    cfg.persistence_threshold = node.get("persistence_threshold", cfg.persistence_threshold)
    cfg.hasher = node.get("hasher", cfg.hasher)
    return cfg
