"""Human log dashboard of node progress events.

Reference analogue: crates/node/events/src/node.rs — the periodic
"Status" / "Block added" INFO lines operators actually read: canonical
tip, throughput since the last report, txpool depth, peer count, and
stage progress during sync. Events arrive over an `EventSender` broadcast
(events.py); a reporter thread coalesces them into one line per interval
instead of one per block.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..events import EventSender
from ..tracing import tracer

log = tracer("node::events")


@dataclass
class CanonUpdate:
    number: int
    hash: bytes
    txs: int
    gas_used: int


class NodeEventReporter:
    """Coalescing progress reporter over the node's event stream."""

    def __init__(self, node, interval: float = 10.0):
        self.node = node
        self.interval = interval
        self.sender = EventSender()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # window accumulators
        self._lock = threading.Lock()
        self._blocks = 0
        self._txs = 0
        self._gas = 0
        self._tip: CanonUpdate | None = None

    # -- event intake ---------------------------------------------------------

    def on_canon_change(self, chain) -> None:
        """Installed as an engine canon listener."""
        if not chain:
            return
        tip = chain[-1].block
        up = CanonUpdate(tip.header.number, tip.header.hash,
                         len(tip.transactions), tip.header.gas_used)
        with self._lock:
            self._blocks += len(chain)
            self._txs += sum(len(eb.block.transactions) for eb in chain)
            self._gas += sum(eb.block.header.gas_used for eb in chain)
            self._tip = up
        self.sender.notify(up)

    # -- reporting ------------------------------------------------------------

    def _snapshot(self):
        with self._lock:
            out = (self._blocks, self._txs, self._gas, self._tip)
            self._blocks = self._txs = self._gas = 0
            self._tip = None
            return out

    def report_once(self) -> str | None:
        blocks, txs, gas, tip = self._snapshot()
        if tip is None:
            return None
        pool = getattr(self.node, "pool", None)
        net = getattr(self.node, "network", None)
        pool_n = len(pool) if pool is not None else 0
        peer_n = len(net.peers) if net is not None else 0
        mgas = gas / 1e6
        line = (f"Canonical chain advanced  number={tip.number} "
                f"hash=0x{tip.hash.hex()[:16]}… blocks={blocks} txs={txs} "
                f"mgas={mgas:.2f} pool={pool_n} peers={peer_n}")
        # --hasher auto: the supervisor's breaker state belongs on the one
        # line operators read — a degraded (CPU-routed) hasher is exactly
        # the "node is slow, why?" answer
        sup = getattr(self.node, "hasher_supervisor", None)
        if sup is not None:
            s = sup.snapshot()
            line += (f" hasher={'cpu' if s['breaker'] != 'closed' else 'device'}"
                     f" breaker={s['breaker']}")
            if s["trips"] or s["failovers"]:
                line += f" trips={s['trips']} failovers={s['failovers']}"
        # --warmup: the compile lifecycle's one-line health — menu
        # progress, whether restarts hit the persistent cache, and how
        # much serving is still degraded onto the CPU twin ("the node is
        # slow right after start, why?" answer)
        wu = getattr(self.node, "warmup", None)
        if wu is not None:
            w = wu.snapshot()
            line += (f" warmup[{w['state']} {w['warm']}/{w['total']}"
                     f" cache={w['cache']['mode']}")
            if w["cache_hits"]:
                line += f" hits={w['cache_hits']}"
            if w["failed"]:
                line += f" failed={w['failed']}"
            if w["cpu_routed"]:
                line += f" cpu_routed={w['cpu_routed']}"
            line += f" wall={w['compile_wall_s']}s]"
        # --hash-service: the shared service's one-line health — queue
        # pressure, whether small batches actually fuse (cf = coalesce
        # factor), and the failure-path counters an operator pages on
        svc = getattr(self.node, "hash_service", None)
        if svc is not None:
            s = svc.snapshot()
            line += (f" hashsvc[q={s['queued_total']}"
                     f" cf={s['coalesce_factor']}"
                     f" disp={s['dispatches']}]")
            if s["replays"] or s["rejects"] or s["lease_bypasses"]:
                line += (f" svc_replays={s['replays']}"
                         f" svc_rejects={s['rejects']}"
                         f" svc_bypass={s['lease_bypasses']}")
            if s["leased_by"]:
                line += f" svc_leased={s['leased_by']}"
        # --mesh: the device mesh's one-line health — live/total devices,
        # whether a rebuild currently holds a sub-mesh lease, and the
        # degradation counters (devices shed by per-device breakers,
        # shrunken-mesh replays) an operator pages on
        hm = getattr(self.node, "hash_mesh", None)
        if hm is not None:
            m = hm.snapshot()
            line += f" mesh[{m['healthy']}/{m['total']}"
            if m["leased"]:
                line += f" leased={m['leased']}"
            if m["unhealthy"]:
                line += f" shed={m['unhealthy']}"
            svc_m = (svc.snapshot().get("mesh") if svc is not None else None)
            if svc_m is not None:
                line += (f" sharded={svc_m['sharded_dispatches']}"
                         f" single={svc_m['single_dispatches']}")
                if svc_m["mesh_replays"]:
                    line += f" replays={svc_m['mesh_replays']}"
            line += "]"
        # --rpc-gateway: the serving gateway's one-line health — queue
        # pressure per admission domain, whether duplicate reads actually
        # share work (cf = coalesce factor), cache effectiveness, and the
        # shed counter an operator pages on
        gw = getattr(self.node, "gateway", None)
        if gw is not None:
            g = gw.snapshot()
            line += (f" gateway[req={g['requests']}"
                     f" q={g['waiting_total']}"
                     f" cf={g['coalesce_factor']}"
                     f" hit={g['cache_hit_rate']}]")
            if g["sheds"]:
                line += f" gw_sheds={g['sheds']}"
        # --fleet: the replica fleet's one-line health — ring membership
        # (healthy/registered), worst feed lag, how many reads actually
        # landed on replicas vs failed over or fell back to this node,
        # and the feed's fanout state (subscribers, witness bytes/block)
        # — the numbers that say the fleet is absorbing read traffic
        fr = getattr(self.node, "fleet_router", None)
        if fr is not None:
            f = fr.snapshot()
            line += (f" fleet[{f['healthy']}/{f['registered']}"
                     f" routed={f['routed']}")
            if f["max_lag"]:
                line += f" lag^={f['max_lag']}"
            if f["failovers"]:
                line += f" fo={f['failovers']}"
            if f["local_fallbacks"]:
                line += f" local={f['local_fallbacks']}"
            if f["sheds"]:
                line += f" sheds={f['sheds']}"
            fs = getattr(self.node, "feed_server", None)
            if fs is not None:
                s = fs.snapshot()
                line += (f" feed={s['subscribers']}sub"
                         f"/{s['blocks_sent']}blk")
                if s["last_witness_bytes"]:
                    line += f" wit={s['last_witness_bytes']}B"
                if s["witness_failures"]:
                    line += f" witfail={s['witness_failures']}"
            line += "]"
        # --fleet: the observability plane's one-line health — how many
        # replicas the metrics federation is actually pulling (stale =
        # the fleet view is partially blind), pull cadence/failures, and
        # correlated flight-dump fan-outs — the numbers that say the
        # fleet is OBSERVABLE, not just serving
        fed = getattr(self.node, "fleet_federation", None)
        if fed is not None:
            fo = fed.snapshot()
            line += (f" fleetobs[{fo['replicas'] - fo['stale']}"
                     f"/{fo['replicas']} pulls={fo['pulls']}")
            if fo["stale"]:
                line += f" stale={fo['stale']}"
            if fo["failures"]:
                line += f" fail={fo['failures']}"
            fs = getattr(self.node, "feed_server", None)
            if fs is not None and fs.flight_fanouts:
                line += f" dumps={fs.flight_fanouts}"
            line += "]"
        # HA: this leader's durable-stream shipping + fencing state —
        # epoch lineage, how many standbys ride the WAL stream, records
        # shipped vs dropped (a standby too slow for the ship queue),
        # and whether this node is fenced (superseded by a promotion)
        fs = getattr(self.node, "feed_server", None)
        if fs is not None:
            s = fs.snapshot()
            if s.get("wal_subscribers") or s.get("st_records_sent") \
                    or getattr(self.node.tree, "fenced", False):
                line += (f" ha[epoch={s['epoch']}"
                         f" standbys={s['wal_subscribers']}"
                         f" shipped={s['st_records_sent']}")
                if s.get("st_dropped"):
                    line += f" dropped={s['st_dropped']}"
                if s.get("resyncs_sent"):
                    line += f" resyncs={s['resyncs_sent']}"
                if s.get("partition_suppressed"):
                    line += f" part={s['partition_suppressed']}"
                if getattr(self.node.tree, "fenced", False):
                    line += " FENCED"
                line += "]"
        # rebuild-pipeline stage walls: during a chunked Merkle rebuild this
        # is the line that says where the time goes (host sweep vs hashing)
        from ..metrics import pipeline_metrics

        pm = pipeline_metrics.last
        if pm is not None:
            line += (f" rebuild[win={pm['windows']} q^={pm['queue_peak']}"
                     f" sweep={pm['sweep_s']}s pack={pm['pack_s']}s"
                     f" disp={pm['dispatch_s']}s fetch={pm['fetch_s']}s]")
            if pm["drained_windows"]:
                line += f" drained={pm['drained_windows']}"
        # whole-subtrie fused commits: the k-level engine's one-line
        # health — configured k, device dispatches the last commit
        # actually issued for how many staged levels, and which rung
        # produced the digests (fused / perlevel / cpu). A mode other
        # than "fused" — or disp creeping toward lv — is the dispatch-
        # count regression the fused SLO rule pages on.
        from ..metrics import fused_metrics

        fm = fused_metrics.last
        if fm is not None:
            line += (f" fused[k={fm['k']} disp={fm['dispatches']}"
                     f" lv={fm['levels']}")
            if fm["mode"] != "fused":
                line += f" {fm['mode'].upper()}"
            line += "]"
        # parallel sparse commit: the live-tip finish path's one-line
        # health — how many depth levels packed across tries, fused
        # dispatches per block, encode-chunk fan-out, and the finish wall
        from ..metrics import sparse_commit_metrics

        sc = sparse_commit_metrics.last
        if sc is not None:
            line += (f" sparse[tries={sc.get('tries', 0)}"
                     f" lv={sc.get('levels', 0)}"
                     f" disp={sc.get('dispatches', 0)}"
                     f" enc={sc.get('encode_chunks', 0)}")
            if sc.get("streamed"):
                line += f" strm={sc['streamed']}"
            if "finish_s" in sc:
                line += f" fin={sc['finish_s']}s"
            line += "]"
        # parallel execution: the last block's scheduling efficiency —
        # optimistic (engine/optimistic.py: native/python rank split,
        # speculative commits vs serial re-runs, rounds, prefetched keys)
        # or BAL wave stats (engine/bal.py) — so BAL-hinted vs optimistic
        # scheduling is comparable on the one line operators read
        from ..metrics import exec_metrics

        ex = exec_metrics.last
        if ex is not None:
            line += (f" exec[opt r={ex.get('rounds', 0)}"
                     f" nat={ex.get('native', 0)}"
                     f" py={ex.get('python', 0)}"
                     f" spec={ex.get('speculative', 0)}"
                     f" conf={ex.get('conflicts', 0)}"
                     f" pre={ex.get('prefetched', 0)}"
                     f" w={ex.get('workers', 0)}")
            if ex.get("fallback"):
                line += " FALLBACK"
            if "wall_s" in ex:
                line += f" {ex['wall_s']}s"
            line += "]"
        eb = exec_metrics.last_bal
        if eb is not None:
            line += (f" exec[bal waves={eb.get('waves', 0)}"
                     f" par={eb.get('parallel', 0)}"
                     f" ser={eb.get('serial', 0)}"
                     f" nat={eb.get('native', 0)}]")
        # consensus robustness: the engine tree's one-line adversarial
        # health — invalid-cache occupancy vs its bound (a flood must
        # plateau), orphan-buffer depth, reorg cadence/depth, storm
        # detections with their backoff, and inserts cancelled by a
        # reorging forkchoice — the numbers that say a hostile CL is
        # being absorbed instead of hurting the node
        from ..metrics import tree_metrics

        tm = tree_metrics.last
        if tm and (tm.get("invalid") or tm.get("orphans")
                   or tm.get("reorgs") or tm.get("cancelled")):
            line += (f" tree[inv={tm.get('invalid', 0)}"
                     f"/{tm.get('invalid_cap', 0)}"
                     f" orph={tm.get('orphans', 0)}"
                     f" reorgs={tm.get('reorgs', 0)}")
            if tm.get("max_depth"):
                line += f" depth^={tm['max_depth']}"
            if tm.get("storms"):
                line += f" storms={tm['storms']}"
            if tm.get("cancelled"):
                line += f" cancelled={tm['cancelled']}"
            if tm.get("backoff"):
                line += " BACKOFF"
            line += "]"
        # cross-block import pipeline: speculations started/adopted/
        # aborted, the measured exec-inside-commit overlap fraction, and
        # the last abort-ladder rung — the one-line answer to "is
        # back-to-back import actually overlapping exec with commit"
        from ..metrics import block_pipeline_metrics

        bp = block_pipeline_metrics.last
        if bp and bp.get("spec"):
            line += (f" pipe[d={bp.get('depth', 2)}"
                     f" spec={bp.get('spec', 0)}"
                     f" adopt={bp.get('adopted', 0)}"
                     f" abort={bp.get('aborted', 0)}")
            if "overlap" in bp:
                line += f" ovl={bp['overlap']:.2f}"
            if bp.get("last_abort"):
                line += f" last={bp['last_abort']}"
            if bp.get("lease_devices"):
                line += f" lease={bp['lease_devices']}d"
            line += "]"
        # write-path firehose: pool admissions/replacements/drops since
        # start, -32005 sheds, and pt_* records shipped to the fleet —
        # the one-line answer to "is the firehose being absorbed"
        from ..metrics import pool_metrics, producer_metrics

        pl = pool_metrics.last
        if pl:
            line += (f" pool[add={pl.get('add', 0)}"
                     f" repl={pl.get('replace', 0)}"
                     f" drop={pl.get('drop', 0)}")
            if pl.get("sheds"):
                line += f" shed={pl['sheds']}"
            if pl.get("shipped"):
                line += f" ship={pl['shipped']}"
            line += "]"
        # continuous producer: candidate size, incremental economy
        # (fresh-executed vs replayed ranks), refresh cadence, staleness
        pr = producer_metrics.last
        if pr and pr.get("refreshes"):
            line += (f" build[ranks={pr.get('ranks', 0)}"
                     f" fresh={pr.get('fresh', 0)}"
                     f" re={pr.get('reexec', 0)}"
                     f" refr={pr.get('refreshes', 0)}")
            if pr.get("staleness_s", 0) > 0.5:
                line += f" stale={pr['staleness_s']:.1f}s"
            line += "]"
        # hot-state plane (--hot-state): node-cache hit rate, resident
        # arena rows, last delta-upload fraction, and the validation
        # catches (stale/poison) — the one-line answer to "is the
        # cross-block cache actually absorbing proof fetches"
        from ..metrics import hotstate_metrics

        hs = hotstate_metrics.last
        if hs:
            line += f" hot[hit={hs.get('hit_rate', 0.0):.2f}"
            ar = hs.get("arena")
            if ar:
                line += f" rows={ar.get('resident_rows', 0)}"
            if "delta_fraction" in hs:
                line += f" dfrac={hs['delta_fraction']:.2f}"
            c = hs.get("cache") or {}
            caught = c.get("stale_drops", 0) + c.get("poison_caught", 0)
            if caught:
                line += f" caught={caught}"
            if ar and ar.get("faults"):
                line += f" faults={ar['faults']}"
            line += "]"
        # --health: the SLO engine's verdict — node status, any non-ok
        # component, and the breach counter an operator pages on. The
        # one line that says "the node itself thinks it is sick" instead
        # of leaving the judgment to whoever reads the fragments above.
        from .. import health as health_mod

        eng = (getattr(self.node, "health", None)
               or health_mod.get_engine())
        if eng is not None:
            comps = eng.components()
            bad = [f"{c}:{s}" for c, s in sorted(comps.items())
                   if s != "ok"]
            line += f" slo[{eng.status()}"
            if bad:
                line += " " + ",".join(bad)
            if eng.breaches_total:
                line += f" breaches={eng.breaches_total}"
            line += "]"
        # --wal: the durability boundary's one-line health — generation,
        # fsync'd appends since start, checkpoints taken, live segment
        # size — the numbers that say what a kill -9 right now would
        # cost; plus the last startup recovery's verdict (replayed
        # records, torn tail discarded, quarantines, root proof)
        dur = getattr(self.node, "durability", None)
        if dur is not None:
            d = dur.snapshot()
            line += (f" wal[gen={d['gen']} app={d['appends']}"
                     f" ckpt={d['checkpoints']}"
                     f" seg={d['segment_bytes']}B]")
        rec = getattr(self.node, "recovery", None)
        if rec is not None and (rec.get("replayed_records")
                                or rec.get("status") != "ok"
                                or rec.get("healed")):
            line += (f" recovery[{rec['status']}"
                     f" replayed={rec.get('replayed_records', 0)}")
            if rec.get("torn_bytes"):
                line += f" torn={rec['torn_bytes']}B"
            if rec.get("quarantined"):
                line += f" quarantined={len(rec['quarantined'])}"
            if rec.get("root_verified") is not None:
                line += (" root=ok" if rec["root_verified"]
                         else " root=MISMATCH")
            line += "]"
        # --trace-blocks: the per-block wall budget — where the last
        # block's time actually went, split by phase and by hash-service
        # queue-wait vs device dispatch (tracing.py block summaries)
        from .. import tracing

        budget = tracing.last_block_summary()
        if budget is not None:
            line += " | " + tracing.format_wall_budget(budget)
        log.info(line)
        return line

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.report_once()
            except Exception:  # noqa: BLE001 — reporting must never kill the node
                pass

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="node-events")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.sender.close()
        if self._thread is not None:
            self._thread.join(timeout=2)
