"""Node assembly: wire storage, engine, pool, payload, RPC into one node.

Reference analogue: crates/node/builder — the typestate `NodeBuilder` →
components → add-ons → `EngineNodeLauncher::launch_node`
(src/launch/engine.rs:70), trimmed to the components that exist.
"""

from .node import Node, NodeConfig

__all__ = ["Node", "NodeConfig"]
