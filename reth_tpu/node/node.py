"""The launched node: components + RPC servers + dev miner.

Reference analogue: `EngineNodeLauncher::launch_node`
(crates/node/builder/src/launch/engine.rs:70-419): provider factory →
genesis → components (pool, payload, consensus, executor) → add-ons
(RPC modules, engine API) → launched handle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from ..consensus import EthBeaconConsensus
from ..engine import EngineTree
from ..engine.local import LocalMiner
from ..evm import EvmConfig
from ..payload import PayloadBuilderService
from ..pool import TransactionPool
from ..primitives.types import Account, Header
from ..rpc import EngineApi, EthApi, RpcServer
from ..rpc.net import NetApi, TxpoolApi, Web3Api
from ..storage import ProviderFactory
from ..storage.genesis import init_genesis
from ..trie.committer import TrieCommitter


@dataclass
class NodeConfig:
    chain_id: int = 1
    datadir: str | Path | None = None
    dev: bool = False                 # dev mode: local miner enabled
    http_port: int = 0                # 0 = ephemeral
    authrpc_port: int = 0
    persistence_threshold: int = 2
    genesis_header: Header | None = None
    genesis_alloc: dict[bytes, Account] = field(default_factory=dict)
    genesis_storage: dict | None = None
    genesis_codes: dict | None = None
    # data lifecycle: move finalized history to static files once the chain
    # is this many blocks past it (None disables), and prune per modes
    static_file_distance: int | None = None
    prune_modes: object | None = None  # PruneModes | None
    jwt_secret: bytes | None = None   # engine-port JWT (auto from datadir)
    chain_spec: object | None = None  # ChainSpec: hardfork schedule + fork ids
    # memdb | native (C++ WAL) | paged (COW B+tree, the default — the MDBX
    # analogue, reference StorageSettings). An ephemeral node (datadir None)
    # silently runs memdb: the persistent engines need a directory.
    db_backend: str = "paged"
    # storage-v2 split layout (history/lookup tables on a dedicated second
    # store — reference StorageSettings.storage_v2). None = keep the
    # datadir's persisted layout (default v1 for fresh datadirs)
    storage_v2: bool | None = None
    ws_port: int | None = None        # WebSocket RPC (None disables; 0 = any)
    ipc_path: str | None = None       # Unix-socket RPC (None disables)
    enable_admin: bool = False        # admin_ is node control: explicit opt-in
    # devp2p: RLPx listener + discv4 discovery (None disables networking)
    p2p_port: int | None = None       # 0 = ephemeral
    p2p_host: str = "127.0.0.1"       # bind + advertised address
    nat: str = "any"                  # any | none | extip:<ip> | upnp | natpmp
    discovery: bool = True
    node_key: int | None = None       # secp256k1 priv; random when unset
    bootnodes: tuple[str, ...] = ()   # enode:// urls
    bootnodes_v5: tuple[str, ...] = ()  # enr:... text records (discv5/DNS)
    # --sparse-workers / [node] sparse_workers: parallel sparse-commit
    # pool width (None = env RETH_TPU_SPARSE_WORKERS or cpu-derived)
    sparse_workers: int | None = None
    # --parallel-exec / [node] parallel_exec: optimistic parallel EVM
    # execution on the no-BAL newPayload path (engine/optimistic.py);
    # speculation width from RETH_TPU_EXEC_WORKERS
    parallel_exec: bool = False
    # --pipeline-depth / [node] pipeline_depth: cross-block import
    # pipeline (engine/block_pipeline.py); 2 = speculate block N+1
    # while N commits, None = env RETH_TPU_PIPELINE_DEPTH (default 1)
    pipeline_depth: int | None = None
    # --continuous-build / [node] continuous_build: standing block
    # producer (payload/producer.py) — keeps a hot candidate payload
    # incrementally refreshed on pool events and head changes, so
    # getPayload / dev mining seal instead of building from scratch;
    # rides the commit window when the import pipeline is on
    continuous_build: bool = False
    # --hot-state / [node] hot_state: hot-state plane — cross-block
    # trie-node cache (trie/hot_cache.py) feeding sparse reveals
    # without proof fetches, plus a device-resident digest arena
    # (ops/fused_commit.py DigestArena) so sparse finishes upload only
    # dirty rows; False defers to RETH_TPU_HOT_STATE
    hot_state: bool = False
    # --rpc-gateway / [rpc] gateway: route every transport's dispatch
    # through the serving gateway (rpc/gateway.py): admission control
    # with priority classes, in-flight coalescing, and a head-invalidated
    # response cache
    rpc_gateway: bool = False
    # --trace-blocks / [node] trace_blocks: block-lifecycle tracing —
    # per-block span timelines + wall-budget line, Chrome-trace export,
    # and flight-recorder dumps under the datadir (tracing.py)
    trace_blocks: bool = False
    trace_file: str | Path | None = None  # Chrome-trace path override
    # --warmup / [node] warmup: device warm-up manager (ops/warmup.py) —
    # AOT-compile the kernel shape menu behind the supervisor's health
    # probe while serving degraded on the CPU twin ("background"), or
    # finish warm-up before serving ("block"). "off" disables.
    warmup: str = "off"
    # --compile-cache-dir / [node] compile_cache_dir: persistent XLA
    # compilation cache (kernel-source-versioned, probe-verified,
    # quarantine-on-corruption). None = <datadir>/compile-cache when
    # warm-up is on.
    compile_cache_dir: str | Path | None = None
    # --health / [node] health: the node health & SLO engine (health.py)
    # — metric time-series retention, burn-rate SLO evaluation over the
    # default rule table, /health + debug_healthCheck/debug_sloStatus/
    # debug_metricsHistory surfaces, and flight dumps on breach
    health: bool = False
    # [node] slo_interval: seconds between sampler/evaluator passes
    # (<= 0 disables the thread — tests drive HealthEngine.tick())
    slo_interval: float = 1.0
    # [node] slo_window: ring-buffer samples retained per metric series
    slo_window: int = 300
    # --wal / [node] wal: write-ahead log beside the memdb image
    # (storage/wal.py) — every commit fsync-appends its table delta
    # before the in-memory publish, so a kill -9 loses at most
    # persistence_threshold blocks instead of the whole session.
    # Memdb-backed stores only: the native/paged engines carry their
    # own WAL / shadow paging.
    wal: bool = True
    # [node] wal_checkpoint_blocks: persisted blocks between WAL
    # checkpoints (image + fsync'd manifest swap + log truncation)
    wal_checkpoint_blocks: int = 8
    # --no-recovery-verify: skip the startup recovery's state-root
    # recomputation through the committer (storage/recovery.py) —
    # large datadirs can trade the proof for boot time
    recovery_verify_root: bool = True
    # --invalid-cache-size / [node] invalid_cache_size: bound of the
    # engine tree's invalid-header LRU (engine/block_buffer.py) — an
    # invalid-payload flood plateaus here instead of leaking memory.
    # None = RETH_TPU_INVALID_CACHE env or 512.
    invalid_cache_size: int | None = None
    # --fleet / [node] fleet: read-replica fleet mode (fleet/) — start
    # the witness feed server (per-block ExecutionWitness fanout to
    # subscribed stateless replicas), put the RPC gateway in fleet mode
    # (consistent-hash ring routing of pure reads with per-replica
    # draining and replica→ring-neighbor→local failover), and register
    # the fleet_* admin methods. Implies rpc_gateway.
    fleet: bool = False
    # --feed-port: witness feed TCP port (0 = ephemeral)
    feed_port: int = 0
    # --fleet-max-lag: heads a replica may trail the node's head before
    # the ring sheds it (fleet/ring.py prober)
    fleet_max_lag: int = 4
    # --ha-peer-feed: HOST:PORT witness feeds of HA peers (the standby's
    # takeover feed). Probed at startup for epoch fencing: a live peer
    # advertising a HIGHER leader epoch means this node was superseded
    # while it was down — the engine tree fences (refuses stale writes)
    # instead of splitting the brain. The leader also ships its WAL
    # stream to any standby that subscribes on the feed (fleet/standby.py)
    ha_peer_feeds: tuple[str, ...] = ()


class Node:
    """A launched node (in-process; networking arrives as its own layer)."""

    def __init__(self, config: NodeConfig, committer: TrieCommitter | None = None):
        from ..tasks import TaskExecutor

        self.config = config
        # --trace-blocks: enable block-lifecycle tracing before any
        # component runs; traces + flight dumps live under the datadir
        # (or the cwd for ephemeral nodes). An explicit
        # RETH_TPU_FLIGHT_DIR wins for the dumps: a FLEET shares one
        # flight directory so correlated dumps from every process land
        # together — the datadir default must not override it.
        self.trace_path = None
        if config.trace_blocks:
            import os as _os

            from .. import tracing

            base = Path(config.datadir) if config.datadir else Path(".")
            trace_dir = base / "traces"
            trace_dir.mkdir(parents=True, exist_ok=True)
            self.trace_path = (Path(config.trace_file) if config.trace_file
                               else trace_dir / "blocks.trace.json")
            tracing.init_block_tracing(
                chrome_path=self.trace_path,
                flight_dir=(_os.environ.get("RETH_TPU_FLIGHT_DIR")
                            or trace_dir))
        self.committer = committer or TrieCommitter()
        # device hasher supervisor (--hasher auto): present when the
        # committer routes through ops/supervisor.py — surfaced on the
        # events dashboard and /metrics
        self.hasher_supervisor = getattr(self.committer, "supervisor", None)
        # shared hash service (--hash-service): present when every keccak
        # client multiplexes over ops/hash_service.py — surfaced on the
        # events dashboard and hash_service_* /metrics
        self.hash_service = getattr(self.committer, "hash_service", None)
        # device mesh (--mesh): the parallel/mesh.py descriptor the turbo
        # committers and the (meshed) hash service shard over — surfaced
        # on the events dashboard and mesh_* /metrics
        self.hash_mesh = (getattr(self.committer, "hash_mesh", None)
                          or getattr(self.hash_service, "mesh", None))
        # device warm-up manager (--warmup): per-shape compile lifecycle +
        # degraded-mode serving (ops/warmup.py). Usually built by the CLI
        # alongside the committer; a directly-constructed Node with
        # config.warmup set builds and starts one here.
        self.warmup = getattr(self.committer, "warmup", None)
        if self.warmup is None and config.warmup and config.warmup != "off":
            from ..ops.warmup import build_warmup

            cache_dir = config.compile_cache_dir
            if not cache_dir and config.datadir:
                cache_dir = Path(config.datadir) / "compile-cache"
            self.warmup = build_warmup(
                supervisor=self.hasher_supervisor, cache_dir=cache_dir)
            self.committer.attach_warmup(self.warmup)
            if config.warmup == "block":
                self.warmup.run()
            else:
                self.warmup.start()
        # warm the native secp build now: a lazy first-use g++ compile
        # inside newPayload would stall a consensus response for seconds
        from ..primitives.secp256k1 import _native_lib

        _native_lib()
        # task runtime (reference crates/tasks): components register their
        # loops here; a critical failure begins shutdown
        def _critical_failed(name, e, tb):
            import sys

            print(f"critical task {name!r} failed: {e}\n{tb}", file=sys.stderr)
            self.tasks.shutdown.signal()

        self.tasks = TaskExecutor(on_critical_failure=_critical_failed)
        # storage-settings switch (reference: the database args picking the
        # backing store): "memdb" = in-process store with snapshot file,
        # "native" = the C++ WAL engine (native/kvstore.cpp), "paged" = the
        # mmap copy-on-write B+tree engine (native/pagedkv.cpp, the MDBX
        # architecture analogue — reference StorageSettings backend choice)
        from ..storage import open_database

        self.factory = ProviderFactory(
            open_database(config.db_backend, config.datadir,
                          storage_v2=config.storage_v2))
        # crash-safe persistence (--wal, storage/wal.py): attach the
        # write-ahead log BEFORE anything reads the store — attaching
        # replays surviving commit records (discarding any torn tail)
        # into the freshly-opened image, so genesis init, chain-spec
        # rebuild, and the engine tree all see the recovered state
        self.durability = None
        if config.datadir and config.wal:
            from ..storage.wal import attach_wal

            static_dir = (Path(config.datadir) / "static_files"
                          if config.static_file_distance is not None else None)
            self.durability = attach_wal(
                self.factory.db, Path(config.datadir) / "wal",
                checkpoint_blocks=config.wal_checkpoint_blocks,
                static_dir=static_dir)
        # storage-v2 startup invariants (reference rocksdb/invariants.rs):
        # reconcile the aux store against the stage checkpoints — prune
        # what's ahead, unwind what's behind
        from ..storage.settings import SplitDb, check_consistency

        if isinstance(self.factory.db, SplitDb):
            target = check_consistency(self.factory)
            if target is not None:
                from ..stages import Pipeline, default_stages

                Pipeline(self.factory,
                         default_stages(committer=self.committer)).unwind(target)
        if config.genesis_header is not None:
            init_genesis(
                self.factory, config.genesis_header, config.genesis_alloc,
                config.genesis_storage, config.genesis_codes, self.committer,
            )
        # startup recovery (storage/recovery.py): reconcile the recovered
        # store against stage checkpoints and static-file jar digests,
        # heal interrupted unwinds, and verify the recovered head's state
        # root by recomputation through the committer BEFORE serving —
        # the report lands on the events line, recovery_* metrics, and
        # the PR 9 health engine's durability component
        self.recovery = None
        if config.datadir:
            import os as _os

            from ..storage.recovery import recover_on_startup

            env = _os.environ.get("RETH_TPU_RECOVERY_VERIFY")
            verify = (config.recovery_verify_root if env is None
                      else env not in ("", "0"))
            self.recovery = recover_on_startup(
                self.factory, durability=self.durability,
                committer=self.committer,
                static_dir=Path(config.datadir) / "static_files",
                verify_root=verify)
        # chain spec: persist on first launch, rebuild on restart (a node
        # relaunched from a datadir without --genesis must keep advertising
        # the right EIP-2124 fork id)
        from ..storage.tables import Tables

        _SPEC_KEY = b"chain_spec"
        if config.chain_spec is not None:
            with self.factory.provider_rw() as p:
                p.tx.put(Tables.Metadata.name, _SPEC_KEY,
                         config.chain_spec.to_json().encode())
        else:
            with self.factory.provider() as p:
                raw = p.tx.get(Tables.Metadata.name, _SPEC_KEY)
            if raw is not None:
                from ..chainspec import ChainSpec

                config.chain_spec = ChainSpec.from_json(raw.decode())
        exec_spec = (config.chain_spec.execution_spec
                     if config.chain_spec is not None else None)
        self.consensus = EthBeaconConsensus(self.committer,
                                            chainspec=exec_spec)
        self.tree = EngineTree(
            self.factory, self.committer, self.consensus,
            EvmConfig(chain_id=config.chain_id, chainspec=exec_spec),
            persistence_threshold=config.persistence_threshold,
            sparse_workers=config.sparse_workers,
            parallel_exec=config.parallel_exec,
            pipeline_depth=config.pipeline_depth,
            # True forces on; False stays None so RETH_TPU_HOT_STATE decides
            hot_state=config.hot_state or None,
            invalid_cache_size=config.invalid_cache_size,
        )
        # the engine's persistence advance is the durability boundary:
        # with a WAL it drives checkpoint cadence, without one it flushes
        self.tree.durability = self.durability
        # HA epoch fencing (fleet/election.py): probe the configured
        # peer feeds BEFORE any write path opens — a live peer with a
        # higher persisted leader epoch supersedes this node
        self.fence_report = None
        if config.ha_peer_feeds and self.durability is not None:
            from ..fleet.election import fence_check

            peers = []
            for spec in config.ha_peer_feeds:
                host, _, port = str(spec).rpartition(":")
                if host and port.isdigit():
                    peers.append((host, int(port)))
            self.fence_report = fence_check(self.durability.epoch, peers)
            if self.fence_report["fenced"]:
                self.tree.fence(
                    f"superseded by leader epoch "
                    f"{self.fence_report['peer_epoch']} at "
                    f"{self.fence_report['peer']} (own epoch "
                    f"{self.fence_report['own_epoch']})")
        from ..pool.pool import PoolConfig

        self.pool = TransactionPool(lambda: self.tree.overlay_provider(),
                                    PoolConfig(chain_id=config.chain_id))
        # batched insertion + validation offload: RPC threads enqueue, one
        # worker batch-recovers senders natively and inserts per batch
        # (reference BatchTxProcessor + validation task)
        from ..pool import TxBatcher

        self.tx_batcher = TxBatcher(self.pool)
        with self.factory.provider() as p:
            tip = p.header_by_number(p.last_block_number())
        if tip is not None and tip.base_fee_per_gas is not None:
            self.pool.base_fee = tip.base_fee_per_gas
        self.payload_service = PayloadBuilderService(self.tree, self.pool)
        self.miner = LocalMiner(self.tree, self.pool) if config.dev else None

        # pool maintenance rides canonical-state notifications, so the pool
        # stays correct in CL-driven mode too (reference src/maintain.rs)
        def _maintain_pool(chain):
            if chain:
                from ..consensus.validation import calc_next_base_fee
                from ..evm.executor import blob_base_fee, next_excess_blob_gas

                tip = chain[-1].block.header
                next_blob_fee = None
                if tip.excess_blob_gas is not None:
                    params = self.tree.config.blob_params_for(
                        tip.number + 1, tip.timestamp)
                    next_blob_fee = blob_base_fee(next_excess_blob_gas(
                        tip.excess_blob_gas, tip.blob_gas_used or 0,
                        params.target_gas), params.update_fraction)
                self.pool.on_canonical_state_change(
                    calc_next_base_fee(tip), blob_base_fee=next_blob_fee
                )

        self.tree.canon_listeners.append(_maintain_pool)

        # ExEx manager: durable canonical-state notifications + the
        # FinishedHeight feedback that gates pruning (reference crates/exex)
        from ..exex import CanonStateNotification, ExExManager

        self.exex = ExExManager(config.datadir if config.datadir else None)

        def _notify_exex(chain):
            if chain and self.exex.handles:
                self.exex.notify(CanonStateNotification(
                    tip_number=chain[-1].number, tip_hash=chain[-1].hash,
                    blocks=[(b.number, b.hash) for b in chain]))

        self.tree.canon_listeners.append(_notify_exex)

        # data lifecycle: static-file producer + pruner run after
        # persistence advances (reference: launched after pipeline commits)
        self.static_producer = None
        self.pruner = None
        if config.static_file_distance is not None and config.datadir:
            from ..storage.static_files import StaticFileProducer

            self.static_producer = StaticFileProducer(
                self.factory, Path(config.datadir) / "static_files"
            )
            self.factory.static_files = self.static_producer.static
        if config.prune_modes is not None:
            from ..prune import Pruner

            self.pruner = Pruner(self.factory, config.prune_modes)

        def _lifecycle(chain):
            tip = self.tree.persisted_number
            if self.static_producer is not None:
                target = tip - config.static_file_distance
                if target >= 0:
                    self.static_producer.run(target)
            if self.pruner is not None:
                # FinishedHeight gate: never prune past what every ExEx
                # has finished (reference exex/src/lib.rs:17-24)
                self.pruner.run(min(tip, self.exex.finished_height()))

        if self.static_producer is not None or self.pruner is not None:
            self.tree.canon_listeners.append(_lifecycle)

        # RPC servers: public + auth (engine) — reference serves the engine
        # API on a separate JWT-authed port (rpc-builder auth server)
        import threading

        shared_lock = threading.RLock()
        # payload improvement loops must serialise with engine/RPC handlers
        self.payload_service.lock = shared_lock
        # --continuous-build: the standing producer shares the engine
        # lock, feeds payload jobs AND the dev miner its hot candidate
        self.producer = None
        if config.continuous_build:
            from ..payload import BlockProducer

            self.producer = BlockProducer(self.tree, self.pool,
                                          lock=shared_lock)
            self.payload_service.producer = self.producer
            if self.miner is not None:
                self.miner.producer = self.producer
        # serving gateway (--rpc-gateway): ONE gateway shared by the
        # public and auth servers (one admission domain — engine traffic
        # outranks public debug traffic) and by the WS/IPC transports
        # that wrap the public registry. Response-cache keys embed the
        # canonical head; the canon listener clears dead-head entries.
        # --fleet: witness feed server + fleet router BEFORE the gateway
        # so the gateway can route reads through the ring (fleet/)
        self.feed_server = None
        self.fleet_router = None
        self.fleet_federation = None
        self._fleet_fault_observer = None
        if config.fleet:
            from .. import tracing
            from ..fleet.feed import WitnessFeedServer
            from ..fleet.ring import FleetRouter
            from ..obs import federation as federation_mod

            # fleet role for cross-process trace attribution (exported
            # span resource attrs + Chrome process metadata)
            tracing.set_process_role("full")
            self.feed_server = WitnessFeedServer(
                self.tree, chain_id=config.chain_id,
                chain_spec=config.chain_spec, port=config.feed_port)
            self.tree.canon_listeners.append(self.feed_server.on_canon_change)
            # HA WAL shipping: every post-fsync commit record, checkpoint
            # manifest, and fork-choice advance rides the feed to any
            # subscribed standby (RTST1 records, fleet/standby.py); the
            # feed's advertised epoch comes from the WAL manifest
            if self.durability is not None:
                self.feed_server.attach_durability(self.durability)
                self.tree.fcu_listeners.append(self.feed_server.ship_fcu)
            # pending-tx propagation: every pool admission/replacement/
            # drop ships as a pt_* record to subscribed replicas, so the
            # fleet answers pending reads instead of failing them over
            self.feed_server.attach_pool(self.pool)
            self.fleet_router = FleetRouter(max_lag=config.fleet_max_lag)
            self.tree.canon_listeners.append(self.fleet_router.on_head_change)
            # metrics federation: background pulls of every replica's
            # registry via fleet_metricsSnapshot -> /metrics?scope=fleet,
            # debug_fleetMetrics, the fleetobs[...] events fragment, and
            # the fleet SLO rules (obs/federation.py)
            self.fleet_federation = federation_mod.MetricsFederation(
                self.fleet_router)
            federation_mod.install(self.fleet_federation)
            # correlated flight dumps: a local fault event / SLO breach
            # fans its dump request to every replica over the feed
            self._fleet_fault_observer = self.feed_server.fault_observer()
            tracing.add_fault_observer(self._fleet_fault_observer)
        self.gateway = None
        if config.rpc_gateway or config.fleet:
            from ..rpc.gateway import RpcGateway

            self.gateway = RpcGateway(
                head_supplier=lambda: self.tree.head_hash,
                fleet=self.fleet_router)
            self.tree.canon_listeners.append(self.gateway.on_head_change)
        self.eth_api = EthApi(self.tree, self.pool, config.chain_id,
                              tx_batcher=self.tx_batcher)
        self.rpc = RpcServer(port=config.http_port, lock=shared_lock,
                             gateway=self.gateway)
        self.rpc.register(self.eth_api)
        self.rpc.register(NetApi(config.chain_id))
        self.rpc.register(Web3Api())
        self.rpc.register(TxpoolApi(self.pool))
        from ..rpc.debug import DebugApi
        from ..rpc.flashbots import BundleApi, ValidationApi
        from ..rpc.miner import MinerApi
        from ..rpc.otterscan import OtterscanApi

        debug_api = DebugApi(self.eth_api)
        self.rpc.register(debug_api)
        self.rpc.register(OtterscanApi(self.eth_api, debug_api))
        self.rpc.register(BundleApi(self.eth_api))
        self.rpc.register(ValidationApi(self.eth_api))
        self.rpc.register(MinerApi(self.payload_service, self.pool))
        if self.producer is not None:
            from ..rpc.net import ProducerApi

            self.rpc.register(ProducerApi(self.producer))
        if self.fleet_router is not None:
            from ..fleet.ring import FleetAdminApi

            # fleet_* classifies into the gateway's engine admission
            # class: replica registration/draining never queues behind
            # a debug_traceBlock re-execution
            self.rpc.register(FleetAdminApi(self.fleet_router,
                                            self.feed_server))
        self.engine_api = EngineApi(self.tree, self.payload_service, pool=self.pool)
        # JWT on the engine port (reference auth_layer.rs): explicit secret,
        # else auto-generated jwt.hex under the datadir; dev mode stays open
        # (the reference's --dev also relaxes local tooling friction)
        jwt_secret = config.jwt_secret
        if jwt_secret is None and config.datadir and not config.dev:
            from ..rpc.jwt import load_or_create_secret

            jwt_secret = load_or_create_secret(Path(config.datadir) / "jwt.hex")
        self.authrpc = RpcServer(port=config.authrpc_port, lock=shared_lock,
                                 jwt_secret=jwt_secret, gateway=self.gateway)
        self.authrpc.register(self.engine_api)
        self.authrpc.register(self.eth_api)  # CLs also query eth_ on authrpc

        # WebSocket + IPC transports over the same public method registry
        self.ws = None
        if config.ws_port is not None:
            from ..rpc.ws import WsRpcServer

            self.ws = WsRpcServer(self.rpc, port=config.ws_port)
        self.ipc = None
        if config.ipc_path:
            from ..rpc.ipc import IpcRpcServer

            self.ipc = IpcRpcServer(self.rpc, config.ipc_path)

        # devp2p: encrypted RLPx listener + discv4 (reference: network
        # component wiring in the node builder, launch/engine.rs:145-156)
        self.network = None
        self.discovery = None
        self.discovery_v5 = None
        if config.p2p_port is not None:
            from ..net.p2p import random_node_key
            from ..net.server import NetworkManager
            from ..net.wire import Status

            key = config.node_key or random_node_key()
            with self.factory.provider() as p:
                tip_num = p.last_block_number()
                tip_header = p.header_by_number(tip_num)
                fork_id = (b"\x00" * 4, 0)
                if config.chain_spec is not None:
                    fork_id = config.chain_spec.fork_id(
                        tip_num, tip_header.timestamp if tip_header else 0)
                status = Status(
                    network_id=config.chain_id,
                    head=p.canonical_hash(tip_num),
                    genesis=p.canonical_hash(0),
                    fork_id=fork_id,
                    earliest=0,  # full node: whole history served
                    latest=tip_num,
                )
            self.network = NetworkManager(
                self.factory, status, pool=self.pool, host=config.p2p_host,
                port=config.p2p_port, node_priv=key,
                chain_spec=config.chain_spec,
                head_position=(tip_num, tip_header.timestamp if tip_header else 0),
                provider_fn=lambda: self.tree.overlay_provider(),
            )
            # NAT resolution decides the ADVERTISED address (enode/ENR);
            # binding stays on p2p_host (reference crates/net/nat)
            from ..net.nat import NatResolver

            self.network.advertised_host = NatResolver.parse(
                config.nat).external_ip(config.p2p_host)

            # keep the advertised Status + ForkFilter anchored to the LIVE
            # head: a node that syncs across a fork boundary must start
            # advertising (and enforcing) the post-fork id
            def _track_head(chain, _net=self.network, _spec=config.chain_spec):
                if chain:
                    tip = chain[-1].block.header
                else:
                    # fully persisted head (low persistence threshold /
                    # FCU to a persisted hash): the handshake Status must
                    # still advertise the LIVE tip, or peers dialing in
                    # would sync against a stale head
                    with self.factory.provider() as p:
                        tip = p.header_by_number(p.last_block_number())
                    if tip is None:
                        return
                _net.head_position = (tip.number, tip.timestamp)
                _net.status.head = tip.hash
                _net.status.latest = tip.number
                if _spec is not None:
                    _net.status.fork_id = _spec.fork_id(tip.number, tip.timestamp)
                # eth/69 range gossip replaces TD announcements
                _net.announce_block_range(_net.status.earliest, tip.number,
                                          tip.hash)

            self.tree.canon_listeners.append(_track_head)
        # node health & SLO engine (--health): samples every metric into
        # bounded ring buffers and evaluates the burn-rate rule table;
        # installed as the process default so /health (served by every
        # RpcServer) and the debug health RPCs reach it (health.py)
        self.health = None
        if config.health:
            from .. import health as health_mod

            self.health = health_mod.HealthEngine(
                interval=config.slo_interval, window=config.slo_window)
            health_mod.install(self.health)
            self.health.start()

        # human progress dashboard (reference crates/node/events)
        from .events import NodeEventReporter

        self.event_reporter = NodeEventReporter(self)
        self.tree.canon_listeners.append(self.event_reporter.on_canon_change)

        from ..rpc.admin import AdminApi

        self.admin_api = AdminApi(self.network, None, config.chain_id)
        if config.enable_admin:
            # node-control surface: only on explicit opt-in (reference
            # gates admin behind --http.api, never on by default)
            self.rpc.register(self.admin_api)

    def start_network(self) -> int | None:
        """Start the RLPx listener (+ discv4 when enabled); returns the
        TCP port, or None when networking is disabled."""
        if self.network is None:
            return None
        port = self.network.start()
        if self.config.discovery:
            from ..net.discv4 import Discv4
            from ..net.discv5 import Discv5

            self.discovery = Discv4(self.network.node_priv,
                                    host=self.network.host, tcp_port=port)
            self.discovery.start()
            self.admin_api.discovery = self.discovery
            # discv5 runs alongside discv4 (reference: both services feed
            # the same peer set, crates/net/discv5/src/lib.rs)
            self.discovery_v5 = Discv5(self.network.node_priv,
                                       host=self.network.host, tcp_port=port)
            self.discovery_v5.start()
            if self.config.bootnodes:
                self.discovery.bootstrap(list(self.config.bootnodes))
                self.discovery.lookup()
            if self.config.bootnodes_v5:
                self.discovery_v5.bootstrap(list(self.config.bootnodes_v5))

                def _v5_lookup(shutdown, d5=self.discovery_v5, net=self.network):
                    # sessions form asynchronously (1+ UDP round trips) —
                    # a lookup fired synchronously after bootstrap would
                    # find zero session peers and degrade to static peering
                    for _ in range(100):
                        if shutdown.wait(0.1):
                            return
                        if d5.sessions:
                            break
                    known = {p.node_id for p in net.peers}
                    for enr in d5.lookup(rounds=2):
                        # discovered records are dialable RLPx peers
                        if not (enr.ip and enr.tcp_port):
                            continue
                        from ..primitives.secp256k1 import pubkey_to_bytes

                        nid = pubkey_to_bytes(enr.pubkey)
                        if nid in known:
                            continue
                        try:
                            net.connect_to(
                                f"enode://{nid.hex()}@{enr.ip}:{enr.tcp_port}")
                        except Exception:  # noqa: BLE001 — best-effort dial
                            pass

                self.tasks.spawn("discv5-lookup", _v5_lookup)
        elif self.config.bootnodes:
            # static peering: without discovery, dial the bootnodes directly
            for url in self.config.bootnodes:
                try:
                    self.network.connect_to(url)
                except Exception:  # noqa: BLE001 — best-effort static dial
                    pass
        return port

    def start_rpc(self) -> tuple[int, int]:
        """Start the RPC transports; returns (http_port, authrpc_port).
        The WS port (when enabled) is at ``self.ws.port`` after this."""
        self.event_reporter.start()
        if self.producer is not None:
            self.producer.start()
        ports = self.rpc.start(), self.authrpc.start()
        if self.feed_server is not None:
            # hello field: a re-anchoring replica registers with this
            # node's fleet gateway at the advertised RPC port
            self.feed_server.rpc_port = ports[0]
        if self.ws is not None:
            self.ws.start()
        if self.ipc is not None:
            self.ipc.start()
        if self.feed_server is not None:
            self.feed_server.start()
        if self.fleet_router is not None:
            self.fleet_router.start()
        if self.fleet_federation is not None:
            self.fleet_federation.start()
        return ports

    def stop(self):
        if self.producer is not None:
            self.producer.stop()
        self.tx_batcher.close()
        if self.health is not None:
            from .. import health as health_mod

            self.health.stop()
            health_mod.uninstall(self.health)
        self.event_reporter.stop()
        if self.fleet_federation is not None:
            from ..obs import federation as federation_mod

            self.fleet_federation.stop()
            federation_mod.uninstall(self.fleet_federation)
        if self._fleet_fault_observer is not None:
            from .. import tracing

            tracing.remove_fault_observer(self._fleet_fault_observer)
        if self.fleet_router is not None:
            self.fleet_router.stop()
        if self.feed_server is not None:
            self.feed_server.stop()
        self.tasks.graceful_shutdown()
        self.rpc.stop()
        self.authrpc.stop()
        if self.ws is not None:
            self.ws.stop()
        if self.ipc is not None:
            self.ipc.stop()
        if self.discovery is not None:
            self.discovery.stop()
        if self.discovery_v5 is not None:
            self.discovery_v5.stop()
        if self.network is not None:
            self.network.stop()
        if self.durability is not None:
            # graceful stop = one final checkpoint: image + manifest
            # swapped, log truncated — the next boot replays nothing
            self.durability.checkpoint(
                head=(self.tree.persisted_number, self.tree.persisted_hash))
            self.durability.close()
        elif self.factory.db is not None and hasattr(self.factory.db, "flush"):
            self.factory.db.flush()
        if self.config.trace_blocks:
            # terminate the Chrome trace into a valid JSON array
            from .. import tracing

            tracing.shutdown_chrome_trace()
