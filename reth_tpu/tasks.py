"""Task runtime: spawned + critical tasks with graceful shutdown.

Reference analogue: crates/tasks (TaskExecutor/TaskManager: panic-
tolerant critical tasks, shutdown signals, spawn_os_thread). The node's
long-running components (network accept loop, discovery, miner, payload
improvement loops) register here so shutdown is one call that signals
every task and joins it, and a CRITICAL task dying is surfaced instead
of silently stopping (the reference shuts the node down; here the
failure is recorded and an optional callback fires).
"""

from __future__ import annotations

import threading
import traceback


class Shutdown:
    """A one-shot shutdown signal tasks poll or wait on (reference
    crates/tasks/src/shutdown.rs)."""

    def __init__(self):
        self._event = threading.Event()

    def is_set(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def signal(self) -> None:
        self._event.set()


class TaskHandle:
    __slots__ = ("name", "critical", "thread", "error")

    def __init__(self, name: str, critical: bool, thread: threading.Thread):
        self.name = name
        self.critical = critical
        self.thread = thread
        self.error: BaseException | None = None

    @property
    def alive(self) -> bool:
        return self.thread.is_alive()


class TaskExecutor:
    """Spawns named tasks bound to one shutdown signal.

    ``fn`` receives the Shutdown as its first argument and should return
    promptly once it is signalled. A raised exception is captured on the
    handle; for CRITICAL tasks ``on_critical_failure`` also fires (the
    node uses it to begin shutdown, mirroring the reference's
    panicked-task => shutdown behavior)."""

    def __init__(self, on_critical_failure=None):
        self.shutdown = Shutdown()
        self.handles: list[TaskHandle] = []
        self.on_critical_failure = on_critical_failure
        self._lock = threading.Lock()

    def _spawn(self, name: str, critical: bool, fn, args) -> TaskHandle:
        handle: TaskHandle = None  # type: ignore[assignment]

        def run():
            try:
                fn(self.shutdown, *args)
            except BaseException as e:  # noqa: BLE001 — captured, never lost
                handle.error = e
                handle_tb = traceback.format_exc()
                if critical:
                    cb = self.on_critical_failure
                    if cb is not None:
                        cb(name, e, handle_tb)

        thread = threading.Thread(target=run, name=f"reth-tpu/{name}", daemon=True)
        handle = TaskHandle(name, critical, thread)
        with self._lock:
            self.handles.append(handle)
        thread.start()
        return handle

    def spawn(self, name: str, fn, *args) -> TaskHandle:
        return self._spawn(name, critical=False, fn=fn, args=args)

    def spawn_critical(self, name: str, fn, *args) -> TaskHandle:
        return self._spawn(name, critical=True, fn=fn, args=args)

    def critical_errors(self) -> list[tuple[str, BaseException]]:
        with self._lock:
            return [(h.name, h.error) for h in self.handles
                    if h.critical and h.error is not None]

    def graceful_shutdown(self, timeout: float = 10.0) -> list[str]:
        """Signal shutdown and join everything; returns names of tasks
        that failed to stop within the timeout."""
        self.shutdown.signal()
        stuck = []
        with self._lock:
            handles = list(self.handles)
        for h in handles:
            h.thread.join(timeout=timeout)
            if h.thread.is_alive():
                stuck.append(h.name)
        return stuck
