"""ethstats: live node telemetry to an ethstats server over WebSocket.

Reference analogue: crates/node/ethstats — `EthStatsService` keeps a WS
connection to the dashboard (url = "node:secret@host:port"), sends the
`hello` login, answers `node-ping` with `node-pong`, and pushes `stats`
/ `block` / `pending` emits on a cadence and on canonical change.

The WS client side (handshake with masking, RFC 6455 framing) lives
here; the server-side codec is shared from rpc/ws.py.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import threading
import time


class EthStatsError(ConnectionError):
    pass


def _client_handshake(sock: socket.socket, host: str, path: str = "/api") -> None:
    key = base64.b64encode(os.urandom(16))
    sock.sendall(
        b"GET " + path.encode() + b" HTTP/1.1\r\n"
        b"Host: " + host.encode() + b"\r\n"
        b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
        b"Sec-WebSocket-Key: " + key + b"\r\n"
        b"Sec-WebSocket-Version: 13\r\n\r\n"
    )
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk:
            raise EthStatsError("closed during handshake")
        data += chunk
    if b" 101 " not in data.split(b"\r\n", 1)[0]:
        raise EthStatsError("upgrade refused")


def _send_masked(sock: socket.socket, payload: bytes, opcode: int = 0x1) -> None:
    """Client frames must be masked (RFC 6455 5.1)."""
    mask = os.urandom(4)
    masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([0x80 | n])
    elif n < (1 << 16):
        header += bytes([0x80 | 126]) + struct.pack(">H", n)
    else:
        header += bytes([0x80 | 127]) + struct.pack(">Q", n)
    sock.sendall(header + mask + masked)


def _recv_unmasked(sock: socket.socket,
                   idle_timeout: float | None = None) -> tuple[int, bytes] | None:
    """Server frames arrive unmasked. With ``idle_timeout``, returns None
    when NO frame has started within it; once the first byte arrives the
    whole frame is read under a long timeout — a timeout mid-frame would
    otherwise discard partial bytes and desync the stream permanently."""
    def exact(n):
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise EthStatsError("connection closed")
            buf += chunk
        return buf

    if idle_timeout is not None:
        sock.settimeout(idle_timeout)
        try:
            first = exact(1)
        except socket.timeout:
            return None
        sock.settimeout(30.0)  # frame in flight: finish it or fail loudly
        b0, b1 = first[0], exact(1)[0]
    else:
        b0, b1 = exact(2)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    ln = b1 & 0x7F
    if ln == 126:
        (ln,) = struct.unpack(">H", exact(2))
    elif ln == 127:
        (ln,) = struct.unpack(">Q", exact(8))
    mask = exact(4) if masked else None
    payload = exact(ln) if ln else b""
    if mask:
        payload = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
    return opcode, payload


def parse_ethstats_url(url: str) -> tuple[str, str, str, int]:
    """"node:secret@host:port" -> (node_name, secret, host, port)."""
    creds, _, addr = url.rpartition("@")
    name, _, secret = creds.partition(":")
    host, _, port = addr.partition(":")
    if not name or not host:
        raise ValueError("ethstats url must be node:secret@host:port")
    return name, secret, host, int(port or "3000")


class EthStatsService:
    """Reports a node's stats to an ethstats server until stopped."""

    def __init__(self, url: str, node, interval: float = 10.0):
        self.node_name, self.secret, self.host, self.port = parse_ethstats_url(url)
        self.node = node
        self.interval = interval
        self.sock: socket.socket | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- wire --------------------------------------------------------------

    def _emit(self, topic: str, payload: dict) -> None:
        msg = json.dumps({"emit": [topic, payload]}).encode()
        with self._lock:
            if self.sock is not None:
                _send_masked(self.sock, msg)

    def connect(self) -> None:
        # handshake on a local socket; publish under the lock only once
        # upgraded, so a concurrent _emit can never write a frame into the
        # raw HTTP upgrade stream
        sock = socket.create_connection((self.host, self.port), timeout=10)
        try:
            _client_handshake(sock, f"{self.host}:{self.port}")
        except Exception:
            sock.close()
            raise
        with self._lock:
            old, self.sock = self.sock, sock
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self._emit("hello", {
            "id": self.node_name,
            "secret": self.secret,
            "info": {
                "name": self.node_name,
                "node": "reth-tpu/0.2",
                "protocol": "eth/68",
                "api": "No", "os": "linux", "os_v": "", "client": "0.2",
                "canUpdateHistory": True,
            },
        })

    # -- payloads ----------------------------------------------------------

    def _stats_payload(self) -> dict:
        peers = len(self.node.network.peers) if self.node.network else 0
        with self.node.factory.provider() as p:
            gas_price = self.node.eth_api.gas_oracle.suggest_gas_price(p)
        return {
            "id": self.node_name,
            "stats": {
                "active": True, "syncing": False, "mining": False,
                "hashrate": 0, "peers": peers,
                "gasPrice": gas_price,
                "uptime": 100,
            },
        }

    def _block_payload(self) -> dict:
        with self.node.factory.provider() as p:
            n = p.last_block_number()
            h = p.header_by_number(n)
        return {
            "id": self.node_name,
            "block": {
                "number": n,
                "hash": "0x" + h.hash.hex(),
                "parentHash": "0x" + h.parent_hash.hex(),
                "timestamp": h.timestamp,
                "gasUsed": h.gas_used,
                "gasLimit": h.gas_limit,
                "difficulty": "0",
                "totalDifficulty": "0",
                "transactions": [],
                "uncles": [],
            },
        }

    def report_block(self) -> None:
        # called from the engine's canon listener: never raise into it
        try:
            self._emit("block", self._block_payload())
        except Exception:  # noqa: BLE001 — the loop's reconnect recovers
            pass

    def report_stats(self) -> None:
        self._emit("stats", self._stats_payload())

    def report_pending(self) -> None:
        self._emit("pending", {
            "id": self.node_name,
            "stats": {"pending": len(self.node.pool) if self.node.pool else 0},
        })

    # -- service loop ------------------------------------------------------

    def start(self) -> None:
        self.connect()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        # push block reports on canonical change
        if getattr(self.node, "tree", None) is not None:
            self.node.tree.canon_listeners.append(lambda _chain: self.report_block())

    def _loop(self) -> None:
        last_report = 0.0
        while not self._stop.is_set():
            try:
                got = _recv_unmasked(self.sock, idle_timeout=0.5)
                op, payload = got if got is not None else (None, None)
                if op == 0x1 and payload:
                    try:
                        msg = json.loads(payload)
                        topic = (msg.get("emit") or [None])[0]
                    except Exception:  # noqa: BLE001 — a malformed frame
                        topic = None   # must not kill the telemetry thread
                    if topic == "node-ping":
                        self._emit("node-pong", {"id": self.node_name,
                                                 "clientTime": time.time()})
                if time.time() - last_report >= self.interval:
                    self.report_stats()
                    self.report_pending()
                    last_report = time.time()
            except (EthStatsError, OSError):
                # reconnect with backoff (the reference keeps retrying)
                if self._stop.wait(2.0):
                    return
                try:
                    self.connect()
                except OSError:
                    continue

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        with self._lock:
            if self.sock is not None:
                self.sock.close()
                self.sock = None
