"""EVM bytecode interpreter (CPU), fork-parameterized Frontier→Prague.

Reference analogue: the revm v41 interpreter (external crate; reth wires
it via `ConfigureEvm`, crates/evm/evm/src/lib.rs:181, and selects a revm
`SpecId` per block — crates/ethereum/evm/src/config.rs:2-3). A
from-scratch stack machine: 256-bit words as Python ints, memory as
bytearray. Everything fork-dependent — opcode availability, the
EIP-2929 warm/cold model vs the flat pre-Berlin gas tables, the three
SSTORE regimes (legacy / EIP-1283-2200 net / post-Berlin), EIP-150
63/64 gas retention, EIP-161 touch semantics, EIP-3529 refunds,
EIP-3860 initcode metering, EIP-1153/5656/6780 — is read from the
active :class:`~reth_tpu.evm.spec.Spec`.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..primitives.keccak import keccak256
from ..primitives.rlp import rlp_encode, encode_int
from .spec import LATEST_SPEC, Spec
from .state import EvmState, resolve_delegation

U256 = 1 << 256
MASK = U256 - 1
SIGN_BIT = 1 << 255

MAX_CALL_DEPTH = 1024
MAX_CODE_SIZE = 24576
MAX_INITCODE_SIZE = 2 * MAX_CODE_SIZE

# gas constants
G_ZERO_BYTE = 4
G_NONZERO_BYTE = 16
G_COLD_SLOAD = 2100
G_WARM_ACCESS = 100
G_COLD_ACCOUNT = 2600
G_SSTORE_SET = 20000
G_SSTORE_RESET = 2900
R_SSTORE_CLEAR = 4800
G_KECCAK = 30
G_KECCAK_WORD = 6
G_COPY_WORD = 3
G_LOG = 375
G_LOG_TOPIC = 375
G_LOG_BYTE = 8
G_CREATE = 32000
G_CODE_DEPOSIT = 200
G_CALL_VALUE = 9000
G_CALL_STIPEND = 2300
G_NEW_ACCOUNT = 25000
G_SELFDESTRUCT = 5000
G_INITCODE_WORD = 2
G_EXP_BYTE = 50
G_MEM = 3
G_TX = 21000
G_TX_CREATE = 32000
G_ACCESS_LIST_ADDR = 2400
G_ACCESS_LIST_SLOT = 1900


class Halt(Exception):
    """Exceptional halt: consumes all frame gas."""


class Revert(Exception):
    def __init__(self, output: bytes):
        self.output = output


@dataclass
class BlockEnv:
    number: int = 0
    timestamp: int = 0
    coinbase: bytes = b"\x00" * 20
    gas_limit: int = 30_000_000
    base_fee: int = 0
    prev_randao: bytes = b"\x00" * 32
    blob_base_fee: int = 1
    chain_id: int = 1
    difficulty: int = 0  # pre-merge DIFFICULTY opcode value
    block_hashes: dict[int, bytes] = field(default_factory=dict)


@dataclass
class TxEnv:
    origin: bytes = b"\x00" * 20
    gas_price: int = 0
    blob_hashes: tuple[bytes, ...] = ()


@dataclass
class CallFrame:
    caller: bytes
    address: bytes          # storage/context address
    code: bytes
    data: bytes
    value: int              # CALLVALUE the frame observes
    gas: int
    static: bool = False
    depth: int = 0
    transfer_value: bool = True  # False for DELEGATECALL: value is context-only
    kind: str = "CALL"           # CALL/CALLCODE/DELEGATECALL/STATICCALL (tracers)


class Interpreter:
    """Iterative interpreter: EVM call frames live on an EXPLICIT frame
    stack of suspended generators (the trampoline in :meth:`_drive`), not
    the Python call stack — depth-1024 chains run without touching the
    recursion limit (reference: revm's iterative frame loop behind
    crates/evm/evm/src/lib.rs:181)."""

    def __init__(self, state: EvmState, block: BlockEnv, tx: TxEnv, tracer=None,
                 spec: Spec | None = None):
        self.state = state
        self.block = block
        self.tx = tx
        self.spec = spec if spec is not None else LATEST_SPEC
        self.transient: dict[tuple[bytes, bytes], int] = {}
        # optional per-step hook(pc, op, gas, stack, mem, depth) — the
        # struct-logger seam for debug_traceTransaction (revm Inspector
        # analogue); None costs one branch per opcode
        self.tracer = tracer

    # -- entry points ---------------------------------------------------------

    def call(self, frame: CallFrame) -> tuple[bool, int, bytes]:
        """Execute a message call; returns (success, gas_left, output)."""
        return self._drive(self._call_gen(frame))

    def create(
        self, caller: bytes, value: int, initcode: bytes, gas: int,
        depth: int, salt: bytes | None = None, tx_nonce: int | None = None,
    ) -> tuple[bool, int, bytes, bytes]:
        """CREATE/CREATE2; returns (success, gas_left, address, output)."""
        return self._drive(self._create_gen(caller, value, initcode, gas,
                                            depth, salt, tx_nonce))

    def _drive(self, root):
        """The explicit frame stack: each entry is one EVM frame suspended
        as a generator at its nested CALL/CREATE site. A child frame's
        result resumes its parent via send(); a child's Revert/Halt is
        thrown INTO the parent at the yield point, which preserves the
        exact semantics the recursive form had (`try: self.call(sub)
        except Revert` around the opcode)."""
        stack = [root]
        value = None
        exc: BaseException | None = None
        while stack:
            g = stack[-1]
            try:
                if exc is not None:
                    e, exc = exc, None
                    req = g.throw(e)
                else:
                    req = g.send(value)
                value = None
            except StopIteration as s:
                stack.pop()
                value = s.value
                continue
            except (Revert, Halt) as e:
                stack.pop()
                exc = e
                value = None
                continue
            kind, arg = req
            stack.append(self._call_gen(arg) if kind == "call"
                         else self._create_gen(*arg))
            value = None
        if exc is not None:
            raise exc
        return value

    def _call_gen(self, frame: CallFrame):
        """One message-call frame (prologue + run + epilogue) as a
        generator; nested frames are yielded to the trampoline."""
        if frame.depth > MAX_CALL_DEPTH:
            return False, frame.gas, b""
        on_enter = getattr(self.tracer, "on_enter", None)
        on_exit = getattr(self.tracer, "on_exit", None)
        if on_enter is not None:
            on_enter(frame.kind, frame)
        state = self.state
        snap = state.snapshot()
        ok = True
        gas_left, out, err = frame.gas, b"", None
        try:
            if frame.value and frame.transfer_value:
                if state.balance(frame.caller) < frame.value:
                    ok = False
                    err = "halted"
                    return False, frame.gas, b""
                state.sub_balance(frame.caller, frame.value)
                state.add_balance(frame.address, frame.value)
            elif (frame.transfer_value and self.spec.touch_creates_empty
                  and state.account(frame.address) is None):
                # pre-EIP-161: every message call materializes its target,
                # value or not (the zero-balance precompile accounts on
                # mainnet exist because of exactly this)
                state.add_balance(frame.address, 0)
            pre = _precompile(frame.address, self.spec)
            if pre is not None:
                ok, gas_left, out = pre(frame.data, frame.gas)
                if not ok:
                    state.revert(snap)
                    err = "halted"
            elif frame.code:
                try:
                    gas_left, out = yield from self._run_gen(frame)
                except Revert as r:
                    state.revert(snap)
                    if on_exit is not None:
                        on_exit(frame, False, getattr(r, "gas_left", 0),
                                r.output, "reverted")
                        on_exit = None
                    raise
                except Halt:
                    state.revert(snap)
                    ok, gas_left, out, err = False, 0, b"", "halted"
        finally:
            if on_exit is not None:
                on_exit(frame, ok, gas_left, out, err)
        return ok, gas_left, out

    def _create_gen(
        self, caller: bytes, value: int, initcode: bytes, gas: int,
        depth: int, salt: bytes | None = None, tx_nonce: int | None = None,
    ):
        """One contract-creation frame as a generator.

        ``tx_nonce`` marks a top-level create transaction: the address
        derives from the tx nonce and the sender's nonce is NOT bumped here
        (the transaction itself already did).
        """
        state = self.state
        if depth > MAX_CALL_DEPTH or state.balance(caller) < value:
            return False, gas, b"", b""
        if state.nonce(caller) >= (1 << 64) - 1:
            return False, gas, b"", b""
        if tx_nonce is not None:
            addr = keccak256(rlp_encode([caller, encode_int(tx_nonce)]))[12:]
        elif salt is None:
            addr = keccak256(rlp_encode([caller, encode_int(state.nonce(caller))]))[12:]
        else:
            addr = keccak256(b"\xff" + caller + salt + keccak256(initcode))[12:]
        spec = self.spec
        if tx_nonce is None:
            state.bump_nonce(caller)
        state.warm_account(addr)
        existing = state.account(addr)
        if existing is not None and (existing.nonce > 0 or existing.code_hash != keccak256(b"")):
            return False, 0, b"", b""  # address collision burns gas
        snap = state.snapshot()
        # EIP-161 starts new contracts at nonce 1; before it, nonce 0
        state.create_account(addr, nonce=1 if spec.state_clearing else 0)
        state.sub_balance(caller, value)
        state.add_balance(addr, value)
        frame = CallFrame(caller=caller, address=addr, code=initcode,
                          data=b"", value=value, gas=gas, depth=depth,
                          kind="CREATE")
        try:
            gas_left, out = yield from self._run_gen(frame)
        except Revert as r:
            state.revert(snap)
            return False, getattr(r, "gas_left", 0), b"", r.output
        except Halt:
            state.revert(snap)
            return False, 0, b"", b""
        # code validation + deposit gas apply even if the initcode
        # selfdestructed the account (execution-specs generic_create order)
        if spec.max_code_size is not None and len(out) > spec.max_code_size:
            state.revert(snap)
            return False, 0, b"", b""
        if spec.reject_ef_code and out and out[0] == 0xEF:  # EIP-3541
            state.revert(snap)
            return False, 0, b"", b""
        deposit = G_CODE_DEPOSIT * len(out)
        if gas_left < deposit:
            if spec.create_fail_on_deposit_oog:  # EIP-2 (Homestead)
                state.revert(snap)
                return False, 0, b"", b""
            out = b""  # Frontier: creation succeeds with empty code
        else:
            gas_left -= deposit
        # EIP-6780: if the initcode selfdestructed the account it is None
        # now (create_account made it live; only a fresh destruct kills it)
        # → creation succeeds but the account stays dead, no code deposit.
        # Stale _selfdestructs membership from earlier txs cannot trip this.
        if state.account(addr) is None:
            return True, gas_left, addr, b""
        state.set_code(addr, out)
        return True, gas_left, addr, b""

    # -- main loop ------------------------------------------------------------

    def _run_gen(self, fr: CallFrame):
        state = self.state
        code = fr.code
        stack: list[int] = []
        mem = bytearray()
        pc = 0
        gas = fr.gas
        returndata = b""
        # initcode is deployment-unique: caching it would churn hot
        # contracts out of the bounded analysis cache
        jumpdests = (_jumpdests(code) if fr.kind == "CREATE"
                     else _jumpdests_cached(code))
        push = stack.append

        def use(n):
            nonlocal gas
            if gas < n:
                raise Halt()
            gas -= n

        def pop():
            if not stack:
                raise Halt()
            return stack.pop()

        def mem_expand(offset, size):
            nonlocal gas
            if size == 0:
                return
            end = offset + size
            if end > len(mem):
                new_words = (end + 31) // 32
                old_words = (len(mem) + 31) // 32
                cost = (G_MEM * new_words + new_words * new_words // 512) - (
                    G_MEM * old_words + old_words * old_words // 512
                )
                use(cost)
                mem.extend(b"\x00" * (new_words * 32 - len(mem)))

        def mem_read(offset, size):
            if size == 0:
                return b""
            if offset > 2**32 or size > 2**32:
                raise Halt()
            mem_expand(offset, size)
            return bytes(mem[offset : offset + size])

        def mem_write(offset, data):
            if not data:
                return
            if offset > 2**32:
                raise Halt()
            mem_expand(offset, len(data))
            mem[offset : offset + len(data)] = data

        tracer = self.tracer
        # fork rule set, read into locals once per frame so the hot loop
        # pays attribute access only at entry
        spec = self.spec
        warm_cold = spec.warm_cold
        has_push0 = spec.has_push0
        has_revert = spec.has_revert
        has_shifts = spec.has_shifts
        sstore_net = spec.sstore_net
        sstore_sentry = spec.sstore_sentry
        cold = None  # cold-op dispatch table, built on first cold op

        def _build_cold():
            """Dispatch table for the cold tail: env/context reads, copies,
            logs, transient storage, selfdestruct. Handlers are closures
            over this frame's cell vars (gas/pc/stack/mem), built lazily so
            small hot-only frames never pay for their construction. A
            handler returning non-None ends the frame with that value."""

            def h_sdiv():
                use(5); a, b = _sgn(pop()), _sgn(pop())
                if b == 0:
                    push(0)
                else:
                    q = abs(a) // abs(b)
                    push((q if (a < 0) == (b < 0) else -q) & MASK)

            def h_smod():
                use(5); a, b = _sgn(pop()), _sgn(pop())
                if b == 0:
                    push(0)
                else:
                    r = abs(a) % abs(b)
                    push((-r if a < 0 else r) & MASK)

            def h_addmod():
                use(8); a, b, n = pop(), pop(), pop(); push((a + b) % n if n else 0)

            def h_mulmod():
                use(8); a, b, n = pop(), pop(), pop(); push((a * b) % n if n else 0)

            def h_exp():
                a, e = pop(), pop()
                use(10 + spec.g_exp_byte * ((e.bit_length() + 7) // 8))
                push(pow(a, e, U256))

            def h_signextend():
                use(5); b, x = pop(), pop()
                if b < 31:
                    bit = 8 * (b + 1) - 1
                    if x & (1 << bit):
                        x |= MASK ^ ((1 << (bit + 1)) - 1)
                    else:
                        x &= (1 << (bit + 1)) - 1
                push(x & MASK)

            def h_byte():
                use(3); i, x = pop(), pop()
                push((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)

            def h_sar():
                use(3); s, x = pop(), _sgn(pop())
                push((x >> s if s < 256 else (0 if x >= 0 else MASK)) & MASK)

            def acct_access(addr, flat):
                """Account-touch cost: EIP-2929 warm/cold after Berlin,
                the fork's flat price before it."""
                if warm_cold:
                    return G_WARM_ACCESS if state.warm_account(addr) else G_COLD_ACCOUNT
                return flat

            def h_balance():
                addr = pop().to_bytes(32, "big")[12:]
                use(acct_access(addr, spec.g_balance))
                push(state.balance(addr))

            def h_origin():
                use(2); push(int.from_bytes(self.tx.origin, "big"))

            def h_codesize():
                use(2); push(len(code))

            def h_gasprice():
                use(2); push(self.tx.gas_price)

            def h_extcodesize():
                addr = pop().to_bytes(32, "big")[12:]
                use(acct_access(addr, spec.g_extcode))
                push(len(state.code(addr)))

            def h_extcodecopy():
                addr = pop().to_bytes(32, "big")[12:]
                d, s, size = pop(), pop(), pop()
                use(acct_access(addr, spec.g_extcode)
                    + G_COPY_WORD * ((size + 31) // 32))
                ext = state.code(addr)
                mem_write(d, ext[s : s + size].ljust(size, b"\x00") if s < len(ext) else b"\x00" * size)

            def h_extcodehash():
                addr = pop().to_bytes(32, "big")[12:]
                use(acct_access(addr, spec.g_extcodehash))
                acc = state.account(addr)
                push(0 if acc is None or acc.is_empty else int.from_bytes(acc.code_hash, "big"))

            def h_blockhash():
                use(20); n = pop()
                h = self.block.block_hashes.get(n, b"")
                push(int.from_bytes(h, "big") if h else 0)

            def h_coinbase():
                use(2); push(int.from_bytes(self.block.coinbase, "big"))

            def h_timestamp():
                use(2); push(self.block.timestamp)

            def h_number():
                use(2); push(self.block.number)

            def h_prevrandao():
                # 0x44: DIFFICULTY before the merge, PREVRANDAO after
                use(2)
                if spec.merge:
                    push(int.from_bytes(self.block.prev_randao, "big"))
                else:
                    push(self.block.difficulty)

            def h_gaslimit():
                use(2); push(self.block.gas_limit)

            def h_chainid():
                use(2); push(self.block.chain_id)

            def h_selfbalance():
                use(5); push(state.balance(fr.address))

            def h_basefee():
                use(2); push(self.block.base_fee)

            def h_blobhash():
                use(3); i = pop()
                push(int.from_bytes(self.tx.blob_hashes[i], "big") if i < len(self.tx.blob_hashes) else 0)

            def h_blobbasefee():
                use(2); push(self.block.blob_base_fee)

            def h_mstore8():
                use(3); off, v = pop(), pop(); mem_write(off, bytes([v & 0xFF]))

            def h_pc():
                use(2); push(pc - 1)

            def h_msize():
                use(2); push(len(mem))

            def h_tload():
                use(100); slot = pop().to_bytes(32, "big")
                push(self.transient.get((fr.address, slot), 0))

            def h_tstore():
                if fr.static:
                    raise Halt()
                use(100); slot, v = pop().to_bytes(32, "big"), pop()
                self.transient[(fr.address, slot)] = v

            def h_mcopy():
                d, s, size = pop(), pop(), pop()
                use(3 + G_COPY_WORD * ((size + 31) // 32))
                data = mem_read(s, size)
                mem_write(d, data)

            def h_selfdestruct():
                if fr.static:
                    raise Halt()
                ben = pop().to_bytes(32, "big")[12:]
                cost = spec.g_selfdestruct
                if warm_cold and not state.warm_account(ben):
                    cost += G_COLD_ACCOUNT
                if spec.selfdestruct_new_account == "absent":  # EIP-150
                    if not state.exists(ben):
                        cost += G_NEW_ACCOUNT
                elif spec.selfdestruct_new_account == "dead_with_value":  # EIP-161
                    if state.balance(fr.address) and state.is_empty(ben):
                        cost += G_NEW_ACCOUNT
                use(cost)
                first = state.selfdestruct(
                    fr.address, ben,
                    same_tx_only=spec.selfdestruct_same_tx_only)
                if first and spec.r_selfdestruct:  # pre-London refund
                    state.add_refund(spec.r_selfdestruct)
                return gas, b""

            table = {
                0x05: h_sdiv, 0x07: h_smod, 0x08: h_addmod, 0x09: h_mulmod,
                0x0A: h_exp, 0x0B: h_signextend, 0x1A: h_byte,
                0x31: h_balance, 0x32: h_origin, 0x38: h_codesize,
                0x3A: h_gasprice, 0x3B: h_extcodesize, 0x3C: h_extcodecopy,
                0x40: h_blockhash, 0x41: h_coinbase,
                0x42: h_timestamp, 0x43: h_number, 0x44: h_prevrandao,
                0x45: h_gaslimit,
                0x53: h_mstore8, 0x58: h_pc, 0x59: h_msize,
                0xFF: h_selfdestruct,
            }
            # fork-gated entries: an absent entry falls through to the
            # invalid-opcode Halt below, which is exactly the pre-fork
            # behavior of an unassigned opcode
            if has_shifts:
                table[0x1D] = h_sar
            if spec.has_extcodehash:
                table[0x3F] = h_extcodehash
            if spec.has_chainid:
                table[0x46] = h_chainid
            if spec.has_selfbalance:
                table[0x47] = h_selfbalance
            if spec.has_basefee:
                table[0x48] = h_basefee
            if spec.has_blob_opcodes:
                table[0x49] = h_blobhash
                table[0x4A] = h_blobbasefee
            if spec.has_transient:
                table[0x5C] = h_tload
                table[0x5D] = h_tstore
            if spec.has_mcopy:
                table[0x5E] = h_mcopy
            return table

        code_len = len(code)
        while pc < code_len:
            op = code[pc]
            if tracer is not None:
                tracer(pc, op, gas, stack, mem, fr.depth)
            pc += 1
            # -- hot tier 1: stack manipulation (the most frequent ops) --
            if 0x5F <= op <= 0x7F:  # PUSH0..PUSH32
                n = op - 0x5F
                if n == 0 and not has_push0:  # EIP-3855
                    raise Halt()
                use(2 if n == 0 else 3)
                if len(stack) >= 1024:
                    raise Halt()
                if pc + n <= code_len:
                    push(int.from_bytes(code[pc : pc + n], "big"))
                else:
                    # truncated PUSH zero-pads on the RIGHT
                    # (execution-specs buffer_read semantics)
                    push(int.from_bytes(code[pc:].ljust(n, b"\x00"), "big"))
                pc += n
                continue
            if 0x80 <= op <= 0x8F:  # DUP1..DUP16
                use(3)
                i = op - 0x7F
                if len(stack) < i or len(stack) >= 1024:
                    raise Halt()
                push(stack[-i])
                continue
            if 0x90 <= op <= 0x9F:  # SWAP1..SWAP16
                use(3)
                i = op - 0x8F
                if len(stack) < i + 1:
                    raise Halt()
                stack[-1], stack[-i - 1] = stack[-i - 1], stack[-1]
                continue

            # -- hot tier 2: control flow, arithmetic, memory, storage --
            # ordered by measured frequency, NOT opcode value; everything
            # else dispatches through the cold table below
            if op == 0x5B:  # JUMPDEST
                use(1)
            elif op == 0x57:  # JUMPI
                use(10); dest, cond = pop(), pop()
                if cond:
                    if dest not in jumpdests:
                        raise Halt()
                    pc = dest
            elif op == 0x56:  # JUMP
                use(8); dest = pop()
                if dest not in jumpdests:
                    raise Halt()
                pc = dest
            elif op == 0x01:  # ADD
                use(3); a, b = pop(), pop(); push((a + b) & MASK)
            elif op == 0x03:  # SUB
                use(3); a, b = pop(), pop(); push((a - b) & MASK)
            elif op == 0x02:  # MUL
                use(5); a, b = pop(), pop(); push((a * b) & MASK)
            elif op == 0x04:  # DIV
                use(5); a, b = pop(), pop(); push(a // b if b else 0)
            elif op == 0x06:  # MOD
                use(5); a, b = pop(), pop(); push(a % b if b else 0)
            elif op == 0x15:  # ISZERO
                use(3); push(1 if pop() == 0 else 0)
            elif op == 0x14:  # EQ
                use(3); push(1 if pop() == pop() else 0)
            elif op == 0x10:  # LT
                use(3); push(1 if pop() < pop() else 0)
            elif op == 0x11:  # GT
                use(3); push(1 if pop() > pop() else 0)
            elif op == 0x12:  # SLT
                use(3); push(1 if _sgn(pop()) < _sgn(pop()) else 0)
            elif op == 0x13:  # SGT
                use(3); push(1 if _sgn(pop()) > _sgn(pop()) else 0)
            elif op == 0x16:  # AND
                use(3); push(pop() & pop())
            elif op == 0x17:  # OR
                use(3); push(pop() | pop())
            elif op == 0x18:  # XOR
                use(3); push(pop() ^ pop())
            elif op == 0x19:  # NOT
                use(3); push(pop() ^ MASK)
            elif op == 0x1B:  # SHL (Constantinople)
                if not has_shifts:
                    raise Halt()
                use(3); s, x = pop(), pop(); push((x << s) & MASK if s < 256 else 0)
            elif op == 0x1C:  # SHR (Constantinople)
                if not has_shifts:
                    raise Halt()
                use(3); s, x = pop(), pop(); push(x >> s if s < 256 else 0)
            elif op == 0x50:  # POP
                use(2); pop()
            elif op == 0x51:  # MLOAD
                use(3); off = pop(); push(int.from_bytes(mem_read(off, 32), "big"))
            elif op == 0x52:  # MSTORE
                use(3); off, v = pop(), pop(); mem_write(off, v.to_bytes(32, "big"))
            elif op == 0x35:  # CALLDATALOAD
                use(3); i = pop()
                push(int.from_bytes(fr.data[i : i + 32].ljust(32, b"\x00"), "big") if i < len(fr.data) else 0)
            elif op == 0x36:  # CALLDATASIZE
                use(2); push(len(fr.data))
            elif op == 0x54:  # SLOAD
                slot = pop().to_bytes(32, "big")
                if warm_cold:
                    use(G_WARM_ACCESS if state.warm_slot(fr.address, slot) else G_COLD_SLOAD)
                else:
                    use(spec.g_sload)
                push(state.sload(fr.address, slot))
            elif op == 0x55:  # SSTORE
                if fr.static:
                    raise Halt()
                if sstore_sentry and gas <= sstore_sentry:  # EIP-2200
                    raise Halt()
                slot, value = pop().to_bytes(32, "big"), pop()
                if not sstore_net:
                    # legacy metering (Frontier; also Petersburg, which
                    # reverted EIP-1283): 20000 zero→nonzero, 5000 otherwise
                    current = state.sload(fr.address, slot)
                    use(G_SSTORE_SET if current == 0 and value != 0 else 5000)
                    if current != 0 and value == 0:
                        state.add_refund(spec.r_sstore_clear)
                    if value != current:
                        state.sstore(fr.address, slot, value)
                else:
                    # net metering: EIP-1283 (load leg 200) / EIP-2200 (800)
                    # / post-Berlin (warm 100 + cold 2100 surcharge)
                    g_load = spec.g_sstore_load
                    reset_cost = G_SSTORE_RESET if warm_cold else 5000
                    cold_extra = 0
                    if warm_cold and not state.warm_slot(fr.address, slot):
                        cold_extra = G_COLD_SLOAD
                    current = state.sload(fr.address, slot)
                    original = state.original_storage(fr.address, slot)
                    if value == current:
                        cost = cold_extra + g_load
                    elif current == original:
                        cost = cold_extra + (G_SSTORE_SET if original == 0 else reset_cost)
                    else:
                        cost = cold_extra + g_load
                    use(cost)
                    r_clear = spec.r_sstore_clear
                    if value != current:
                        if current == original:
                            if original != 0 and value == 0:
                                state.add_refund(r_clear)
                        else:
                            if original != 0:
                                if current == 0:
                                    state.add_refund(-r_clear)
                                elif value == 0:
                                    state.add_refund(r_clear)
                            if value == original:
                                if original == 0:
                                    state.add_refund(G_SSTORE_SET - g_load)
                                else:
                                    state.add_refund(reset_cost - g_load)
                        state.sstore(fr.address, slot, value)
            elif op == 0x20:  # KECCAK256
                off, size = pop(), pop()
                use(G_KECCAK + G_KECCAK_WORD * ((size + 31) // 32))
                push(int.from_bytes(keccak256(mem_read(off, size)), "big"))
            elif op == 0x5A:  # GAS
                use(2); push(gas)
            elif op == 0x33:  # CALLER
                use(2); push(int.from_bytes(fr.caller, "big"))
            elif op == 0x34:  # CALLVALUE
                use(2); push(fr.value)
            elif op == 0x30:  # ADDRESS
                use(2); push(int.from_bytes(fr.address, "big"))
            elif op == 0x37:  # CALLDATACOPY
                d, s, size = pop(), pop(), pop()
                use(3 + G_COPY_WORD * ((size + 31) // 32))
                mem_write(d, fr.data[s : s + size].ljust(size, b"\x00") if s < len(fr.data) else b"\x00" * size)
            elif op == 0x39:  # CODECOPY
                d, s, size = pop(), pop(), pop()
                use(3 + G_COPY_WORD * ((size + 31) // 32))
                mem_write(d, code[s : s + size].ljust(size, b"\x00") if s < len(code) else b"\x00" * size)
            elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                if fr.static:
                    raise Halt()
                n = op - 0xA0
                off, size = pop(), pop()
                topics = tuple(pop().to_bytes(32, "big") for _ in range(n))
                use(G_LOG + G_LOG_TOPIC * n + G_LOG_BYTE * size)
                data = mem_read(off, size)
                from ..primitives.types import Log

                state.add_log(Log(fr.address, topics, data))
            elif op == 0x3D:  # RETURNDATASIZE (Byzantium)
                if not has_revert:
                    raise Halt()
                use(2); push(len(returndata))
            elif op == 0x3E:  # RETURNDATACOPY (Byzantium)
                if not has_revert:
                    raise Halt()
                d, s, size = pop(), pop(), pop()
                use(3 + G_COPY_WORD * ((size + 31) // 32))
                if s + size > len(returndata):
                    raise Halt()
                mem_write(d, returndata[s : s + size])
            elif op == 0x00:  # STOP
                return gas, b""
            elif op == 0xF3:  # RETURN
                off, size = pop(), pop()
                return gas, mem_read(off, size)
            elif op == 0xFD:  # REVERT (Byzantium)
                if not has_revert:
                    raise Halt()
                off, size = pop(), pop()
                r = Revert(mem_read(off, size))
                r.gas_left = gas
                raise r
            elif op in (0xF1, 0xF2, 0xF4, 0xFA):  # CALL/CALLCODE/DELEGATECALL/STATICCALL
                if op == 0xF4 and not spec.has_delegatecall:  # Homestead
                    raise Halt()
                if op == 0xFA and not has_revert:  # Byzantium
                    raise Halt()
                g = pop()
                addr = pop().to_bytes(32, "big")[12:]
                value = pop() if op in (0xF1, 0xF2) else 0
                ain, ains, aout, aouts = pop(), pop(), pop(), pop()
                if fr.static and value and op == 0xF1:
                    raise Halt()
                if warm_cold:
                    extra = G_WARM_ACCESS if state.warm_account(addr) else G_COLD_ACCOUNT
                else:
                    extra = spec.g_call
                if value:
                    extra += G_CALL_VALUE
                if op == 0xF1:
                    # new-account surcharge: pre-EIP-161 whenever the target
                    # is absent; after, only for a value transfer to a dead
                    # account
                    if spec.new_account_charge_always:
                        if not state.exists(addr):
                            extra += G_NEW_ACCOUNT
                    elif value and state.is_empty(addr):
                        extra += G_NEW_ACCOUNT
                use(extra)
                if spec.has_setcode:
                    # EIP-7702: a delegation designator executes the
                    # delegate's code (one level, delegate access charged)
                    run_code, tgt = resolve_delegation(state, addr)
                    if tgt is not None:
                        use(G_WARM_ACCESS if state.warm_account(tgt) else G_COLD_ACCOUNT)
                else:
                    run_code = state.code(addr)
                data = mem_read(ain, ains)
                mem_expand(aout, aouts)
                if spec.call_63_64:
                    child_gas = min(g, gas - gas // 64)
                else:  # pre-EIP-150: the requested gas, or out-of-gas
                    child_gas = g
                use(child_gas)
                if value:
                    child_gas += G_CALL_STIPEND
                if op == 0xF1:  # CALL
                    sub = CallFrame(fr.address, addr, run_code, data, value,
                                    child_gas, fr.static, fr.depth + 1, kind="CALL")
                elif op == 0xF2:  # CALLCODE
                    sub = CallFrame(fr.address, fr.address, run_code, data,
                                    value, child_gas, fr.static, fr.depth + 1,
                                    kind="CALLCODE")
                elif op == 0xF4:  # DELEGATECALL: parent's value/caller, NO transfer
                    sub = CallFrame(fr.caller, fr.address, run_code, data,
                                    fr.value, child_gas, fr.static, fr.depth + 1,
                                    transfer_value=False, kind="DELEGATECALL")
                else:  # STATICCALL
                    sub = CallFrame(fr.address, addr, run_code, data, 0,
                                    child_gas, True, fr.depth + 1, kind="STATICCALL")
                try:
                    ok, gas_left, out = yield ("call", sub)
                except Revert as r:
                    # child reverted: its unused gas comes back, output exposed
                    ok, out = False, r.output
                    gas_left = getattr(r, "gas_left", 0)
                gas += gas_left
                returndata = out
                mem[aout : aout + min(aouts, len(out))] = out[: aouts]
                push(1 if ok else 0)
            elif op == 0xF0 or op == 0xF5:  # CREATE / CREATE2
                if op == 0xF5 and not spec.has_create2:  # Constantinople
                    raise Halt()
                if fr.static:
                    raise Halt()
                value = pop(); off = pop(); size = pop()
                salt = pop().to_bytes(32, "big") if op == 0xF5 else None
                words = (size + 31) // 32
                use(G_CREATE
                    + (G_INITCODE_WORD * words if spec.initcode_limit else 0)
                    + (G_KECCAK_WORD * words if op == 0xF5 else 0))
                if spec.initcode_limit and size > MAX_INITCODE_SIZE:
                    raise Halt()
                initcode = mem_read(off, size)
                child_gas = gas - gas // 64 if spec.call_63_64 else gas
                use(child_gas)
                ok, gas_left, addr, out = yield (
                    "create",
                    (fr.address, value, initcode, child_gas, fr.depth + 1, salt),
                )
                gas += gas_left
                returndata = out
                push(int.from_bytes(addr, "big") if ok else 0)
            elif op == 0xFE:  # INVALID
                raise Halt()
            else:
                # -- cold tier: table dispatch ---------------------------
                if cold is None:
                    cold = _build_cold()
                h = cold.get(op)
                if h is None:
                    raise Halt()
                res = h()
                if res is not None:  # SELFDESTRUCT ends the frame
                    return res
        return gas, b""


def _sgn(x: int) -> int:
    return x - U256 if x & SIGN_BIT else x


_JUMPDEST_CACHE: dict[bytes, set[int]] = {}


def _jumpdests_cached(code: bytes) -> set[int]:
    """Per-code jumpdest analysis, cached: the scan is O(len(code)) and a
    hot contract is entered thousands of times per block (revm caches its
    analysis on the bytecode object the same way). Keyed by the code
    bytes — their hash is computed once and cached by CPython."""
    dests = _JUMPDEST_CACHE.get(code)
    if dests is None:
        if len(_JUMPDEST_CACHE) >= 1024:
            _JUMPDEST_CACHE.clear()  # bounded; rebuild is cheap
        dests = _jumpdests(code)
        _JUMPDEST_CACHE[code] = dests
    return dests


def _jumpdests(code: bytes) -> set[int]:
    dests = set()
    i = 0
    n = len(code)
    while i < n:
        op = code[i]
        if op == 0x5B:
            dests.add(i)
        if 0x60 <= op <= 0x7F:
            i += op - 0x5F
        i += 1
    return dests


# -- precompiles --------------------------------------------------------------


def _pre_ecrecover(data: bytes, gas: int):
    if gas < 3000:
        return False, 0, b""
    gas -= 3000
    data = data.ljust(128, b"\x00")[:128]
    h = data[:32]
    v = int.from_bytes(data[32:64], "big")
    r = int.from_bytes(data[64:96], "big")
    s = int.from_bytes(data[96:128], "big")
    if v not in (27, 28):
        return True, gas, b""
    from ..primitives.secp256k1 import ecrecover

    try:
        addr = ecrecover(h, v - 27, r, s, allow_high_s=True)
    except ValueError:
        return True, gas, b""
    return True, gas, addr.rjust(32, b"\x00")


def _pre_sha256(data: bytes, gas: int):
    cost = 60 + 12 * ((len(data) + 31) // 32)
    if gas < cost:
        return False, 0, b""
    return True, gas - cost, hashlib.sha256(data).digest()


def _pre_ripemd160(data: bytes, gas: int):
    cost = 600 + 120 * ((len(data) + 31) // 32)
    if gas < cost:
        return False, 0, b""
    try:
        h = hashlib.new("ripemd160", data).digest()
    except ValueError:
        return False, 0, b""
    return True, gas - cost, h.rjust(32, b"\x00")


def _pre_identity(data: bytes, gas: int):
    cost = 15 + 3 * ((len(data) + 31) // 32)
    if gas < cost:
        return False, 0, b""
    return True, gas - cost, data


def _pre_modexp(data: bytes, gas: int, eip2565: bool = True):
    """0x05 modexp: EIP-2565 pricing (Berlin) or EIP-198 (Byzantium)."""
    data = bytes(data)
    bl = int.from_bytes(data[0:32].ljust(32, b"\x00"), "big")
    el = int.from_bytes(data[32:64].ljust(32, b"\x00"), "big")
    ml = int.from_bytes(data[64:96].ljust(32, b"\x00"), "big")
    if bl > 4096 or el > 4096 or ml > 4096:
        return False, 0, b""
    body = data[96:].ljust(bl + el + ml, b"\x00")
    b_ = int.from_bytes(body[:bl], "big")
    e_ = int.from_bytes(body[bl : bl + el], "big")
    m_ = int.from_bytes(body[bl + el : bl + el + ml], "big")
    # adjusted exponent length (shared by both pricings): full bit length
    # for short exponents, else 8*(len-32) + bits of the leading 32 bytes
    head = int.from_bytes(body[bl : bl + min(32, el)], "big")
    if el <= 32:
        adj = head.bit_length() - 1 if head else 0
    else:
        adj = 8 * (el - 32) + (head.bit_length() - 1 if head else 0)
    if eip2565:
        words = (max(bl, ml) + 7) // 8
        cost = max(200, words * words * max(1, adj) // 3)
    else:  # EIP-198
        x = max(bl, ml)
        if x <= 64:
            mult = x * x
        elif x <= 1024:
            mult = x * x // 4 + 96 * x - 3072
        else:
            mult = x * x // 16 + 480 * x - 199_680
        cost = mult * max(1, adj) // 20
    if gas < cost:
        return False, 0, b""
    out = pow(b_, e_, m_).to_bytes(ml, "big") if m_ else b"\x00" * ml
    return True, gas - cost, out


def _bn_g1_point(data: bytes):
    """64-byte (x, y) -> validated bn254 G1 point; raises on bad input."""
    from ..primitives.pairing import BN254, g1_group

    x = int.from_bytes(data[:32], "big")
    y = int.from_bytes(data[32:64], "big")
    if x == 0 and y == 0:
        return None
    if x >= BN254.p or y >= BN254.p or not g1_group(BN254).on_curve((x, y)):
        raise ValueError("invalid bn254 G1 point")
    return (x, y)


def _pre_bn_add(data: bytes, gas: int, price: int = 150):
    """0x06 alt_bn128 ADD (EIP-196; 500 gas, 150 since EIP-1108)."""
    if gas < price:
        return False, 0, b""
    gas -= price
    from ..primitives.pairing import BN254, g1_group

    data = data.ljust(128, b"\x00")[:128]
    try:
        a = _bn_g1_point(data[0:64])
        b = _bn_g1_point(data[64:128])
    except ValueError:
        return False, 0, b""
    s = g1_group(BN254).padd(a, b)
    if s is None:
        return True, gas, b"\x00" * 64
    return True, gas, s[0].to_bytes(32, "big") + s[1].to_bytes(32, "big")


def _pre_bn_mul(data: bytes, gas: int, price: int = 6000):
    """0x07 alt_bn128 MUL (EIP-196; 40000 gas, 6000 since EIP-1108)."""
    if gas < price:
        return False, 0, b""
    gas -= price
    from ..primitives.pairing import BN254, g1_group

    data = data.ljust(96, b"\x00")[:96]
    try:
        a = _bn_g1_point(data[0:64])
    except ValueError:
        return False, 0, b""
    k = int.from_bytes(data[64:96], "big")
    s = g1_group(BN254).mul_scalar(a, k) if a is not None else None
    if s is None:
        return True, gas, b"\x00" * 64
    return True, gas, s[0].to_bytes(32, "big") + s[1].to_bytes(32, "big")


def _pre_bn_pairing(data: bytes, gas: int, base: int = 45_000, per: int = 34_000):
    """0x08 alt_bn128 pairing check (EIP-197; EIP-1108 gas). G2 Fp2
    coordinates arrive imaginary-part first: [x_c1, x_c0, y_c1, y_c0]."""
    if len(data) % 192 != 0:
        return False, 0, b""
    k = len(data) // 192
    cost = base + per * k
    if gas < cost:
        return False, 0, b""
    gas -= cost
    from ..primitives.pairing import BN254, g2_valid, pairing_product_is_one

    pairs = []
    for i in range(k):
        chunk = data[i * 192 : (i + 1) * 192]
        try:
            p1 = _bn_g1_point(chunk[0:64])
        except ValueError:
            return False, 0, b""
        x = (int.from_bytes(chunk[96:128], "big"), int.from_bytes(chunk[64:96], "big"))
        y = (int.from_bytes(chunk[160:192], "big"), int.from_bytes(chunk[128:160], "big"))
        q2 = None if x == (0, 0) and y == (0, 0) else (x, y)
        if q2 is not None and not g2_valid(q2, BN254):
            return False, 0, b""
        if p1 is not None and q2 is not None:
            pairs.append((p1, q2))
    ok = pairing_product_is_one(pairs, BN254) if pairs else True
    return True, gas, (1 if ok else 0).to_bytes(32, "big")


def _pre_blake2f(data: bytes, gas: int):
    """0x09 blake2b F compression (EIP-152)."""
    if len(data) != 213:
        return False, 0, b""
    rounds = int.from_bytes(data[0:4], "big")
    final = data[212]
    if final not in (0, 1):
        return False, 0, b""
    if gas < rounds:
        return False, 0, b""
    from ..primitives.blake2 import blake2f

    h = [int.from_bytes(data[4 + 8 * i : 12 + 8 * i], "little") for i in range(8)]
    m = [int.from_bytes(data[68 + 8 * i : 76 + 8 * i], "little") for i in range(16)]
    t0 = int.from_bytes(data[196:204], "little")
    t1 = int.from_bytes(data[204:212], "little")
    out = blake2f(rounds, h, m, t0, t1, final == 1)
    return True, gas - rounds, b"".join(v.to_bytes(8, "little") for v in out)


def _pre_point_eval(data: bytes, gas: int):
    """0x0a KZG point evaluation (EIP-4844): verify p(z) == y against a
    versioned-hash-bound commitment."""
    if gas < 50000:
        return False, 0, b""
    gas -= 50000
    if len(data) != 192:
        return False, 0, b""
    from ..primitives import kzg

    versioned_hash = data[0:32]
    z = int.from_bytes(data[32:64], "big")
    y = int.from_bytes(data[64:96], "big")
    commitment_b = data[96:144]
    proof_b = data[144:192]
    if z >= kzg.BLS_MODULUS or y >= kzg.BLS_MODULUS:
        return False, 0, b""
    if kzg.kzg_to_versioned_hash(commitment_b) != versioned_hash:
        return False, 0, b""
    try:
        commitment = kzg.g1_from_bytes(commitment_b)
        proof = kzg.g1_from_bytes(proof_b)
    except kzg.KzgError:
        return False, 0, b""
    if not kzg.verify_kzg_proof(commitment, z, y, proof):
        return False, 0, b""
    out = kzg.FIELD_ELEMENTS_PER_BLOB.to_bytes(32, "big") + kzg.BLS_MODULUS.to_bytes(32, "big")
    return True, gas, out


class PrecompileNotImplemented(NotImplementedError):
    """A precompile in the active fork's address range whose operation this
    repo cannot faithfully implement. Raised INSTEAD of behaving like an
    empty account: a silent stub would produce a wrong-but-plausible state
    root and break the native/interpreter bit-identical invariant without
    anyone noticing (round-5 verdict). The block executor surfaces this as
    a BlockExecutionError — loud, block-invalidating, grep-able."""


def _pre_bls_g1add(data: bytes, gas: int):
    """0x0b BLS12_G1ADD (EIP-2537): 375 gas, no subgroup check."""
    if gas < 375:
        return False, 0, b""
    from ..primitives import bls12381 as bls

    try:
        out = bls.g1add_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - 375, out


def _pre_bls_g2add(data: bytes, gas: int):
    """0x0d BLS12_G2ADD (EIP-2537): 600 gas, no subgroup check."""
    if gas < 600:
        return False, 0, b""
    from ..primitives import bls12381 as bls

    try:
        out = bls.g2add_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - 600, out


def _pre_bls_g1msm(data: bytes, gas: int):
    """0x0c BLS12_G1MSM (EIP-2537): discounted per-pair gas, curve AND
    subgroup check on every input point."""
    from ..primitives import bls12381 as bls

    if len(data) == 0 or len(data) % 160 != 0:
        return False, 0, b""
    cost = bls.g1msm_gas(len(data) // 160)
    if gas < cost:
        return False, 0, b""
    try:
        out = bls.g1msm_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - cost, out


def _pre_bls_g2msm(data: bytes, gas: int):
    """0x0e BLS12_G2MSM (EIP-2537): discounted per-pair gas, curve AND
    subgroup check on every input point."""
    from ..primitives import bls12381 as bls

    if len(data) == 0 or len(data) % 288 != 0:
        return False, 0, b""
    cost = bls.g2msm_gas(len(data) // 288)
    if gas < cost:
        return False, 0, b""
    try:
        out = bls.g2msm_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - cost, out


def _pre_bls_pairing(data: bytes, gas: int):
    """0x0f BLS12_PAIRING_CHECK (EIP-2537): per-pair gas, curve AND
    subgroup check on every input point, 32-byte 0/1 output."""
    from ..primitives import bls12381 as bls

    if len(data) == 0 or len(data) % 384 != 0:
        return False, 0, b""
    cost = bls.pairing_gas(len(data) // 384)
    if gas < cost:
        return False, 0, b""
    try:
        out = bls.pairing_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - cost, out


def _pre_bls_map_fp_to_g1(data: bytes, gas: int):
    """0x10 BLS12_MAP_FP_TO_G1 (EIP-2537): 5500 gas, RFC 9380 SSWU +
    11-isogeny + effective-cofactor clearing."""
    from ..primitives import bls12381 as bls

    if gas < bls.MAP_FP_TO_G1_GAS:
        return False, 0, b""
    try:
        out = bls.map_fp_to_g1_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - bls.MAP_FP_TO_G1_GAS, out


def _pre_bls_map_fp2_to_g2(data: bytes, gas: int):
    """0x11 BLS12_MAP_FP2_TO_G2 (EIP-2537): 23800 gas, RFC 9380 SSWU +
    3-isogeny + effective-cofactor clearing."""
    from ..primitives import bls12381 as bls

    if gas < bls.MAP_FP2_TO_G2_GAS:
        return False, 0, b""
    try:
        out = bls.map_fp2_to_g2_precompile(bytes(data))
    except bls.BlsError:
        return False, 0, b""
    return True, gas - bls.MAP_FP2_TO_G2_GAS, out


_RAW_PRECOMPILES = {
    1: _pre_ecrecover,
    2: _pre_sha256,
    3: _pre_ripemd160,
    4: _pre_identity,
    5: _pre_modexp,
    6: _pre_bn_add,
    7: _pre_bn_mul,
    8: _pre_bn_pairing,
    9: _pre_blake2f,
    10: _pre_point_eval,
    # EIP-2537 (Prague): the full table — affine ADD/MSM with subgroup
    # checks, the pairing check over primitives/pairing.py, and the RFC
    # 9380 SSWU+isogeny maps (primitives/bls12381.py)
    11: _pre_bls_g1add,
    12: _pre_bls_g1msm,
    13: _pre_bls_g2add,
    14: _pre_bls_g2msm,
    15: _pre_bls_pairing,
    16: _pre_bls_map_fp_to_g1,
    17: _pre_bls_map_fp2_to_g2,
}

# -- precompile result cache (reference engine/tree precompile_cache.rs) ------
# Precompiles are pure: (index, input) fully determines the output and the
# charged gas. The expensive ones (modexp, bn254 add/mul/pairing, KZG point
# evaluation, ecrecover) cache their successful results across calls,
# transactions, and blocks; failures are gas-dependent and never cached.

from collections import OrderedDict as _OrderedDict
from threading import Lock as _Lock

_PRECOMPILE_CACHE: "_OrderedDict[tuple[int, bytes], tuple[int, bytes]]" = _OrderedDict()
_PRECOMPILE_CACHE_MAX = 2048
_CACHED_INDICES = frozenset({1, 5, 6, 7, 8, 10, 15, 16, 17})
# prewarm workers overlap canonical execution (engine/tree.py starts
# PrewarmTask without joining), so the LRU bookkeeping must be guarded —
# an unguarded get()+move_to_end can race a popitem eviction
_PRECOMPILE_CACHE_LOCK = _Lock()
precompile_cache_stats = {"hits": 0, "misses": 0}


def _cached_precompile(idx: int, fn, era: str = ""):
    def run(data, gas: int):
        key = (idx, era, bytes(data))
        with _PRECOMPILE_CACHE_LOCK:
            hit = _PRECOMPILE_CACHE.get(key)
            if hit is not None:
                _PRECOMPILE_CACHE.move_to_end(key)
                precompile_cache_stats["hits"] += 1
            else:
                precompile_cache_stats["misses"] += 1
        if hit is not None:
            charged, out = hit
            if gas < charged:
                return False, 0, b""
            return True, gas - charged, out
        ok, gas_left, out = fn(data, gas)
        if ok:
            with _PRECOMPILE_CACHE_LOCK:
                _PRECOMPILE_CACHE[key] = (gas - gas_left, out)
                while len(_PRECOMPILE_CACHE) > _PRECOMPILE_CACHE_MAX:
                    _PRECOMPILE_CACHE.popitem(last=False)
        return ok, gas_left, out

    return run


# per-era dispatch tables: precompile availability and pricing both vary
# by fork (reference: revm builds its precompile set per SpecId)
_ERA_TABLES: dict[tuple, dict] = {}


def _era_table(spec) -> dict:
    key = (min(spec.precompiles, 17), spec.modexp_eip2565, spec.bn_add_gas)
    table = _ERA_TABLES.get(key)
    if table is not None:
        return table
    import functools

    table = {i: _RAW_PRECOMPILES[i] for i in range(1, key[0] + 1)}
    if 5 in table and not spec.modexp_eip2565:
        table[5] = functools.partial(_pre_modexp, eip2565=False)
    if 6 in table and spec.bn_add_gas != 150:
        table[6] = functools.partial(_pre_bn_add, price=spec.bn_add_gas)
        table[7] = functools.partial(_pre_bn_mul, price=spec.bn_mul_gas)
        table[8] = functools.partial(_pre_bn_pairing, base=spec.bn_pair_base,
                                     per=spec.bn_pair_per)
    era = f"{int(spec.modexp_eip2565)}:{spec.bn_add_gas}"
    for i in _CACHED_INDICES:
        if i in table:
            table[i] = _cached_precompile(i, table[i], era)
    _ERA_TABLES[key] = table
    return table


_PRECOMPILES = _era_table(LATEST_SPEC)  # latest-rules table (tests, tools)


def _precompile(address: bytes, spec: Spec | None = None):
    if spec is None:
        spec = LATEST_SPEC
    if address[:19] == b"\x00" * 19 and 1 <= address[19] <= spec.precompiles:
        return _era_table(spec).get(address[19])
    return None
