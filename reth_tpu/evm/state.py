"""Journaled EVM state with changeset capture.

Reference analogue: revm's `Journal`/`State` + reth's
`StateProviderDatabase` adapter (crates/revm/src/database.rs) and the
changeset output consumed by `ExecutionStage`. Reads fall through to a
state source (the provider's plain state); writes are journaled so call
frames can revert, and per-block previous-images are captured for the
AccountChangeSets/StorageChangeSets tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..primitives.keccak import keccak256
from ..primitives.types import Account, DELEGATION_PREFIX, KECCAK_EMPTY, Log


def resolve_delegation(state, address: bytes) -> tuple[bytes, bytes | None]:
    """Code to EXECUTE for a call to ``address`` (EIP-7702, one level).

    Returns (code, delegate) — ``delegate`` is the designated target whose
    account-access cost the caller must charge, or None when the account's
    code is not a delegation designator. EXTCODE* opcodes must NOT use
    this: they observe the designator itself."""
    code = state.code(address)
    if code[:3] == DELEGATION_PREFIX and len(code) == 23:
        target = code[3:]
        return state.code(target), target
    return code, None


class StateSource:
    """Read interface the EVM pulls cold state through (StateProvider)."""

    def account(self, address: bytes) -> Account | None:
        raise NotImplementedError

    def storage(self, address: bytes, slot: bytes) -> int:
        raise NotImplementedError

    def bytecode(self, code_hash: bytes) -> bytes:
        raise NotImplementedError


@dataclass
class BlockChanges:
    """Previous-images of everything a block touched (changeset rows)."""

    accounts: dict[bytes, Account | None] = field(default_factory=dict)
    storage: dict[bytes, dict[bytes, int]] = field(default_factory=dict)
    wiped_storage: set[bytes] = field(default_factory=set)
    new_bytecodes: dict[bytes, bytes] = field(default_factory=dict)


class EvmState:
    """Mutable world state for one block's execution."""

    def __init__(self, source: StateSource):
        self.source = source
        self._accounts: dict[bytes, Account | None] = {}
        self._storage: dict[bytes, dict[bytes, int]] = {}
        self._code: dict[bytes, bytes] = {}
        self._journal: list[tuple] = []
        self._logs: list[Log] = []
        self.refund: int = 0
        # EIP-2929 warm sets (reset per transaction)
        self.warm_accounts: set[bytes] = set()
        self.warm_slots: set[tuple[bytes, bytes]] = set()
        self._selfdestructs: set[bytes] = set()
        self._created: set[bytes] = set()
        # destruct bookkeeping: accounts marked by SELFDESTRUCT this tx
        # (refund-once tracking) and those scheduled for end-of-tx deletion
        self._destruct_marks: set[bytes] = set()
        self._pending_destructs: set[bytes] = set()
        self._tx_original: dict[tuple[bytes, bytes], int] = {}
        # block-level changeset capture
        self.changes = BlockChanges()
        self._touched: set[bytes] = set()  # EIP-161 touched-empty tracking

    # -- account reads -------------------------------------------------------

    def account(self, address: bytes) -> Account | None:
        if address not in self._accounts:
            self._accounts[address] = self.source.account(address)
        return self._accounts[address]

    def account_or_empty(self, address: bytes) -> Account:
        return self.account(address) or Account()

    def balance(self, address: bytes) -> int:
        return self.account_or_empty(address).balance

    def nonce(self, address: bytes) -> int:
        return self.account_or_empty(address).nonce

    def code(self, address: bytes) -> bytes:
        acc = self.account(address)
        if acc is None or acc.code_hash == KECCAK_EMPTY:
            return b""
        if acc.code_hash not in self._code:
            self._code[acc.code_hash] = self.source.bytecode(acc.code_hash)
        return self._code[acc.code_hash]

    def exists(self, address: bytes) -> bool:
        return self.account(address) is not None

    def is_empty(self, address: bytes) -> bool:
        acc = self.account(address)
        return acc is None or acc.is_empty

    # -- storage -------------------------------------------------------------

    def sload(self, address: bytes, slot: bytes) -> int:
        per = self._storage.setdefault(address, {})
        if slot not in per:
            if address in self._created or address in self._selfdestructs:
                per[slot] = 0
            else:
                per[slot] = self.source.storage(address, slot)
        return per[slot]

    def original_storage(self, address: bytes, slot: bytes) -> int:
        """Value at TRANSACTION start (SSTORE gas/refunds, EIP-2200/3529)."""
        key = (address, slot)
        if key in self._tx_original:
            return self._tx_original[key]
        return self.sload(address, slot)

    def sstore(self, address: bytes, slot: bytes, value: int):
        prev = self.sload(address, slot)
        self._tx_original.setdefault((address, slot), prev)
        self._capture_storage_change(address, slot, prev)
        self._journal.append(("storage", address, slot, prev))
        self._storage[address][slot] = value

    # -- account writes ------------------------------------------------------

    def _capture_account_change(self, address: bytes):
        if address not in self.changes.accounts:
            # previous image = value at block start (source), unless already
            # modified this block — then the first capture already holds it.
            self.changes.accounts[address] = self.source.account(address)

    def _capture_storage_change(self, address: bytes, slot: bytes, prev: int):
        per = self.changes.storage.setdefault(address, {})
        if slot not in per:
            if address in self._created or address in self._selfdestructs or address in self.changes.wiped_storage:
                per[slot] = 0
            else:
                per[slot] = self.source.storage(address, slot)

    def _set_account(self, address: bytes, account: Account | None):
        self._capture_account_change(address)
        self._journal.append(("account", address, self._accounts.get(address, self.source.account(address))))
        self._accounts[address] = account

    def set_balance(self, address: bytes, balance: int):
        self._set_account(address, self.account_or_empty(address).with_(balance=balance))
        self._touched.add(address)

    def add_balance(self, address: bytes, amount: int):
        self.set_balance(address, self.balance(address) + amount)

    def sub_balance(self, address: bytes, amount: int):
        bal = self.balance(address)
        assert bal >= amount, "insufficient balance"
        self.set_balance(address, bal - amount)

    def set_nonce(self, address: bytes, nonce: int):
        self._set_account(address, self.account_or_empty(address).with_(nonce=nonce))

    def bump_nonce(self, address: bytes):
        self.set_nonce(address, self.nonce(address) + 1)

    def set_code(self, address: bytes, code: bytes):
        code_hash = keccak256(code) if code else KECCAK_EMPTY
        if code:
            self._code[code_hash] = code
            self.changes.new_bytecodes[code_hash] = code
        self._set_account(address, self.account_or_empty(address).with_(code_hash=code_hash))

    def create_account(self, address: bytes, nonce: int = 1):
        """Mark an account created by CREATE/CREATE2 (storage resets).
        EIP-161 starts contracts at nonce 1; pre-Spurious forks pass 0."""
        self._capture_account_change(address)
        self._journal.append(("create", address, self._accounts.get(address, self.source.account(address)), address in self._created))
        self._created.add(address)
        prev = self.account(address)
        balance = prev.balance if prev else 0
        self._accounts[address] = Account(nonce=nonce, balance=balance)
        self._storage[address] = {}

    def selfdestruct(self, address: bytes, beneficiary: bytes,
                     same_tx_only: bool = True) -> bool:
        """SELFDESTRUCT. With ``same_tx_only`` (EIP-6780, Cancun) a
        pre-existing account is NOT destroyed — pure balance move; before
        Cancun every destruct deletes the account. Deletion itself happens
        at END of transaction (``process_destructs``): until then the code
        keeps executing if called again, exactly per spec. Returns True on
        the first mark of ``address`` this tx (pre-London refund-once)."""
        bal = self.balance(address)
        first = address not in self._destruct_marks
        if first:
            self._journal.append(("destruct_mark", address))
            self._destruct_marks.add(address)
        destroys = (address in self._created) or not same_tx_only
        if not destroys:
            # EIP-6780 with a pre-existing account: balance move only
            # (self-beneficiary is a no-op)
            self.set_balance(address, 0)
            self.add_balance(beneficiary, bal)
            return first
        if address not in self._pending_destructs:
            self._journal.append(("destruct_pending", address))
            self._pending_destructs.add(address)
        if beneficiary != address:
            self.set_balance(address, 0)
            self.add_balance(beneficiary, bal)
        # beneficiary == address: balance stays and burns with the deletion
        return first

    def process_destructs(self):
        """End-of-tx deletion of selfdestructed accounts (+ storage wipe)."""
        for address in self._pending_destructs:
            self._capture_account_change(address)
            self._accounts[address] = None
            self._storage[address] = {}
            self._selfdestructs.add(address)
            self.changes.wiped_storage.add(address)
        self._pending_destructs = set()

    # -- logs / journal ------------------------------------------------------

    def add_log(self, log: Log):
        self._journal.append(("log", len(self._logs)))
        self._logs.append(log)

    def add_refund(self, amount: int):
        self._journal.append(("refund", self.refund))
        self.refund += amount

    def warm_account(self, address: bytes) -> bool:
        """Warm an account; returns True if it was already warm."""
        if address in self.warm_accounts:
            return True
        self._journal.append(("warm_acct", address))
        self.warm_accounts.add(address)
        return False

    def warm_slot(self, address: bytes, slot: bytes) -> bool:
        key = (address, slot)
        if key in self.warm_slots:
            return True
        self._journal.append(("warm_slot", key))
        self.warm_slots.add(key)
        return False

    def snapshot(self) -> int:
        return len(self._journal)

    def revert(self, snap: int):
        while len(self._journal) > snap:
            entry = self._journal.pop()
            kind = entry[0]
            if kind == "storage":
                _, addr, slot, prev = entry
                self._storage[addr][slot] = prev
            elif kind == "account":
                _, addr, prev = entry
                self._accounts[addr] = prev
            elif kind == "create":
                _, addr, prev, was_created = entry
                self._accounts[addr] = prev
                if not was_created:
                    self._created.discard(addr)
                self._storage.pop(addr, None)
            elif kind == "selfdestruct":
                _, addr, prev, storage, was_dead = entry
                self._accounts[addr] = prev
                self._storage[addr] = storage
                if not was_dead:
                    self._selfdestructs.discard(addr)
                    self.changes.wiped_storage.discard(addr)
            elif kind == "log":
                del self._logs[entry[1] :]
            elif kind == "refund":
                self.refund = entry[1]
            elif kind == "warm_acct":
                self.warm_accounts.discard(entry[1])
            elif kind == "warm_slot":
                self.warm_slots.discard(entry[1])
            elif kind == "destruct_mark":
                self._destruct_marks.discard(entry[1])
            elif kind == "destruct_pending":
                self._pending_destructs.discard(entry[1])

    def take_logs(self) -> list[Log]:
        logs = self._logs
        self._logs = []
        return logs

    def begin_tx(self):
        """Per-transaction resets (EIP-2929 warm sets, refund counter).
        Finalizes the previous tx's pending destructs first, so a caller
        that skips the explicit ``process_destructs`` cannot lose them."""
        self.process_destructs()
        self.warm_accounts = set()
        self.warm_slots = set()
        self.refund = 0
        self._created = set()
        self._destruct_marks = set()
        self._pending_destructs = set()
        self._tx_original = {}
        self._journal.clear()

    def delete_empty_touched(self):
        """EIP-161: remove touched empty accounts at tx end."""
        for addr in self._touched:
            acc = self._accounts.get(addr)
            if acc is not None and acc.is_empty:
                self._capture_account_change(addr)
                self._accounts[addr] = None
        self._touched = set()

    # -- post-block ----------------------------------------------------------

    def final_state(self) -> tuple[dict[bytes, Account | None], dict[bytes, dict[bytes, int]]]:
        """Post-block accounts and storage values for everything touched."""
        self.process_destructs()
        accounts = {a: self._accounts.get(a) for a in self.changes.accounts}
        storage: dict[bytes, dict[bytes, int]] = {}
        for addr, slots in self.changes.storage.items():
            cur = self._storage.get(addr, {})
            storage[addr] = {s: cur.get(s, 0) for s in slots}
        return accounts, storage
