"""Per-fork EVM rule sets (revm ``SpecId`` analogue).

Reference analogue: reth selects a revm ``SpecId`` per block from the
chainspec (crates/ethereum/evm/src/config.rs:2-3 re-exporting
``spec_by_timestamp_and_block_number``); revm then branches its opcode
table, gas schedule, and host rules on it. Here the same idea is a frozen
:class:`Spec` of feature flags + gas parameters, built by layering
per-fork deltas in ``HARDFORK_ORDER`` — each hardfork is literally a diff
against the previous rule set, which is how the EIPs themselves are
written.

``Interpreter`` and ``BlockExecutor`` read everything fork-dependent from
the active ``Spec``; ``ChainSpec.spec_at`` picks the fork name per block.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..chainspec import (
    BERLIN,
    BYZANTIUM,
    CANCUN,
    CONSTANTINOPLE,
    FRONTIER,
    HARDFORK_ORDER,
    HOMESTEAD,
    ISTANBUL,
    LONDON,
    OSAKA,
    PARIS,
    PETERSBURG,
    PRAGUE,
    SHANGHAI,
    SPURIOUS_DRAGON,
    TANGERINE,
    BlobParams,
    ChainSpec,
)

ETHER = 10**18

CANCUN_BLOBS = BlobParams(target=3, max=6, update_fraction=3_338_477)
PRAGUE_BLOBS = BlobParams(target=6, max=9, update_fraction=5_007_716)


@dataclass(frozen=True)
class Spec:
    """One fork's complete EVM rule set. Grouped by the subsystem that
    consumes each field; every activation cited to its EIP."""

    name: str = FRONTIER

    # -- opcode availability ------------------------------------------------
    has_delegatecall: bool = False   # Homestead (EIP-7)
    has_revert: bool = False         # Byzantium: REVERT/RETURNDATA*/STATICCALL
    has_shifts: bool = False         # Constantinople (EIP-145)
    has_create2: bool = False        # Constantinople (EIP-1014)
    has_extcodehash: bool = False    # Constantinople (EIP-1052)
    has_chainid: bool = False        # Istanbul (EIP-1344)
    has_selfbalance: bool = False    # Istanbul (EIP-1884)
    has_basefee: bool = False        # London (EIP-3198)
    has_push0: bool = False          # Shanghai (EIP-3855)
    has_transient: bool = False      # Cancun (EIP-1153)
    has_mcopy: bool = False          # Cancun (EIP-5656)
    has_blob_opcodes: bool = False   # Cancun (EIP-4844/7516)
    merge: bool = False              # Paris: PREVRANDAO, no PoW rewards

    # -- account-access pricing --------------------------------------------
    warm_cold: bool = False          # Berlin (EIP-2929); flat costs below until then
    g_sload: int = 50                # 50 → 200 (EIP-150) → 800 (EIP-1884)
    g_balance: int = 20              # 20 → 400 (EIP-150) → 700 (EIP-1884)
    g_extcode: int = 20              # EXTCODESIZE/EXTCODECOPY: 20 → 700 (EIP-150)
    g_extcodehash: int = 400         # 400 (EIP-1052) → 700 (EIP-1884)
    g_call: int = 40                 # CALL family base: 40 → 700 (EIP-150)
    g_selfdestruct: int = 0          # 0 → 5000 (EIP-150)
    g_exp_byte: int = 10             # 10 → 50 (EIP-160, Spurious)

    # -- call / create semantics -------------------------------------------
    call_63_64: bool = False               # EIP-150 gas retention
    new_account_charge_always: bool = True # pre-EIP-161: absent target charges
    touch_creates_empty: bool = True       # pre-EIP-161: calls materialize target
    # SELFDESTRUCT beneficiary new-account charge: "never" (Frontier),
    # "absent" (EIP-150), "dead_with_value" (EIP-161)
    selfdestruct_new_account: str = "never"
    selfdestruct_same_tx_only: bool = False  # Cancun (EIP-6780)
    create_fail_on_deposit_oog: bool = False # Homestead (EIP-2); pre: empty code
    max_code_size: int | None = None         # Spurious (EIP-170)
    reject_ef_code: bool = False             # London (EIP-3541)
    initcode_limit: bool = False             # Shanghai (EIP-3860)

    # -- SSTORE regime ------------------------------------------------------
    sstore_net: bool = False         # EIP-1283 (Constantinople) / 2200 (Istanbul)
    sstore_sentry: int = 0           # EIP-2200 adds the 2300-gas sentry
    g_sstore_load: int = 200         # net-metering "sload leg": 200 → 800 → warm 100
    r_sstore_clear: int = 15_000     # → 4800 (EIP-3529, London)
    r_selfdestruct: int = 24_000     # → 0 (EIP-3529)
    refund_quotient: int = 2         # → 5 (EIP-3529)

    # -- transaction rules --------------------------------------------------
    g_calldata_nonzero: int = 68     # → 16 (EIP-2028, Istanbul)
    g_tx_create_extra: int = 0       # → 32000 (EIP-2, Homestead)
    calldata_floor: bool = False     # Prague (EIP-7623)
    eip155: bool = False             # Spurious: chain-id signatures
    state_clearing: bool = False     # Spurious (EIP-161)
    max_tx_type: int = 0             # 1 Berlin, 2 London, 3 Cancun, 4 Prague
    warm_coinbase: bool = False      # Shanghai (EIP-3651)

    # -- precompiles --------------------------------------------------------
    precompiles: int = 4             # highest address: 8 Byzantium, 9 Istanbul,
    #                                  10 Cancun, 17 Prague (EIP-2537 BLS)
    bn_add_gas: int = 500            # EIP-1108 (Istanbul): 150
    bn_mul_gas: int = 40_000         # EIP-1108: 6000
    bn_pair_base: int = 100_000      # EIP-1108: 45000
    bn_pair_per: int = 80_000        # EIP-1108: 34000
    modexp_eip2565: bool = True      # Berlin repricing (min 200); False = EIP-198

    # -- block rules --------------------------------------------------------
    block_reward: int = 5 * ETHER    # 3 Byzantium, 2 Constantinople, 0 Paris
    receipt_status: bool = False     # Byzantium (EIP-658); pre: post-tx state root
    has_withdrawals: bool = False    # Shanghai (EIP-4895)
    has_setcode: bool = False        # Prague (EIP-7702)
    beacon_root_call: bool = False   # Cancun (EIP-4788) pre-block system call
    history_contract_call: bool = False  # Prague (EIP-2935)
    has_requests: bool = False       # Prague (EIP-7685/6110/7002/7251)
    blob: BlobParams | None = None   # Cancun+

    # -- helpers ------------------------------------------------------------
    def at_least(self, fork: str) -> bool:
        return HARDFORK_ORDER.index(self.name) >= HARDFORK_ORDER.index(fork)


# Each fork is a diff against the previous rule set, applied in order.
_DELTAS: dict[str, dict] = {
    HOMESTEAD: dict(
        has_delegatecall=True, g_tx_create_extra=32_000,
        create_fail_on_deposit_oog=True,
    ),
    # DAO / glacier forks: difficulty-schedule only, no EVM delta
    TANGERINE: dict(  # EIP-150 + EIP-158 precursor semantics stay
        call_63_64=True, g_sload=200, g_call=700, g_balance=400,
        g_extcode=700, g_selfdestruct=5_000,
        selfdestruct_new_account="absent",
    ),
    SPURIOUS_DRAGON: dict(  # EIP-155/160/161/170
        eip155=True, state_clearing=True, touch_creates_empty=False,
        new_account_charge_always=False,
        selfdestruct_new_account="dead_with_value",
        max_code_size=24_576, g_exp_byte=50,
    ),
    BYZANTIUM: dict(  # EIP-140/211/214/658 + precompiles 5-8
        has_revert=True, precompiles=8, receipt_status=True,
        block_reward=3 * ETHER, modexp_eip2565=False,
    ),
    CONSTANTINOPLE: dict(  # EIP-145/1014/1052/1283/1234
        has_shifts=True, has_create2=True, has_extcodehash=True,
        sstore_net=True, g_sstore_load=200, block_reward=2 * ETHER,
    ),
    PETERSBURG: dict(sstore_net=False),  # EIP-1283 removed
    ISTANBUL: dict(  # EIP-152/1108/1344/1884/2028/2200
        sstore_net=True, sstore_sentry=2_300, g_sstore_load=800,
        g_sload=800, g_balance=700, g_extcodehash=700,
        g_calldata_nonzero=16, precompiles=9,
        bn_add_gas=150, bn_mul_gas=6_000, bn_pair_base=45_000,
        bn_pair_per=34_000, has_chainid=True, has_selfbalance=True,
    ),
    BERLIN: dict(  # EIP-2565/2929/2930
        warm_cold=True, g_sstore_load=100, modexp_eip2565=True,
        max_tx_type=1,
    ),
    LONDON: dict(  # EIP-1559/3198/3529/3541
        has_basefee=True, r_sstore_clear=4_800, r_selfdestruct=0,
        refund_quotient=5, max_tx_type=2, reject_ef_code=True,
    ),
    PARIS: dict(merge=True, block_reward=0),
    SHANGHAI: dict(  # EIP-3651/3855/3860/4895
        has_push0=True, warm_coinbase=True, initcode_limit=True,
        has_withdrawals=True,
    ),
    CANCUN: dict(  # EIP-1153/4788/4844/5656/6780/7516
        has_transient=True, has_mcopy=True, has_blob_opcodes=True,
        selfdestruct_same_tx_only=True, precompiles=10, max_tx_type=3,
        beacon_root_call=True, blob=CANCUN_BLOBS,
    ),
    PRAGUE: dict(  # EIP-2537/2935/6110/7002/7251/7623/7691/7702
        has_setcode=True, calldata_floor=True, max_tx_type=4,
        history_contract_call=True, has_requests=True, blob=PRAGUE_BLOBS,
        # EIP-2537 extends the precompile ADDRESS RANGE to 0x11 (warming
        # per EIP-2929 init covers 1..17 — validated against the
        # reference's hive chain). The whole table is implemented in
        # primitives/bls12381.py: ADD/MSM (affine + subgroup checks),
        # PAIRING over primitives/pairing.py, and the RFC 9380
        # SSWU+isogeny maps whose constants are derived offline and
        # pinned by exact polynomial identities + RFC vectors.
        precompiles=17,
    ),
    OSAKA: dict(),
}

_SPECS: dict[str, Spec] = {}


def _build_specs() -> None:
    spec = Spec()
    _SPECS[FRONTIER] = spec
    for fork in HARDFORK_ORDER[1:]:
        delta = _DELTAS.get(fork, {})
        spec = replace(spec, name=fork, **delta)
        _SPECS[fork] = spec


_build_specs()

LATEST_SPEC = _SPECS[PRAGUE]


def spec_for_fork(fork: str) -> Spec:
    return _SPECS[fork]


def spec_for_block(chainspec: ChainSpec, number: int, timestamp: int = 0) -> Spec:
    """Rule set for a block at (number, timestamp) — the per-block SpecId
    selection (reference crates/ethereum/evm/src/config.rs:2-3). Honors a
    chain's blobSchedule overrides when the chainspec carries them."""
    spec = _SPECS[chainspec.spec_at(number, timestamp)]
    if chainspec.blob_schedule and spec.blob is not None:
        params = chainspec.blob_schedule.get(spec.name)
        if params is not None:
            spec = replace(spec, blob=params)
    return spec
