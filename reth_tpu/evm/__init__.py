"""Block execution on CPU — interpreter, journaled state, block executor.

Reference analogue: the revm v41 interpreter (external crate) plus reth's
glue (crates/revm, crates/evm/evm, crates/ethereum/evm). Execution stays
on the host by design (SURVEY.md north star): the TPU accelerates the
state-commitment path, not the EVM; this package produces the state
changes and receipts that feed the hashing/merkle stages.
"""

from .state import EvmState, BlockChanges
from .executor import BlockExecutor, BlockExecutionOutput, EvmConfig

__all__ = [
    "EvmState",
    "BlockChanges",
    "BlockExecutor",
    "BlockExecutionOutput",
    "EvmConfig",
]
