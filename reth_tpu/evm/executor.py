"""Block executor: transaction validation, execution, receipts, changesets.

Reference analogue: `ConfigureEvm`/`Executor`/`BlockExecutionOutput`
(crates/evm/evm/src/lib.rs:181, crates/evm/execution-types) with
`EthEvmConfig`'s mainnet wiring (crates/ethereum/evm). Post-merge rules:
no block rewards, withdrawals credited in gwei, EIP-1559 fee handling
(priority fee to coinbase, base fee burned), EIP-3529 refund cap of 1/5.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..primitives.keccak import keccak256
from ..primitives.types import Account, Block, Log, Receipt, Transaction
from .interpreter import (
    BlockEnv,
    CallFrame,
    G_ACCESS_LIST_ADDR,
    G_ACCESS_LIST_SLOT,
    G_INITCODE_WORD,
    G_NONZERO_BYTE,
    G_TX,
    G_TX_CREATE,
    G_ZERO_BYTE,
    Halt,
    Interpreter,
    MAX_INITCODE_SIZE,
    Revert,
    TxEnv,
)
from .state import BlockChanges, EvmState, StateSource

MAX_REFUND_QUOTIENT = 5  # EIP-3529


class InvalidTransaction(Exception):
    pass


@dataclass
class EvmConfig:
    """Chain-level execution config (reference `EthEvmConfig`)."""

    chain_id: int = 1


@dataclass
class TxResult:
    receipt: Receipt
    gas_used: int
    success: bool
    output: bytes = b""


@dataclass
class BlockExecutionOutput:
    """Everything downstream stages need (reference `BlockExecutionOutput`)."""

    receipts: list[Receipt] = field(default_factory=list)
    gas_used: int = 0
    changes: BlockChanges | None = None
    post_accounts: dict[bytes, Account | None] = field(default_factory=dict)
    post_storage: dict[bytes, dict[bytes, int]] = field(default_factory=dict)
    senders: list[bytes] = field(default_factory=list)


def intrinsic_gas(tx: Transaction) -> int:
    gas = G_TX
    for b in tx.data:
        gas += G_ZERO_BYTE if b == 0 else G_NONZERO_BYTE
    if tx.to is None:
        gas += G_TX_CREATE
        gas += G_INITCODE_WORD * ((len(tx.data) + 31) // 32)  # EIP-3860
    for _addr, slots in tx.access_list:
        gas += G_ACCESS_LIST_ADDR + G_ACCESS_LIST_SLOT * len(slots)
    return gas


class BlockExecutor:
    """Executes one block against a state source."""

    def __init__(self, source: StateSource, config: EvmConfig | None = None):
        self.source = source
        self.config = config or EvmConfig()

    def execute(
        self, block: Block, senders: list[bytes] | None = None,
        block_hashes: dict[int, bytes] | None = None,
    ) -> BlockExecutionOutput:
        header = block.header
        env = BlockEnv(
            number=header.number,
            timestamp=header.timestamp,
            coinbase=header.beneficiary,
            gas_limit=header.gas_limit,
            base_fee=header.base_fee_per_gas or 0,
            prev_randao=header.mix_hash,
            chain_id=self.config.chain_id,
            block_hashes=block_hashes or {},
        )
        state = EvmState(self.source)
        out = BlockExecutionOutput()
        if senders is None:
            senders = [tx.recover_sender() for tx in block.transactions]
        out.senders = senders
        cumulative_gas = 0
        for tx, sender in zip(block.transactions, senders):
            result = self._execute_tx(state, env, tx, sender, header.gas_limit - cumulative_gas)
            cumulative_gas += result.gas_used
            receipt = Receipt(
                tx_type=tx.tx_type,
                success=result.success,
                cumulative_gas_used=cumulative_gas,
                logs=tuple(result.receipt.logs),
            )
            out.receipts.append(receipt)
        # withdrawals (gwei → wei), post-merge; zero-amount does not touch
        for w in block.withdrawals or ():
            if w.amount:
                state._capture_account_change(w.address)
                state.add_balance(w.address, w.amount * 10**9)
        out.gas_used = cumulative_gas
        out.changes = state.changes
        out.post_accounts, out.post_storage = state.final_state()
        return out

    def _execute_tx(
        self, state: EvmState, env: BlockEnv, tx: Transaction, sender: bytes,
        gas_available: int, tracer=None,
    ) -> TxResult:
        base_fee = env.base_fee
        # -- validation (reference: EthTransactionValidator + pre-exec checks)
        if tx.gas_limit > gas_available:
            raise InvalidTransaction("block gas limit exceeded")
        if tx.chain_id is not None and tx.chain_id != env.chain_id:
            raise InvalidTransaction("wrong chain id")
        gas_price = tx.effective_gas_price(base_fee)
        if tx.tx_type >= 2 and tx.max_fee_per_gas < base_fee:
            raise InvalidTransaction("max fee below base fee")
        if tx.tx_type < 2 and gas_price < base_fee:  # legacy + EIP-2930
            raise InvalidTransaction("gas price below base fee")
        acct = state.account_or_empty(sender)
        if acct.nonce != tx.nonce:
            raise InvalidTransaction(f"nonce mismatch: acct {acct.nonce} vs tx {tx.nonce}")
        max_cost = tx.gas_limit * (tx.max_fee_per_gas if tx.tx_type >= 2 else tx.gas_price)
        if acct.balance < max_cost + tx.value:
            raise InvalidTransaction("insufficient funds")
        ig = intrinsic_gas(tx)
        if tx.gas_limit < ig:
            raise InvalidTransaction("intrinsic gas too high")
        if tx.to is None and len(tx.data) > MAX_INITCODE_SIZE:
            raise InvalidTransaction("initcode too large")

        # -- setup
        state.begin_tx()
        state.delete_empty_touched()
        interp = Interpreter(state, env, TxEnv(origin=sender, gas_price=gas_price),
                             tracer=tracer)
        # buy gas
        state.sub_balance(sender, tx.gas_limit * gas_price)
        state.bump_nonce(sender)
        # warm: sender, coinbase (EIP-3651), target, precompiles (EIP-2929
        # initialises accessed_addresses with them), access list
        state.warm_account(sender)
        state.warm_account(env.coinbase)
        for i in range(1, 11):
            state.warm_account(b"\x00" * 19 + bytes([i]))
        if tx.to is not None:
            state.warm_account(tx.to)
        for addr, slots in tx.access_list:
            state.warm_account(addr)
            for s in slots:
                state.warm_slot(addr, s)

        gas = tx.gas_limit - ig
        success, output = True, b""
        if tx.to is None:
            ok, gas_left, _addr, output = interp.create(
                sender, tx.value, tx.data, gas, 0, tx_nonce=tx.nonce
            )
            success = ok
        else:
            frame = CallFrame(
                caller=sender, address=tx.to, code=state.code(tx.to),
                data=tx.data, value=tx.value, gas=gas,
            )
            try:
                ok, gas_left, output = interp.call(frame)
                success = ok
            except Revert as r:
                success, gas_left, output = False, getattr(r, "gas_left", 0), r.output
            except Halt:
                success, gas_left, output = False, 0, b""

        gas_used = tx.gas_limit - gas_left
        if success:
            refund = min(state.refund, gas_used // MAX_REFUND_QUOTIENT)
            gas_used -= refund
        # refund unused gas, pay coinbase the priority fee, burn base fee
        state.add_balance(sender, (tx.gas_limit - gas_used) * gas_price)
        priority = gas_price - base_fee
        if priority > 0:
            state._capture_account_change(env.coinbase)
            state.add_balance(env.coinbase, gas_used * priority)
        # failed frames already popped their logs via journal revert
        logs = state.take_logs()
        state.delete_empty_touched()
        return TxResult(
            receipt=Receipt(tx_type=tx.tx_type, success=success, logs=tuple(logs)),
            gas_used=gas_used,
            success=success,
            output=output,
        )


class ProviderStateSource(StateSource):
    """StateSource over a DatabaseProvider's plain state."""

    def __init__(self, provider):
        self.provider = provider

    def account(self, address: bytes) -> Account | None:
        return self.provider.account(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        return self.provider.storage(address, slot)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.provider.bytecode(code_hash) or b""


class InMemoryStateSource(StateSource):
    """Dict-backed source for tests and genesis building."""

    def __init__(self, accounts=None, storages=None, codes=None):
        self.accounts = dict(accounts or {})
        self.storages = {a: dict(s) for a, s in (storages or {}).items()}
        self.codes = dict(codes or {})

    def account(self, address: bytes) -> Account | None:
        return self.accounts.get(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        return self.storages.get(address, {}).get(slot, 0)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.codes.get(code_hash, b"")
