"""Block executor: transaction validation, execution, receipts, changesets.

Reference analogue: `ConfigureEvm`/`Executor`/`BlockExecutionOutput`
(crates/evm/evm/src/lib.rs:181, crates/evm/execution-types) with
`EthEvmConfig`'s mainnet wiring (crates/ethereum/evm). Post-merge rules:
no block rewards, withdrawals credited in gwei, EIP-1559 fee handling
(priority fee to coinbase, base fee burned), EIP-3529 refund cap of 1/5.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..primitives.keccak import keccak256
from ..primitives.types import (
    Account,
    Block,
    DELEGATION_PREFIX,
    EIP4844_TX_TYPE,
    EIP7702_TX_TYPE,
    GAS_PER_BLOB,
    Log,
    Receipt,
    Transaction,
)
from .interpreter import (
    BlockEnv,
    CallFrame,
    G_ACCESS_LIST_ADDR,
    G_ACCESS_LIST_SLOT,
    G_INITCODE_WORD,
    G_NONZERO_BYTE,
    G_TX,
    G_TX_CREATE,
    G_ZERO_BYTE,
    Halt,
    Interpreter,
    MAX_INITCODE_SIZE,
    Revert,
    TxEnv,
)
from .state import BlockChanges, EvmState, StateSource, resolve_delegation

MAX_REFUND_QUOTIENT = 5  # EIP-3529

# EIP-4844 blob fee market (Cancun parameters)
MIN_BLOB_BASE_FEE = 1
BLOB_BASE_FEE_UPDATE_FRACTION = 3_338_477
TARGET_BLOB_GAS_PER_BLOCK = 3 * GAS_PER_BLOB
MAX_BLOB_GAS_PER_BLOCK = 6 * GAS_PER_BLOB

# EIP-7702
PER_EMPTY_ACCOUNT_COST = 25_000
PER_AUTH_BASE_COST = 12_500
SECP256K1_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def fake_exponential(factor: int, numerator: int, denominator: int) -> int:
    """EIP-4844 blob base fee approximation of factor * e^(num/denom)."""
    i = 1
    output = 0
    acc = factor * denominator
    while acc > 0:
        output += acc
        acc = acc * numerator // (denominator * i)
        i += 1
    return output // denominator


def blob_base_fee(excess_blob_gas: int) -> int:
    return fake_exponential(MIN_BLOB_BASE_FEE, excess_blob_gas,
                            BLOB_BASE_FEE_UPDATE_FRACTION)


def next_excess_blob_gas(parent_excess: int, parent_blob_gas_used: int) -> int:
    total = parent_excess + parent_blob_gas_used
    return max(0, total - TARGET_BLOB_GAS_PER_BLOCK)


class InvalidTransaction(Exception):
    pass


@dataclass
class EvmConfig:
    """Chain-level execution config (reference `EthEvmConfig`)."""

    chain_id: int = 1


@dataclass
class TxResult:
    receipt: Receipt
    gas_used: int
    success: bool
    output: bytes = b""


@dataclass
class BlockExecutionOutput:
    """Everything downstream stages need (reference `BlockExecutionOutput`)."""

    receipts: list[Receipt] = field(default_factory=list)
    gas_used: int = 0
    changes: BlockChanges | None = None
    post_accounts: dict[bytes, Account | None] = field(default_factory=dict)
    post_storage: dict[bytes, dict[bytes, int]] = field(default_factory=dict)
    senders: list[bytes] = field(default_factory=list)


def intrinsic_gas(tx: Transaction) -> int:
    gas = G_TX
    for b in tx.data:
        gas += G_ZERO_BYTE if b == 0 else G_NONZERO_BYTE
    if tx.to is None:
        gas += G_TX_CREATE
        gas += G_INITCODE_WORD * ((len(tx.data) + 31) // 32)  # EIP-3860
    for _addr, slots in tx.access_list:
        gas += G_ACCESS_LIST_ADDR + G_ACCESS_LIST_SLOT * len(slots)
    gas += PER_EMPTY_ACCOUNT_COST * len(tx.authorization_list)  # EIP-7702
    return gas


class BlockExecutor:
    """Executes one block against a state source."""

    def __init__(self, source: StateSource, config: EvmConfig | None = None):
        self.source = source
        self.config = config or EvmConfig()

    def _credit_coinbase(self, state: EvmState, env: "BlockEnv", amount: int):
        """Priority-fee credit seam: the BAL wave executor overrides this to
        accumulate a commutative delta instead of writing state (coinbase
        would otherwise conflict every pair of transactions)."""
        state._capture_account_change(env.coinbase)
        state.add_balance(env.coinbase, amount)

    def execute(
        self, block: Block, senders: list[bytes] | None = None,
        block_hashes: dict[int, bytes] | None = None,
        state_hook=None,
    ) -> BlockExecutionOutput:
        """``state_hook(keys)`` is called after every transaction with the
        plain keys it newly touched — 20-byte addresses and
        ``(address, slot)`` pairs — the OnStateHook seam feeding the
        background state-root job (reference crates/evm/evm/src/lib.rs
        OnStateHook -> state_root_task)."""
        header = block.header
        env = BlockEnv(
            number=header.number,
            timestamp=header.timestamp,
            coinbase=header.beneficiary,
            gas_limit=header.gas_limit,
            base_fee=header.base_fee_per_gas or 0,
            prev_randao=header.mix_hash,
            chain_id=self.config.chain_id,
            block_hashes=block_hashes or {},
            blob_base_fee=blob_base_fee(header.excess_blob_gas or 0),
        )
        state = EvmState(self.source)
        out = BlockExecutionOutput()
        if senders is None:
            senders = [tx.recover_sender() for tx in block.transactions]
        out.senders = senders
        cumulative_gas = 0
        sent_accounts = 0
        sent_slots: dict[bytes, int] = {}
        for tx, sender in zip(block.transactions, senders):
            result = self._execute_tx(state, env, tx, sender, header.gas_limit - cumulative_gas)
            cumulative_gas += result.gas_used
            receipt = Receipt(
                tx_type=tx.tx_type,
                success=result.success,
                cumulative_gas_used=cumulative_gas,
                logs=tuple(result.receipt.logs),
            )
            out.receipts.append(receipt)
            if state_hook is not None:
                # stream only this tx's NEWLY touched keys: the changes maps
                # are append-only per block (prev-images capture once), so
                # watermarks over insertion order give exact per-tx deltas
                accts = list(state.changes.accounts)
                new = accts[sent_accounts:]
                sent_accounts = len(accts)
                for addr, per in state.changes.storage.items():
                    seen = sent_slots.get(addr, 0)
                    if len(per) > seen:
                        new += [(addr, s) for s in list(per)[seen:]]
                        sent_slots[addr] = len(per)
                if new:
                    state_hook(new)
        # withdrawals (gwei → wei), post-merge; zero-amount does not touch
        for w in block.withdrawals or ():
            if w.amount:
                state._capture_account_change(w.address)
                state.add_balance(w.address, w.amount * 10**9)
        out.gas_used = cumulative_gas
        out.changes = state.changes
        out.post_accounts, out.post_storage = state.final_state()
        return out

    def _execute_tx(
        self, state: EvmState, env: BlockEnv, tx: Transaction, sender: bytes,
        gas_available: int, tracer=None,
    ) -> TxResult:
        base_fee = env.base_fee
        # -- validation (reference: EthTransactionValidator + pre-exec checks)
        if tx.gas_limit > gas_available:
            raise InvalidTransaction("block gas limit exceeded")
        if tx.chain_id is not None and tx.chain_id != env.chain_id:
            raise InvalidTransaction("wrong chain id")
        gas_price = tx.effective_gas_price(base_fee)
        if tx.tx_type >= 2 and tx.max_fee_per_gas < base_fee:
            raise InvalidTransaction("max fee below base fee")
        if tx.tx_type < 2 and gas_price < base_fee:  # legacy + EIP-2930
            raise InvalidTransaction("gas price below base fee")
        blob_fee = 0
        if tx.tx_type == EIP4844_TX_TYPE:
            # EIP-4844: blob txs must target a contract and carry blobs
            if tx.to is None:
                raise InvalidTransaction("blob tx cannot create")
            if not tx.blob_versioned_hashes:
                raise InvalidTransaction("blob tx without blobs")
            if any(len(h) != 32 or h[0] != 0x01 for h in tx.blob_versioned_hashes):
                raise InvalidTransaction("malformed blob versioned hash")
            if tx.max_fee_per_blob_gas < env.blob_base_fee:
                raise InvalidTransaction("max blob fee below blob base fee")
            blob_fee = tx.blob_gas() * env.blob_base_fee
        if tx.tx_type == EIP7702_TX_TYPE:
            if tx.to is None:
                raise InvalidTransaction("set-code tx cannot create")
            if not tx.authorization_list:
                raise InvalidTransaction("set-code tx without authorizations")
        acct = state.account_or_empty(sender)
        if acct.nonce != tx.nonce:
            raise InvalidTransaction(f"nonce mismatch: acct {acct.nonce} vs tx {tx.nonce}")
        max_cost = tx.gas_limit * (tx.max_fee_per_gas if tx.tx_type >= 2 else tx.gas_price)
        max_cost += tx.blob_gas() * tx.max_fee_per_blob_gas
        if acct.balance < max_cost + tx.value:
            raise InvalidTransaction("insufficient funds")
        ig = intrinsic_gas(tx)
        if tx.gas_limit < ig:
            raise InvalidTransaction("intrinsic gas too high")
        if tx.to is None and len(tx.data) > MAX_INITCODE_SIZE:
            raise InvalidTransaction("initcode too large")

        # -- setup
        state.begin_tx()
        state.delete_empty_touched()
        interp = Interpreter(
            state, env,
            TxEnv(origin=sender, gas_price=gas_price,
                  blob_hashes=tuple(tx.blob_versioned_hashes)),
            tracer=tracer,
        )
        # buy gas (+ the blob fee, burned — EIP-4844)
        state.sub_balance(sender, tx.gas_limit * gas_price + blob_fee)
        state.bump_nonce(sender)
        # warm: sender, coinbase (EIP-3651), target, precompiles (EIP-2929
        # initialises accessed_addresses with them), access list
        state.warm_account(sender)
        state.warm_account(env.coinbase)
        for i in range(1, 11):
            state.warm_account(b"\x00" * 19 + bytes([i]))
        if tx.to is not None:
            state.warm_account(tx.to)
        for addr, slots in tx.access_list:
            state.warm_account(addr)
            for s in slots:
                state.warm_slot(addr, s)
        if tx.tx_type == EIP7702_TX_TYPE:
            self._apply_authorizations(state, env, tx)

        gas = tx.gas_limit - ig
        success, output = True, b""
        if tx.to is None:
            ok, gas_left, _addr, output = interp.create(
                sender, tx.value, tx.data, gas, 0, tx_nonce=tx.nonce
            )
            success = ok
        else:
            # EIP-7702: execute the delegate's code in tx.to's context,
            # charging the delegate's account-access cost; running short of
            # gas here is an IN-BLOCK out-of-gas failure, never a tx-
            # validity error (state mutations above must stand)
            code, target = resolve_delegation(state, tx.to)
            oog = False
            if target is not None:
                from .interpreter import G_COLD_ACCOUNT, G_WARM_ACCESS

                cost = G_WARM_ACCESS if state.warm_account(target) else G_COLD_ACCOUNT
                if gas < cost:
                    success, gas_left, output, oog = False, 0, b"", True
                else:
                    gas -= cost
            if not oog:
                frame = CallFrame(
                    caller=sender, address=tx.to, code=code,
                    data=tx.data, value=tx.value, gas=gas,
                )
                try:
                    ok, gas_left, output = interp.call(frame)
                    success = ok
                except Revert as r:
                    success, gas_left, output = False, getattr(r, "gas_left", 0), r.output
                except Halt:
                    success, gas_left, output = False, 0, b""

        gas_used = tx.gas_limit - gas_left
        if success:
            refund = min(state.refund, gas_used // MAX_REFUND_QUOTIENT)
            gas_used -= refund
        # refund unused gas, pay coinbase the priority fee, burn base fee
        state.add_balance(sender, (tx.gas_limit - gas_used) * gas_price)
        priority = gas_price - base_fee
        if priority > 0:
            self._credit_coinbase(state, env, gas_used * priority)
        # failed frames already popped their logs via journal revert
        logs = state.take_logs()
        state.delete_empty_touched()
        return TxResult(
            receipt=Receipt(tx_type=tx.tx_type, success=success, logs=tuple(logs)),
            gas_used=gas_used,
            success=success,
            output=output,
        )


    def _apply_authorizations(self, state: EvmState, env: BlockEnv, tx: Transaction):
        """EIP-7702 set-code processing: each valid authorization installs a
        delegation designator (0xef0100 ++ address) as the authority's code.
        Invalid tuples are SKIPPED, never fatal (per spec)."""
        for auth in tx.authorization_list:
            if len(auth.address) != 20:
                continue
            if auth.chain_id not in (0, env.chain_id):
                continue
            if auth.nonce >= 2**64 - 1:
                continue
            if auth.s > SECP256K1_N // 2 or auth.y_parity not in (0, 1):
                continue
            try:
                authority = auth.recover_authority()
            except ValueError:
                continue
            state.warm_account(authority)
            code = state.code(authority)
            if code and not (code[:3] == DELEGATION_PREFIX and len(code) == 23):
                continue  # real contract code cannot be overridden
            if state.nonce(authority) != auth.nonce:
                continue
            if state.exists(authority) and not state.is_empty(authority):
                state.add_refund(PER_EMPTY_ACCOUNT_COST - PER_AUTH_BASE_COST)
            state._capture_account_change(authority)
            if auth.address == b"\x00" * 20:
                state.set_code(authority, b"")  # clear the delegation
            else:
                state.set_code(authority, DELEGATION_PREFIX + auth.address)
            state.set_nonce(authority, auth.nonce + 1)


class ProviderStateSource(StateSource):
    """StateSource over a DatabaseProvider's plain state."""

    def __init__(self, provider):
        self.provider = provider

    def account(self, address: bytes) -> Account | None:
        return self.provider.account(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        return self.provider.storage(address, slot)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.provider.bytecode(code_hash) or b""


class InMemoryStateSource(StateSource):
    """Dict-backed source for tests and genesis building."""

    def __init__(self, accounts=None, storages=None, codes=None):
        self.accounts = dict(accounts or {})
        self.storages = {a: dict(s) for a, s in (storages or {}).items()}
        self.codes = dict(codes or {})

    def account(self, address: bytes) -> Account | None:
        return self.accounts.get(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        return self.storages.get(address, {}).get(slot, 0)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.codes.get(code_hash, b"")
