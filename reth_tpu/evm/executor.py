"""Block executor: transaction validation, execution, receipts, changesets.

Reference analogue: `ConfigureEvm`/`Executor`/`BlockExecutionOutput`
(crates/evm/evm/src/lib.rs:181, crates/evm/execution-types) with
`EthEvmConfig`'s mainnet wiring (crates/ethereum/evm) and its per-block
revm `SpecId` selection (crates/ethereum/evm/src/config.rs:2-3). All
fork-dependent rules come from the active :class:`Spec`: EIP-1559 fee
handling vs full-fee-to-miner, EIP-3529 refund caps, pre-merge block +
ommer rewards, pre-Byzantium state-root receipts, EIP-161 state
clearing, EIP-7623 calldata floor, and the system calls (EIP-4788
beacon roots, EIP-2935 history, EIP-7002/7251 request contracts,
EIP-6110 deposit log parsing — reference
crates/evm/evm/src/system_calls/).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..primitives.keccak import keccak256
from ..primitives.types import (
    Account,
    Block,
    DELEGATION_PREFIX,
    EIP4844_TX_TYPE,
    EIP7702_TX_TYPE,
    GAS_PER_BLOB,
    Receipt,
    Transaction,
)
from .interpreter import (
    BlockEnv,
    CallFrame,
    G_ACCESS_LIST_ADDR,
    G_ACCESS_LIST_SLOT,
    G_INITCODE_WORD,
    G_TX,
    G_ZERO_BYTE,
    Halt,
    Interpreter,
    MAX_INITCODE_SIZE,
    PrecompileNotImplemented,
    Revert,
    TxEnv,
)
from .spec import LATEST_SPEC, Spec, spec_for_block
from .state import BlockChanges, EvmState, StateSource, resolve_delegation

MAX_REFUND_QUOTIENT = 5  # EIP-3529

# EIP-4844 blob fee market (Cancun parameters)
MIN_BLOB_BASE_FEE = 1
BLOB_BASE_FEE_UPDATE_FRACTION = 3_338_477
TARGET_BLOB_GAS_PER_BLOCK = 3 * GAS_PER_BLOB
MAX_BLOB_GAS_PER_BLOCK = 6 * GAS_PER_BLOB

# EIP-7702
PER_EMPTY_ACCOUNT_COST = 25_000
PER_AUTH_BASE_COST = 12_500
SECP256K1_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def fake_exponential(factor: int, numerator: int, denominator: int) -> int:
    """EIP-4844 blob base fee approximation of factor * e^(num/denom)."""
    i = 1
    output = 0
    acc = factor * denominator
    while acc > 0:
        output += acc
        acc = acc * numerator // (denominator * i)
        i += 1
    return output // denominator


def blob_base_fee(excess_blob_gas: int,
                  update_fraction: int = BLOB_BASE_FEE_UPDATE_FRACTION) -> int:
    return fake_exponential(MIN_BLOB_BASE_FEE, excess_blob_gas, update_fraction)


def next_excess_blob_gas(parent_excess: int, parent_blob_gas_used: int,
                         target: int = TARGET_BLOB_GAS_PER_BLOCK) -> int:
    total = parent_excess + parent_blob_gas_used
    return max(0, total - target)


# system-call fixed addresses (each from its EIP)
SYSTEM_ADDRESS = bytes.fromhex("fffffffffffffffffffffffffffffffffffffffe")
BEACON_ROOTS_ADDRESS = bytes.fromhex("000f3df6d732807ef1319fb7b8bb8522d0beac02")
HISTORY_STORAGE_ADDRESS = bytes.fromhex("0000f90827f1c53a10cb7a02335b175320002935")
WITHDRAWAL_REQUEST_ADDRESS = bytes.fromhex("00000961ef480eb55e80d19ad83579a64c007002")
CONSOLIDATION_REQUEST_ADDRESS = bytes.fromhex("0000bbddc7ce488642fb579f8b00f3a590007251")
MAINNET_DEPOSIT_CONTRACT = bytes.fromhex("00000000219ab540356cbb839cbe05303d7705fa")
# keccak256("DepositEvent(bytes,bytes,bytes,bytes,bytes)")
DEPOSIT_EVENT_TOPIC = keccak256(b"DepositEvent(bytes,bytes,bytes,bytes,bytes)")


class InvalidTransaction(ValueError):
    """A transaction that cannot be included in its block (consensus
    invalidity — nonce/fee/fork gating), distinct from an in-block
    failure. ValueError subclass so generic rejection paths catch it."""


class BlockExecutionError(InvalidTransaction):
    """A block-level execution failure that invalidates the whole block:
    a mandatory system call reverted/halted (EIP-7002/7251 contracts must
    not fail) or a deposit-contract log that does not decode (EIP-6110).
    Subclasses InvalidTransaction so every block-rejection path (engine
    tree, pipeline, conformance) treats it as block-invalid."""


# EIP-6110 DepositEvent field sizes, in ABI order
_DEPOSIT_FIELDS = (48, 32, 8, 96, 8)  # pubkey, wc, amount, signature, index


def _decode_deposit_log(data: bytes) -> bytes:
    """Decode one DepositEvent(bytes,bytes,bytes,bytes,bytes) log's ABI
    data into the EIP-6110 deposit-request encoding: the five payloads
    concatenated (48+32+8+96+8 = 192 bytes).

    The ABI head is five 32-byte offsets; each tail is a 32-byte length
    word followed by the right-padded payload. Offsets and lengths are
    VALIDATED, not assumed (reference crates/ethereum/evm deposit
    decoding) — the canonical deposit contract always emits the fixed
    576-byte layout, but a spoofed log with the right topic from a chain's
    overridden deposit-contract address must not be trusted blindly.
    Raises :class:`BlockExecutionError` on any malformed field."""

    def word(off: int) -> int:
        if off + 32 > len(data):
            raise BlockExecutionError(
                f"deposit log truncated at byte {off} (len {len(data)})")
        return int.from_bytes(data[off : off + 32], "big")

    out = bytearray()
    for i, size in enumerate(_DEPOSIT_FIELDS):
        tail = word(32 * i)
        if tail % 32 or tail < 32 * len(_DEPOSIT_FIELDS):
            raise BlockExecutionError(
                f"deposit log field {i}: bad ABI offset {tail}")
        length = word(tail)
        if length != size:
            raise BlockExecutionError(
                f"deposit log field {i}: length {length} != {size}")
        start = tail + 32
        if start + size > len(data):
            raise BlockExecutionError(
                f"deposit log field {i}: payload out of bounds")
        out += data[start : start + size]
    return bytes(out)


@dataclass
class EvmConfig:
    """Chain-level execution config (reference `EthEvmConfig`).

    ``chainspec`` drives per-block fork selection; ``spec`` pins one rule
    set regardless of height (tests, conformance). With neither, the
    latest rule set applies — the right default for dev chains and the
    post-merge live-tip paths."""

    chain_id: int = 1
    chainspec: object | None = None  # reth_tpu.chainspec.ChainSpec
    spec: Spec | None = None
    # revm CfgEnv-style relaxations (eth_simulateV1 / eth_call paths)
    disable_eip3607: bool = False
    disable_nonce_check: bool = False

    def spec_for(self, number: int, timestamp: int) -> Spec:
        if self.spec is not None:
            return self.spec
        if self.chainspec is not None:
            return spec_for_block(self.chainspec, number, timestamp)
        return LATEST_SPEC

    def blob_params_for(self, number: int, timestamp: int):
        """Active EIP-4844 parameters (Cancun defaults when the fork
        predates blobs — callers gate on the parent's blob fields)."""
        from .spec import CANCUN_BLOBS

        return self.spec_for(number, timestamp).blob or CANCUN_BLOBS


@dataclass
class TxResult:
    receipt: Receipt
    gas_used: int
    success: bool
    output: bytes = b""


@dataclass
class BlockExecutionOutput:
    """Everything downstream stages need (reference `BlockExecutionOutput`)."""

    receipts: list[Receipt] = field(default_factory=list)
    gas_used: int = 0
    changes: BlockChanges | None = None
    post_accounts: dict[bytes, Account | None] = field(default_factory=dict)
    post_storage: dict[bytes, dict[bytes, int]] = field(default_factory=dict)
    senders: list[bytes] = field(default_factory=list)
    # EIP-7685 execution requests (Prague+): type-prefixed payloads in
    # ascending type order, empty payloads excluded
    requests: list[bytes] = field(default_factory=list)
    # per-tx return data (eth_simulateV1 and tracing consumers)
    tx_outputs: list[bytes] = field(default_factory=list)


def intrinsic_gas(tx: Transaction, spec: Spec = LATEST_SPEC) -> int:
    gas = G_TX
    for b in tx.data:
        gas += G_ZERO_BYTE if b == 0 else spec.g_calldata_nonzero
    if tx.to is None:
        gas += spec.g_tx_create_extra  # 32000 since Homestead (EIP-2)
        if spec.initcode_limit:  # EIP-3860
            gas += G_INITCODE_WORD * ((len(tx.data) + 31) // 32)
    for _addr, slots in tx.access_list:
        gas += G_ACCESS_LIST_ADDR + G_ACCESS_LIST_SLOT * len(slots)
    gas += PER_EMPTY_ACCOUNT_COST * len(tx.authorization_list)  # EIP-7702
    return gas


def calldata_floor_gas(tx: Transaction) -> int:
    """EIP-7623 (Prague): minimum gas a tx pays, from its calldata tokens."""
    tokens = sum(1 if b == 0 else 4 for b in tx.data)
    return G_TX + 10 * tokens


class BlockExecutor:
    """Executes one block against a state source."""

    def __init__(self, source: StateSource, config: EvmConfig | None = None):
        self.source = source
        self.config = config or EvmConfig()

    def _credit_coinbase(self, state: EvmState, env: "BlockEnv", amount: int):
        """Priority-fee credit seam: the BAL wave executor overrides this to
        accumulate a commutative delta instead of writing state (coinbase
        would otherwise conflict every pair of transactions)."""
        state._capture_account_change(env.coinbase)
        state.add_balance(env.coinbase, amount)

    def execute(
        self, block: Block, senders: list[bytes] | None = None,
        block_hashes: dict[int, bytes] | None = None,
        state_hook=None, intermediate_root_fn=None,
    ) -> BlockExecutionOutput:
        """``state_hook(keys)`` is called after every transaction with the
        plain keys it newly touched — 20-byte addresses and
        ``(address, slot)`` pairs — the OnStateHook seam feeding the
        background state-root job (reference crates/evm/evm/src/lib.rs
        OnStateHook -> state_root_task).

        ``intermediate_root_fn(state)`` supplies the post-tx state root for
        pre-Byzantium receipts (the importer owns the trie pipeline, so the
        executor just asks)."""
        header = block.header
        spec = self.config.spec_for(header.number, header.timestamp)
        blob = spec.blob
        env = BlockEnv(
            number=header.number,
            timestamp=header.timestamp,
            coinbase=header.beneficiary,
            gas_limit=header.gas_limit,
            base_fee=header.base_fee_per_gas or 0,
            prev_randao=header.mix_hash,
            chain_id=self.config.chain_id,
            difficulty=header.difficulty,
            block_hashes=block_hashes or {},
            blob_base_fee=blob_base_fee(
                header.excess_blob_gas or 0,
                blob.update_fraction if blob else BLOB_BASE_FEE_UPDATE_FRACTION),
        )
        state = EvmState(self.source)
        out = BlockExecutionOutput()
        if senders is None:
            senders = [tx.recover_sender() for tx in block.transactions]
        out.senders = senders

        # pre-block system calls (reference crates/evm/evm/src/system_calls/)
        if spec.beacon_root_call and header.parent_beacon_block_root is not None:
            self._system_call(state, env, spec, BEACON_ROOTS_ADDRESS,
                              header.parent_beacon_block_root)  # EIP-4788
        if spec.history_contract_call and header.number > 0:
            self._system_call(state, env, spec, HISTORY_STORAGE_ADDRESS,
                              header.parent_hash)  # EIP-2935

        cumulative_gas = 0
        sent_accounts = 0
        sent_slots: dict[bytes, int] = {}

        def flush_hook():
            nonlocal sent_accounts
            # stream only NEWLY touched keys: the changes maps are
            # append-only per block (prev-images capture once), so
            # watermarks over insertion order give exact deltas
            accts = list(state.changes.accounts)
            new = accts[sent_accounts:]
            sent_accounts = len(accts)
            for addr, per in state.changes.storage.items():
                seen = sent_slots.get(addr, 0)
                if len(per) > seen:
                    new += [(addr, s) for s in list(per)[seen:]]
                    sent_slots[addr] = len(per)
            if new:
                state_hook(new)

        for tx, sender in zip(block.transactions, senders):
            result = self._execute_tx(state, env, tx, sender,
                                      header.gas_limit - cumulative_gas,
                                      spec=spec)
            cumulative_gas += result.gas_used
            receipt = Receipt(
                tx_type=tx.tx_type,
                success=result.success,
                cumulative_gas_used=cumulative_gas,
                logs=tuple(result.receipt.logs),
                state_root=(intermediate_root_fn(state)
                            if not spec.receipt_status and intermediate_root_fn
                            else None),
            )
            out.receipts.append(receipt)
            out.tx_outputs.append(result.output)
            if state_hook is not None:
                flush_hook()

        # post-block system calls + EIP-6110 deposit log parsing (Prague)
        if spec.has_requests:
            out.requests = self._collect_requests(state, env, spec, out.receipts)
        # withdrawals (gwei → wei), post-merge; zero-amount does not touch
        for w in block.withdrawals or ():
            if w.amount:
                state._capture_account_change(w.address)
                state.add_balance(w.address, w.amount * 10**9)
        # pre-merge PoW rewards: miner gets R + R/32 per ommer, each ommer
        # miner R*(8-depth)/8 (yellow paper; reference pre-merge executors)
        if spec.block_reward:
            reward = spec.block_reward
            state.add_balance(header.beneficiary,
                              reward + (reward // 32) * len(block.ommers))
            for o in block.ommers:
                r = reward * (8 - (header.number - o.number)) // 8
                if r > 0:
                    state.add_balance(o.beneficiary, r)
        if state_hook is not None:
            flush_hook()  # rewards/withdrawals/system-call keys
        out.gas_used = cumulative_gas
        out.changes = state.changes
        out.post_accounts, out.post_storage = state.final_state()
        return out

    # -- system calls (EIP-4788/2935/7002/7251) ---------------------------

    def _system_call(self, state: EvmState, env: BlockEnv, spec: Spec,
                     target: bytes, data: bytes) -> bytes | None:
        """One system transaction: caller = SYSTEM_ADDRESS, 30M gas, no
        fees, not metered in the block; skipped when the contract is
        absent. Returns the call output (request contracts) or None."""
        code = state.code(target)
        if not code:
            return None
        state.begin_tx()
        interp = Interpreter(
            state, env, TxEnv(origin=SYSTEM_ADDRESS, gas_price=0), spec=spec)
        frame = CallFrame(caller=SYSTEM_ADDRESS, address=target, code=code,
                          data=data, value=0, gas=30_000_000, kind="CALL")
        try:
            ok, _gas_left, out = interp.call(frame)
        except (Revert, Halt) as e:
            # a failed mandatory system call invalidates the BLOCK (the
            # reference's BlockExecutionError / EIP-7002 "call must not
            # fail") — silently returning None here would let a block with
            # a broken system contract slip through with wrong requests
            raise BlockExecutionError(
                f"system call to 0x{target.hex()} "
                f"{type(e).__name__.lower()}ed: {e}") from e
        state.process_destructs()
        if not ok:
            raise BlockExecutionError(
                f"system call to 0x{target.hex()} failed")
        return out

    def _collect_requests(self, state: EvmState, env: BlockEnv, spec: Spec,
                          receipts: list[Receipt]) -> list[bytes]:
        """EIP-7685 requests: 0x00 deposits (EIP-6110, parsed from deposit
        contract logs), 0x01 withdrawals (EIP-7002 system call), 0x02
        consolidations (EIP-7251). Empty payloads are excluded."""
        deposit_contract = MAINNET_DEPOSIT_CONTRACT
        if self.config.chainspec is not None and \
                getattr(self.config.chainspec, "deposit_contract", None):
            deposit_contract = self.config.chainspec.deposit_contract
        deposits = b""
        for receipt in receipts:
            for log in receipt.logs:
                if log.address == deposit_contract and log.topics and \
                        log.topics[0] == DEPOSIT_EVENT_TOPIC:
                    deposits += _decode_deposit_log(log.data)
        requests = []
        if deposits:
            requests.append(b"\x00" + deposits)
        withdrawals = self._system_call(state, env, spec,
                                        WITHDRAWAL_REQUEST_ADDRESS, b"")
        if withdrawals:
            requests.append(b"\x01" + withdrawals)
        consolidations = self._system_call(state, env, spec,
                                           CONSOLIDATION_REQUEST_ADDRESS, b"")
        if consolidations:
            requests.append(b"\x02" + consolidations)
        return requests

    def _execute_tx(
        self, state: EvmState, env: BlockEnv, tx: Transaction, sender: bytes,
        gas_available: int, tracer=None, spec: Spec | None = None,
    ) -> TxResult:
        if spec is None:
            spec = self.config.spec_for(env.number, env.timestamp)
        base_fee = env.base_fee
        # -- validation (reference: EthTransactionValidator + pre-exec checks)
        if tx.tx_type > spec.max_tx_type:
            raise InvalidTransaction(
                f"tx type {tx.tx_type} not active in {spec.name}")
        if tx.chain_id is not None and not spec.eip155:
            raise InvalidTransaction("chain-id signature before EIP-155")
        if tx.gas_limit > gas_available:
            raise InvalidTransaction("block gas limit exceeded")
        if tx.chain_id is not None and tx.chain_id != env.chain_id:
            raise InvalidTransaction("wrong chain id")
        gas_price = tx.effective_gas_price(base_fee)
        if tx.tx_type >= 2 and tx.max_fee_per_gas < base_fee:
            raise InvalidTransaction("max fee below base fee")
        if tx.tx_type < 2 and gas_price < base_fee:  # legacy + EIP-2930
            raise InvalidTransaction("gas price below base fee")
        blob_fee = 0
        if tx.tx_type == EIP4844_TX_TYPE:
            # EIP-4844: blob txs must target a contract and carry blobs
            if tx.to is None:
                raise InvalidTransaction("blob tx cannot create")
            if not tx.blob_versioned_hashes:
                raise InvalidTransaction("blob tx without blobs")
            if any(len(h) != 32 or h[0] != 0x01 for h in tx.blob_versioned_hashes):
                raise InvalidTransaction("malformed blob versioned hash")
            if tx.max_fee_per_blob_gas < env.blob_base_fee:
                raise InvalidTransaction("max blob fee below blob base fee")
            blob_fee = tx.blob_gas() * env.blob_base_fee
        if tx.tx_type == EIP7702_TX_TYPE:
            if tx.to is None:
                raise InvalidTransaction("set-code tx cannot create")
            if not tx.authorization_list:
                raise InvalidTransaction("set-code tx without authorizations")
        acct = state.account_or_empty(sender)
        if acct.nonce != tx.nonce and not self.config.disable_nonce_check:
            raise InvalidTransaction(f"nonce mismatch: acct {acct.nonce} vs tx {tx.nonce}")
        # EIP-3607: reject txs from senders with deployed code (a 7702
        # delegation designator is not "code" for this rule)
        sender_code = state.code(sender)
        if sender_code and not self.config.disable_eip3607 and not (
                sender_code[:3] == DELEGATION_PREFIX and len(sender_code) == 23):
            raise InvalidTransaction("sender is a contract (EIP-3607)")
        max_cost = tx.gas_limit * (tx.max_fee_per_gas if tx.tx_type >= 2 else tx.gas_price)
        max_cost += tx.blob_gas() * tx.max_fee_per_blob_gas
        if acct.balance < max_cost + tx.value:
            raise InvalidTransaction("insufficient funds")
        ig = intrinsic_gas(tx, spec)
        if tx.gas_limit < ig:
            raise InvalidTransaction("intrinsic gas too high")
        if spec.calldata_floor and tx.gas_limit < calldata_floor_gas(tx):
            raise InvalidTransaction("gas limit below EIP-7623 calldata floor")
        if spec.initcode_limit and tx.to is None and len(tx.data) > MAX_INITCODE_SIZE:
            raise InvalidTransaction("initcode too large")

        # -- setup
        state.begin_tx()
        interp = Interpreter(
            state, env,
            TxEnv(origin=sender, gas_price=gas_price,
                  blob_hashes=tuple(tx.blob_versioned_hashes)),
            tracer=tracer, spec=spec,
        )
        # buy gas (+ the blob fee, burned — EIP-4844)
        state.sub_balance(sender, tx.gas_limit * gas_price + blob_fee)
        state.bump_nonce(sender)
        if spec.warm_cold:
            # warm: sender, coinbase (EIP-3651), target, precompiles
            # (EIP-2929 initialises accessed_addresses with them), access list
            state.warm_account(sender)
            if spec.warm_coinbase:
                state.warm_account(env.coinbase)
            for i in range(1, spec.precompiles + 1):
                state.warm_account(b"\x00" * 19 + bytes([i]))
            if tx.to is not None:
                state.warm_account(tx.to)
            for addr, slots in tx.access_list:
                state.warm_account(addr)
                for s in slots:
                    state.warm_slot(addr, s)
        if tx.tx_type == EIP7702_TX_TYPE:
            self._apply_authorizations(state, env, tx)

        gas = tx.gas_limit - ig
        success, output = True, b""
        try:
            if tx.to is None:
                ok, gas_left, _addr, output = interp.create(
                    sender, tx.value, tx.data, gas, 0, tx_nonce=tx.nonce
                )
                success = ok
            else:
                # EIP-7702: a delegated destination executes the delegate's
                # code in tx.to's context. At the TOP level the delegation
                # target joins accessed_addresses for free (the EIP extends
                # EIP-2929's initialization); only CALL-family opcodes charge
                # the extra account access.
                code, target = (resolve_delegation(state, tx.to)
                                if spec.has_setcode else (state.code(tx.to), None))
                if target is not None:
                    state.warm_account(target)
                frame = CallFrame(
                    caller=sender, address=tx.to, code=code,
                    data=tx.data, value=tx.value, gas=gas,
                )
                try:
                    ok, gas_left, output = interp.call(frame)
                    success = ok
                except Revert as r:
                    success, gas_left, output = False, getattr(r, "gas_left", 0), r.output
                except Halt:
                    success, gas_left, output = False, 0, b""
        except PrecompileNotImplemented as e:
            # a silently-stubbed precompile would corrupt the state root
            # without tripping any invariant — fail the BLOCK loudly instead
            raise BlockExecutionError(str(e)) from e

        gas_used = tx.gas_limit - gas_left
        # refunds: capped at 1/2 of used gas pre-London, 1/5 after (EIP-3529).
        # Failed txs keep no refund; pre-Byzantium a "failed" top-level frame
        # consumed everything anyway.
        if success:
            refund = min(state.refund, gas_used // spec.refund_quotient)
            gas_used -= refund
        if spec.calldata_floor:  # EIP-7623: calldata-heavy txs pay the floor
            gas_used = max(gas_used, calldata_floor_gas(tx))
        # refund unused gas, pay coinbase the priority fee, burn base fee
        # (pre-1559 base_fee is 0, so the miner gets the full fee)
        state.add_balance(sender, (tx.gas_limit - gas_used) * gas_price)
        priority = gas_price - base_fee
        if priority > 0:
            self._credit_coinbase(state, env, gas_used * priority)
        # failed frames already popped their logs via journal revert
        logs = state.take_logs()
        state.process_destructs()
        if spec.state_clearing:  # EIP-161
            state.delete_empty_touched()
        else:
            state._touched.clear()
        return TxResult(
            receipt=Receipt(tx_type=tx.tx_type, success=success, logs=tuple(logs)),
            gas_used=gas_used,
            success=success,
            output=output,
        )


    def _apply_authorizations(self, state: EvmState, env: BlockEnv, tx: Transaction):
        """EIP-7702 set-code processing: each valid authorization installs a
        delegation designator (0xef0100 ++ address) as the authority's code.
        Invalid tuples are SKIPPED, never fatal (per spec)."""
        for auth in tx.authorization_list:
            if len(auth.address) != 20:
                continue
            if auth.chain_id not in (0, env.chain_id):
                continue
            if auth.nonce >= 2**64 - 1:
                continue
            if auth.s > SECP256K1_N // 2 or auth.y_parity not in (0, 1):
                continue
            try:
                authority = auth.recover_authority()
            except ValueError:
                continue
            state.warm_account(authority)
            code = state.code(authority)
            if code and not (code[:3] == DELEGATION_PREFIX and len(code) == 23):
                continue  # real contract code cannot be overridden
            if state.nonce(authority) != auth.nonce:
                continue
            if state.exists(authority) and not state.is_empty(authority):
                state.add_refund(PER_EMPTY_ACCOUNT_COST - PER_AUTH_BASE_COST)
            state._capture_account_change(authority)
            if auth.address == b"\x00" * 20:
                state.set_code(authority, b"")  # clear the delegation
            else:
                state.set_code(authority, DELEGATION_PREFIX + auth.address)
            state.set_nonce(authority, auth.nonce + 1)


class ProviderStateSource(StateSource):
    """StateSource over a DatabaseProvider's plain state."""

    def __init__(self, provider):
        self.provider = provider

    def account(self, address: bytes) -> Account | None:
        return self.provider.account(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        return self.provider.storage(address, slot)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.provider.bytecode(code_hash) or b""


class InMemoryStateSource(StateSource):
    """Dict-backed source for tests and genesis building."""

    def __init__(self, accounts=None, storages=None, codes=None):
        self.accounts = dict(accounts or {})
        self.storages = {a: dict(s) for a, s in (storages or {}).items()}
        self.codes = dict(codes or {})

    def account(self, address: bytes) -> Account | None:
        return self.accounts.get(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        return self.storages.get(address, {}).get(slot, 0)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.codes.get(code_hash, b"")
