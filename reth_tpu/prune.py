"""Segment-based pruning of historical data.

Reference analogue: crates/prune — `Pruner` with per-segment run limits
(src/pruner.rs, src/segments/) and `PruneModes` config. Segments:
sender recovery, receipts, transaction lookup, account/storage
changesets. Runs after persistence advances; respects a per-run delete
limit so pruning never stalls the node.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .storage.provider import DatabaseProvider, ProviderFactory
from .storage.tables import Tables, be64, from_be64


@dataclass
class PruneMode:
    """How far a segment keeps history: keep everything (None), keep the
    last ``distance`` blocks, or prune everything before ``before``."""

    distance: int | None = None
    before: int | None = None

    def prune_target(self, tip: int) -> int | None:
        """Highest block whose data may be pruned, or None."""
        if self.before is not None:
            return min(self.before - 1, tip)
        if self.distance is not None:
            return tip - self.distance - 1 if tip > self.distance else None
        return None


@dataclass
class PruneModes:
    sender_recovery: PruneMode = field(default_factory=PruneMode)
    receipts: PruneMode = field(default_factory=PruneMode)
    transaction_lookup: PruneMode = field(default_factory=PruneMode)
    account_history: PruneMode = field(default_factory=PruneMode)
    storage_history: PruneMode = field(default_factory=PruneMode)


@dataclass
class PruneProgress:
    segment: str
    pruned: int
    done: bool


class Pruner:
    def __init__(self, factory: ProviderFactory, modes: PruneModes,
                 delete_limit_per_run: int = 10_000):
        self.factory = factory
        self.modes = modes
        self.delete_limit = delete_limit_per_run

    def run(self, tip: int) -> list[PruneProgress]:
        """One pruning pass up to ``tip``; returns per-segment progress."""
        out = []
        with self.factory.provider_rw() as p:
            budget = self.delete_limit
            for name, mode, fn in [
                ("SenderRecovery", self.modes.sender_recovery, self._prune_senders),
                ("Receipts", self.modes.receipts, self._prune_receipts),
                ("TransactionLookup", self.modes.transaction_lookup, self._prune_lookup),
                ("AccountHistory", self.modes.account_history, self._prune_account_history),
                ("StorageHistory", self.modes.storage_history, self._prune_storage_history),
            ]:
                target = mode.prune_target(tip)
                if target is None:
                    continue
                checkpoint = self._checkpoint(p, name)
                if checkpoint > target:
                    continue
                pruned, done, new_cp = fn(p, checkpoint, target, budget)
                budget -= pruned
                p.tx.put(Tables.PruneCheckpoints.name, name.encode(), be64(new_cp))
                out.append(PruneProgress(name, pruned, done))
                if budget <= 0:
                    break
        return out

    def _checkpoint(self, p: DatabaseProvider, segment: str) -> int:
        raw = p.tx.get(Tables.PruneCheckpoints.name, segment.encode())
        return from_be64(raw) if raw else 0

    # each segment prunes tx-number- or block-keyed rows in [checkpoint, target]

    def _tx_range(self, p, start_block, end_block):
        first = p.block_body_indices(start_block)
        last = p.block_body_indices(end_block)
        if first is None or last is None:
            return None
        return first.first_tx_num, last.next_tx_num

    def _prune_tx_keyed(self, p, table, checkpoint, target, budget):
        rng = self._tx_range(p, checkpoint, target)
        if rng is None:
            return 0, True, target + 1
        lo, hi = rng
        cur = p.tx.cursor(table)
        doomed = []
        for k, _ in cur.walk_range(be64(lo), be64(hi)):
            doomed.append(k)
            if len(doomed) >= budget:
                break
        for k in doomed:
            p.tx.delete(table, k)
        done = len(doomed) < budget
        # conservative checkpoint: only advance fully when done
        return len(doomed), done, (target + 1 if done else checkpoint)

    def _prune_senders(self, p, checkpoint, target, budget):
        return self._prune_tx_keyed(p, Tables.TransactionSenders.name, checkpoint, target, budget)

    def _prune_receipts(self, p, checkpoint, target, budget):
        return self._prune_tx_keyed(p, Tables.Receipts.name, checkpoint, target, budget)

    def _prune_lookup(self, p, checkpoint, target, budget):
        # Scan the hash→number index directly: works even when the tx rows
        # themselves were moved to static files or already pruned.
        rng = self._tx_range(p, checkpoint, target)
        if rng is None:
            return 0, True, target + 1
        lo, hi = rng
        cur = p.tx.cursor(Tables.TransactionHashNumbers.name)
        doomed = []
        for h, v in cur.walk():
            if lo <= from_be64(v) < hi:
                doomed.append(h)
                if len(doomed) >= budget:
                    break
        for h in doomed:
            p.tx.delete(Tables.TransactionHashNumbers.name, h)
        done = len(doomed) < budget
        return len(doomed), done, (target + 1 if done else checkpoint)

    def _prune_block_keyed(self, p, table, checkpoint, target, budget):
        cur = p.tx.cursor(table)
        doomed = set()
        for k, _ in cur.walk_range(be64(checkpoint), be64(target + 1)):
            doomed.add(k)
            if len(doomed) >= budget:
                break
        for k in doomed:
            p.tx.delete(table, k)
        done = len(doomed) < budget
        return len(doomed), done, (target + 1 if done else checkpoint)

    def _prune_account_history(self, p, checkpoint, target, budget):
        return self._prune_block_keyed(p, Tables.AccountChangeSets.name, checkpoint, target, budget)

    def _prune_storage_history(self, p, checkpoint, target, budget):
        return self._prune_block_keyed(p, Tables.StorageChangeSets.name, checkpoint, target, budget)
