"""Command-line interface: init / import / node / db / stage commands.

Reference analogue: bin/reth (`Cli::run`, Commands enum —
crates/ethereum/cli/src/interface.rs:284) and crates/cli/commands
(init, import, db stats, stage run…). Genesis files use the geth-style
JSON schema (chainId + alloc).

Run as ``python -m reth_tpu <command> ...``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def _num(v, default=0) -> int:
    """Genesis numeric field: hex string, decimal string, or JSON number
    (geth's math.HexOrDecimal256 accepts all three)."""
    if v is None:
        return default
    if isinstance(v, int):
        return v
    s = str(v)
    if s.startswith(("0x", "0X")):
        return int(s, 16)
    return int(s)


def _resolve_warmup(args) -> tuple[str, str | None]:
    """(mode, cache_dir) for the warm-up manager: flags beat env; the cache
    dir defaults under the datadir once warm-up is on."""
    import os

    mode = (getattr(args, "warmup", None)
            or os.environ.get("RETH_TPU_WARMUP") or "off")
    cache_dir = (getattr(args, "compile_cache_dir", None)
                 or os.environ.get("RETH_TPU_COMPILE_CACHE_DIR"))
    if not cache_dir and mode != "off" and getattr(args, "datadir", None):
        cache_dir = str(Path(args.datadir) / "compile-cache")
    return mode, cache_dir


def _resolve_wal(args) -> bool:
    """memdb write-ahead log: --wal/--no-wal beats RETH_TPU_WAL beats
    the on-by-default (storage/wal.py no-ops for non-memdb engines)."""
    import os

    flag = getattr(args, "wal", None)
    if flag is not None:
        return flag
    env = os.environ.get("RETH_TPU_WAL")
    if env is not None:
        return env not in ("", "0")
    return True


def _resolve_mesh(args) -> int:
    """Device-mesh width: --mesh beats RETH_TPU_MESH beats [node]
    mesh_devices (reth.toml); 0/1 = the mesh layer stays off."""
    import os

    n = getattr(args, "mesh", None)
    if n is None:
        n = os.environ.get("RETH_TPU_MESH") or 0
    return int(n or 0)


def _resolve_subtrie(args) -> int:
    """Whole-subtrie k-level fused kernels: --subtrie-levels beats
    RETH_TPU_SUBTRIE_LEVELS beats [node] subtrie_levels (reth.toml);
    0/1 = per-level dispatching. The resolved k is exported back into
    the env so EVERY consumer (TurboCommitter, ParallelSparseCommitter,
    HashService window requests) picks it up without plumbing."""
    import os

    k = getattr(args, "subtrie_levels", None)
    if k is None:
        k = os.environ.get("RETH_TPU_SUBTRIE_LEVELS") or 0
    k = int(k or 0)
    if k > 1:
        os.environ["RETH_TPU_SUBTRIE_LEVELS"] = str(k)
    return k


def _make_committer(args):
    from .trie.committer import TrieCommitter

    _resolve_subtrie(args)
    mode = getattr(args, "hasher", "device")
    warm_mode, cache_dir = _resolve_warmup(args)
    mesh_n = _resolve_mesh(args) if mode != "cpu" else 0
    hash_mesh = None
    if mesh_n > 1:
        # --mesh: the real device-mesh descriptor (parallel/mesh.py) —
        # health mask + sub-mesh leases + the partition-rule table. Turbo
        # committers shard fused level windows over it; with
        # --hash-service the service routes every coalesced dispatch
        # through it (per-device breakers, partial-mesh degradation).
        from .parallel.mesh import HashMesh

        hash_mesh = HashMesh.build(mesh_n)
        mesh_n = hash_mesh.n_devices  # clamped to the available topology
    warmup = None
    if mode != "cpu" and warm_mode != "off":
        # device warm-up manager (ops/warmup.py): the shape menu AOT-
        # compiles under per-shape watchdog budgets while the node serves
        # degraded on the CPU twin; the persistent compile cache (keyed
        # under the datadir, probe-verified) makes restarts near-free
        from .ops.warmup import build_warmup
    if mode == "cpu":
        from .primitives.keccak import keccak256_batch_np

        committer = TrieCommitter(hasher=keccak256_batch_np)
        committer.turbo_backend = "numpy"  # MerkleStage clean-path backend
    elif mode == "auto":
        # supervised device route (ops/supervisor.py): startup health
        # probe, watchdog-bounded dispatch, circuit breaker with CPU
        # failover — a wedged tunnel degrades the node, never hangs it
        from .ops.supervisor import DeviceSupervisor

        sup = DeviceSupervisor.shared()
        healthy = sup.startup()
        if warm_mode != "off":
            warmup = build_warmup(supervisor=sup, cache_dir=cache_dir,
                                  mesh_size=max(1, mesh_n))
        committer = TrieCommitter(supervisor=sup, warmup=warmup)
        committer.turbo_backend = "auto"
        if not healthy:
            print(f"hasher auto: device unhealthy at startup "
                  f"({sup.last_probe.diag}); routing to cpu until a "
                  f"re-probe succeeds", file=sys.stderr)
    else:
        if warm_mode != "off":
            warmup = build_warmup(cache_dir=cache_dir,
                                  mesh_size=max(1, mesh_n))
        committer = TrieCommitter(warmup=warmup)
        committer.turbo_backend = "device"
    if warmup is not None:
        committer.warmup = warmup
        if warm_mode == "block":
            # blocking warm-up: nothing dispatches before the menu is warm
            # (offline commands — init/import — prefer determinism)
            warmup.run()
        else:
            warmup.start()
    if hash_mesh is not None:
        # mesh without a service still shards the turbo committers'
        # fused level loops (stages/merkle, incremental full rebuild)
        committer.hash_mesh = hash_mesh
    if getattr(args, "hash_service", False):
        # --hash-service: ONE background service owns the (supervised)
        # hashing backend and multiplexes every client over priority lanes
        # (ops/hash_service.py). The committer's own hasher becomes the
        # live-tip lane client; call sites pick other lanes via for_lane.
        # With --mesh the service owns the MESH: coalesced dispatches
        # route through the partition-rule table, rebuild commits take
        # sub-mesh leases, per-device breakers degrade partially.
        from .ops.hash_service import HashService

        committer.hash_service = HashService(
            backend=committer.hasher,
            supervisor=getattr(committer, "supervisor", None),
            mesh=hash_mesh, warmup=warmup)
        committer.hasher = committer.hash_service.client("live")
    return committer


# Built-in dev-mode genesis (reference --dev auto-installs a dev chainspec).
# Funded key: the standard dev mnemonic's first account.
DEV_PRIVATE_KEY = 0xAC0974BEC39A17E36BA4A6B4D238FF944BACB478CBED5EFCAE784D7BF4F2FF80


def _dev_genesis_spec() -> dict:
    from .primitives import secp256k1

    addr = secp256k1.address_from_priv(DEV_PRIVATE_KEY)
    return {
        "config": {"chainId": 1337},
        "gasLimit": hex(30_000_000),
        "alloc": {"0x" + addr.hex(): {"balance": hex(10**24)}},
    }


def _load_genesis(path: str | None, committer, spec: dict | None = None):
    from .primitives.types import Account, Header, EMPTY_ROOT_HASH
    from .primitives.keccak import keccak256

    if spec is None:
        spec = json.loads(Path(path).read_text())
    alloc = {}
    storage = {}
    codes = {}
    for addr_hex, entry in spec.get("alloc", {}).items():
        addr = bytes.fromhex(addr_hex.removeprefix("0x"))
        code = bytes.fromhex(entry.get("code", "0x")[2:]) if entry.get("code") else b""
        code_hash = keccak256(code) if code else keccak256(b"")
        alloc[addr] = Account(
            nonce=_num(entry.get("nonce")),
            balance=_num(entry.get("balance")),
            code_hash=code_hash,
        )
        if code:
            codes[code_hash] = code
        if entry.get("storage"):
            storage[addr] = {
                _num(k).to_bytes(32, "big"): _num(v)
                for k, v in entry["storage"].items()
            }
    config = spec.get("config", {})
    chain_id = _num(config.get("chainId"), 1)
    from .trie.state_root import state_root

    root, _ = state_root(alloc, storage, committer=committer)
    from .chainspec import ChainSpec

    common = dict(
        number=0,
        state_root=root,
        gas_limit=_num(spec.get("gasLimit"), 30_000_000),
        timestamp=_num(spec.get("timestamp")),
        extra_data=bytes.fromhex(spec.get("extraData", "0x")[2:]),
        difficulty=_num(spec.get("difficulty")),
        beneficiary=bytes.fromhex(spec.get("coinbase", "0x" + "00" * 20)[2:]),
        mix_hash=bytes.fromhex(spec.get("mixHash", "0x" + "00" * 32)[2:]),
        nonce=_num(spec.get("nonce")).to_bytes(8, "big"),
    )
    if ChainSpec.config_has_forks(config):
        # explicit schedule: build the genesis header with exactly the
        # fields its genesis-time fork carries (geth's genesis ToBlock)
        cs_tmp = ChainSpec.from_genesis_config(config, chain_id=chain_id)
        from .evm.spec import spec_for_block

        s0 = spec_for_block(cs_tmp, 0, common["timestamp"])
        import hashlib as _hashlib

        header = Header(
            **common,
            base_fee_per_gas=(_num(spec.get("baseFeePerGas"), 10**9)
                              if s0.has_basefee or spec.get("baseFeePerGas")
                              else None),
            withdrawals_root=EMPTY_ROOT_HASH if s0.has_withdrawals else None,
            blob_gas_used=_num(spec.get("blobGasUsed"), 0) if s0.blob else None,
            excess_blob_gas=(_num(spec.get("excessBlobGas"), 0)
                             if s0.blob else None),
            parent_beacon_block_root=(b"\x00" * 32 if s0.beacon_root_call
                                      else None),
            requests_hash=(_hashlib.sha256().digest() if s0.has_requests
                           else None),
        )
    else:
        # dev-style genesis (no schedule): keep the repo's legacy shape
        header = Header(
            **common,
            base_fee_per_gas=_num(spec.get("baseFeePerGas"), 10**9),
            withdrawals_root=None if spec.get("preMerge") else EMPTY_ROOT_HASH,
        )
    chain_spec = ChainSpec.from_genesis_config(
        config, genesis_hash=header.hash, chain_id=chain_id)
    return header, alloc, storage, codes, chain_id, chain_spec


def cmd_init(args):
    from .node import Node, NodeConfig

    committer = _make_committer(args)
    header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(args.genesis, committer)
    cfg = NodeConfig(
        chain_id=chain_id, datadir=args.datadir, genesis_header=header,
        genesis_alloc=alloc, genesis_storage=storage, genesis_codes=codes,
        chain_spec=chain_spec, db_backend=_resolve_backend(args),
        storage_v2=getattr(args, "storage_v2", None),
    )
    node = Node(cfg, committer=committer)
    node.factory.db.flush()
    print(f"genesis initialised: hash=0x{header.hash.hex()} chain_id={chain_id}")
    return 0


def cmd_import(args):
    from .consensus import EthBeaconConsensus
    from .node import Node, NodeConfig
    from .primitives.types import Block
    from .stages import Pipeline, default_stages
    from .storage.genesis import import_chain

    committer = _make_committer(args)
    header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(args.genesis, committer)
    cfg = NodeConfig(chain_id=chain_id, datadir=args.datadir, genesis_header=header,
                     genesis_alloc=alloc, genesis_storage=storage, genesis_codes=codes,
                     chain_spec=chain_spec, db_backend=_resolve_backend(args),
                     storage_v2=getattr(args, "storage_v2", None))
    node = Node(cfg, committer=committer)
    raw = Path(args.file).read_bytes()
    blocks = []
    pos = 0
    from .primitives.rlp import _decode_at

    while pos < len(raw):
        _item, end = _decode_at(raw, pos)
        blocks.append(Block.decode(raw[pos:end]))
        pos = end
    from .evm import EvmConfig as _EvmConfig

    exec_spec = chain_spec.execution_spec
    consensus = EthBeaconConsensus(node.committer, chainspec=exec_spec)
    tip = import_chain(node.factory, blocks, consensus)
    print(f"imported {len(blocks)} blocks, tip={tip}")
    t0 = time.time()
    pipeline = Pipeline(node.factory, default_stages(
        committer=node.committer, consensus=consensus,
        evm_config=_EvmConfig(chain_id=chain_id, chainspec=exec_spec)))
    pipeline.run(tip)
    node.factory.db.flush()
    print(f"pipeline synced to {tip} in {time.time()-t0:.2f}s")
    return 0


def cmd_import_era(args):
    from .consensus import EthBeaconConsensus
    from .era import import_era, read_era1
    from .node import Node, NodeConfig
    from .stages import Pipeline, default_stages

    committer = _make_committer(args)
    header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(args.genesis, committer)
    cfg = NodeConfig(chain_id=chain_id, datadir=args.datadir, genesis_header=header,
                     genesis_alloc=alloc, genesis_storage=storage, genesis_codes=codes,
                     chain_spec=chain_spec, db_backend=_resolve_backend(args))
    node = Node(cfg, committer=committer)
    consensus = EthBeaconConsensus(node.committer)
    if args.source:
        # checksummed multi-archive source driven by the Era STAGE
        # (reference era-downloader + EraStage)
        from .era_sync import EraDownloader, EraStage, era_source_for

        dl = EraDownloader(era_source_for(args.source),
                           Path(args.datadir) / "era-cache")
        paths = dl.fetch_all()
        tip = max(
            read_era1(p).start_block + len(read_era1(p).blocks) - 1
            for p in paths
        )
        stages = [EraStage(dl, consensus)] + default_stages(committer=node.committer)
        print(f"era source verified: {len(paths)} archives, tip={tip}")
        Pipeline(node.factory, stages).run(tip)
    else:
        tip = import_era(node.factory, args.file, consensus)
        print(f"imported era1 file, tip={tip}")
        Pipeline(node.factory, default_stages(committer=node.committer)).run(tip)
    node.factory.db.flush()
    print(f"pipeline synced to {tip}")
    return 0


def cmd_export_era(args):
    from .era import export_era
    from .storage import ProviderFactory

    factory = ProviderFactory(_open_db(args))
    n = export_era(factory, args.first, args.last, args.file)
    print(f"exported {n} blocks to {args.file}")
    return 0


def _env_trace_enabled() -> bool:
    from .tracing import _env_enabled

    return _env_enabled()


def cmd_node(args):
    from .node import Node, NodeConfig

    if getattr(args, "role", "full") == "replica":
        # the stateless read-replica role holds no database and builds
        # no committer: everything it serves arrives over the feed
        if not getattr(args, "feed", None):
            print("error: --role replica needs --feed HOST:PORT",
                  file=sys.stderr)
            return 1
        from .fleet.__main__ import main as fleet_main

        argv = ["replica", "--feed", args.feed,
                "--http-port", str(args.http_port),
                "--retention", str(args.replica_retention)]
        if getattr(args, "register", None):
            argv += ["--register", args.register]
        return fleet_main(argv)
    if getattr(args, "role", "full") == "standby":
        # the hot-standby role replays the leader's WAL stream into its
        # own datadir and only becomes a full node at promotion time
        if not getattr(args, "feed", None):
            print("error: --role standby needs --feed HOST:PORT",
                  file=sys.stderr)
            return 1
        if not args.datadir:
            print("error: --role standby needs --datadir",
                  file=sys.stderr)
            return 1
        from .fleet.__main__ import main as fleet_main

        argv = ["standby", "--feed", args.feed,
                "--datadir", args.datadir,
                "--http-port", str(args.http_port),
                "--takeover-feed-port", str(args.takeover_feed_port),
                "--heartbeat-timeout", str(args.heartbeat_timeout)]
        if getattr(args, "no_auto_promote", False):
            argv += ["--no-auto-promote"]
        return fleet_main(argv)
    committer = _make_committer(args)
    backend = _resolve_backend(args)
    if args.db_backend in ("paged", "native") and not args.datadir:
        print(f"error: --db {args.db_backend} is a persistent engine and "
              "needs --datadir", file=sys.stderr)
        return 1
    if not args.datadir:
        backend = "memdb"  # ephemeral node: in-process store
    kw = {}
    if args.genesis:
        header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(args.genesis, committer)
        kw = dict(genesis_header=header, genesis_alloc=alloc,
                  genesis_storage=storage, genesis_codes=codes, chain_id=chain_id,
                  chain_spec=chain_spec)
    elif args.dev:
        # reference --dev auto-installs a dev chainspec with a funded key
        header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(
            None, committer, spec=_dev_genesis_spec()
        )
        kw = dict(genesis_header=header, genesis_alloc=alloc,
                  genesis_storage=storage, genesis_codes=codes, chain_id=chain_id,
                  chain_spec=chain_spec)
        print(f"dev genesis: funded key 0x{DEV_PRIVATE_KEY:064x}")
    else:
        # no genesis given: the datadir must already be initialised. The
        # persistent engines are probed by their on-disk artifacts (opening
        # them here would double-open the store the Node is about to own).
        initialised = False
        if args.datadir:
            from .storage import store_initialised

            initialised = store_initialised(backend, args.datadir)
        if not initialised:
            print("error: no genesis — pass --genesis or run `init`, or use --dev",
                  file=sys.stderr)
            return 1
    jwt_secret = None
    if args.authrpc_jwtsecret:
        from .rpc.jwt import load_or_create_secret

        jwt_secret = load_or_create_secret(args.authrpc_jwtsecret)
    warm_mode, warm_cache = _resolve_warmup(args)
    cfg = NodeConfig(datadir=args.datadir, dev=args.dev,
                     http_port=args.http_port, authrpc_port=args.authrpc_port,
                     jwt_secret=jwt_secret, ws_port=args.ws_port,
                     ipc_path=args.ipc_path, enable_admin=args.enable_admin,
                     p2p_port=args.port if not args.disable_p2p else None,
                     p2p_host=args.addr,
                     discovery=not args.no_discovery,
                     nat=args.nat,
                     bootnodes=tuple(args.bootnodes.split(",")) if args.bootnodes else (),
                     bootnodes_v5=tuple(args.bootnodes_v5.split(",")) if args.bootnodes_v5 else (),
                     db_backend=backend,
                     storage_v2=getattr(args, "storage_v2", None),
                     sparse_workers=getattr(args, "sparse_workers", None),
                     parallel_exec=getattr(args, "parallel_exec", False),
                     pipeline_depth=getattr(args, "pipeline_depth", None),
                     continuous_build=getattr(args, "continuous_build",
                                              False),
                     hot_state=getattr(args, "hot_state", False),
                     rpc_gateway=getattr(args, "rpc_gateway", False),
                     warmup=warm_mode,
                     compile_cache_dir=warm_cache,
                     health=getattr(args, "health", False),
                     slo_interval=getattr(args, "slo_interval", 1.0),
                     slo_window=getattr(args, "slo_window", 300),
                     wal=_resolve_wal(args),
                     wal_checkpoint_blocks=getattr(
                         args, "wal_checkpoint_blocks", 8),
                     recovery_verify_root=getattr(
                         args, "recovery_verify_root", True),
                     invalid_cache_size=getattr(
                         args, "invalid_cache_size", None),
                     fleet=bool(getattr(args, "fleet", None)),
                     ha_peer_feeds=tuple(
                         getattr(args, "ha_peer_feeds", None) or ()),
                     feed_port=getattr(args, "feed_port", 0) or 0,
                     fleet_max_lag=(getattr(args, "fleet_max_lag", None)
                                    if getattr(args, "fleet_max_lag", None)
                                    is not None else 4),
                     # --trace-blocks; unset falls back to RETH_TPU_TRACE
                     trace_blocks=(args.trace_blocks
                                   if getattr(args, "trace_blocks", None)
                                   is not None
                                   else _env_trace_enabled()),
                     trace_file=getattr(args, "trace_file", None),
                     **kw)
    node = Node(cfg, committer=committer)
    p2p_port = node.start_network()
    if p2p_port is not None:
        print(f"P2P listening on {node.network.host}:{p2p_port} "
              f"({node.network.enode})")
        if node.discovery is not None:
            print(f"discv4 on udp/{node.discovery.port}")
    http_port, auth_port = node.start_rpc()
    print(f"RPC listening on 127.0.0.1:{http_port}, engine API on 127.0.0.1:{auth_port}")
    if node.feed_server is not None:
        print(f"witness feed on 127.0.0.1:{node.feed_server.port} "
              f"(replicas: --role replica --feed "
              f"127.0.0.1:{node.feed_server.port})")
    if getattr(args, "ethstats", None):
        from .ethstats import EthStatsService

        try:
            stats = EthStatsService(args.ethstats, node)
            stats.start()
            node.ethstats = stats
            print(f"ethstats reporting to {stats.host}:{stats.port} as {stats.node_name}")
        except OSError as e:
            print(f"ethstats connection failed: {e}", file=sys.stderr)
    if node.ws is not None:
        print(f"WebSocket RPC on 127.0.0.1:{node.ws.port}")
    if node.ipc is not None:
        print(f"IPC RPC at {node.ipc.path}")
    if args.dev and args.block_time > 0:
        print(f"dev mode: mining every {args.block_time}s")

        def mine_loop(shutdown):
            while not shutdown.wait(args.block_time):
                block = node.miner.mine_block(timestamp=int(time.time()))
                print(f"mined block {block.header.number} "
                      f"({len(block.transactions)} txs) 0x{block.hash.hex()[:16]}")

        node.tasks.spawn_critical("dev-miner", mine_loop)
    elif args.dev:
        # --block-time 0: geth-dev style instant sealing — mine the moment
        # the pool holds an executable transaction
        print("dev mode: instant sealing (mine on transaction)")

        def mine_on_tx(shutdown):
            while not shutdown.wait(0.05):
                if not node.pool.updated.is_set():
                    continue  # no pool activity since last look: no reads
                node.pool.updated.clear()
                # only seal when something is executable — queued-only
                # (nonce-gapped) pools must not grind out empty blocks
                if next(node.pool.best_transactions(), None) is None:
                    continue
                block = node.miner.mine_block(timestamp=int(time.time()))
                print(f"mined block {block.header.number} "
                      f"({len(block.transactions)} txs) 0x{block.hash.hex()[:16]}")

        node.tasks.spawn_critical("dev-miner", mine_on_tx)
    try:
        while not node.tasks.shutdown.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass
    node.stop()
    errors = node.tasks.critical_errors()
    for name, err in errors:
        print(f"critical task {name} failed: {err}", file=sys.stderr)
    return 1 if errors else 0


def _resolve_backend(args) -> str:
    """Pick the storage backend: an explicit --db always wins; otherwise a
    datadir that already holds a store keeps its engine (legacy datadirs
    must never silently open a brand-new empty default store); otherwise
    the paged default."""
    from .storage import store_initialised

    explicit = getattr(args, "db_backend", None)
    if explicit:
        return explicit
    datadir = getattr(args, "datadir", None)
    if datadir:
        for b in ("paged", "native", "memdb"):
            if store_initialised(b, datadir):
                return b
    return "paged"


def _open_db(args):
    """Open the datadir's database with the selected backend (reference:
    the database args shared by every offline command)."""
    from .storage import open_database

    Path(args.datadir).mkdir(parents=True, exist_ok=True)
    return open_database(_resolve_backend(args), args.datadir,
                         getattr(args, "storage_v2", None))


def cmd_db_get(args):
    """Print one table entry (reference `reth db get`)."""
    db = _open_db(args)
    with db.tx() as tx:
        key = bytes.fromhex(args.key.removeprefix("0x"))
        if args.subkey:
            sub = bytes.fromhex(args.subkey.removeprefix("0x"))
            entry = tx.cursor(args.table).seek_by_key_subkey(key, sub)
            val = entry[1] if entry else None
        else:
            val = tx.get(args.table, key)
    if val is None:
        print("not found", file=sys.stderr)
        return 1
    print("0x" + val.hex())
    return 0


def cmd_db_list(args):
    """List table entries from an offset (reference `reth db list`)."""
    db = _open_db(args)
    with db.tx() as tx:
        cur = tx.cursor(args.table)
        start = bytes.fromhex(args.start.removeprefix("0x")) if args.start else None
        shown = 0
        for key, val in cur.walk(start):
            print(f"0x{key.hex()}  0x{val.hex()[:2 * args.value_bytes]}"
                  + ("…" if len(val) > args.value_bytes else ""))
            shown += 1
            if shown >= args.limit:
                break
        print(f"-- {shown} entr{'y' if shown == 1 else 'ies'} "
              f"(of {tx.entry_count(args.table)})")
    return 0


def cmd_db_diff(args):
    """Compare two databases table-by-table (reference `reth db diff`)."""
    import argparse as _ap

    db_a = _open_db(args)
    db_b = _open_db(_ap.Namespace(datadir=args.other,
                                  db_backend=getattr(args, "db_backend", None)))
    tables = args.table.split(",") if args.table else None
    differences = 0
    with db_a.tx() as ta, db_b.tx() as tb:
        names = tables
        if names is None:
            from .storage.tables import TableDef, Tables

            names = sorted(v.name for v in vars(Tables).values()
                           if isinstance(v, TableDef))
        for name in names:
            ca, cb = ta.entry_count(name), tb.entry_count(name)
            seen = 0
            # keys only; values compared as whole duplicate sets (DUPSORT
            # tables hold several values per key)
            cur = ta.cursor(name)
            entry = cur.first()
            while entry is not None:
                key = entry[0]
                if ta.get_dups(name, key) != tb.get_dups(name, key):
                    differences += 1
                    seen += 1
                    if seen <= args.limit:
                        missing = tb.get(name, key) is None
                        print(f"{name}: 0x{key.hex()} "
                              f"{'missing' if missing else 'differs'}")
                entry = cur.next_no_dup()
            if ca != cb:
                differences += 1
                print(f"{name}: entry count {ca} != {cb}")
    print(f"{differences} difference(s)")
    return 0 if differences == 0 else 1


def cmd_db_repair_trie(args):
    """Rebuild the trie tables from the hashed state and fix divergences
    (reference `reth db repair-trie`): verify first, then clear + recompute
    stored branch nodes so the stored trie matches the leaves."""
    from .storage import ProviderFactory
    from .trie.incremental import full_state_root, verify_state_root

    factory = ProviderFactory(_open_db(args))
    committer = _make_committer(args)
    with factory.provider() as p:
        tip = p.stage_checkpoint("MerkleExecute")
        header = p.header_by_number(tip)
        if header is None:
            print("empty database (no merkle checkpoint)", file=sys.stderr)
            return 1
        try:
            root, problems = verify_state_root(p, committer)
        except Exception as e:  # noqa: BLE001 — corrupt nodes may not decode
            root, problems = None, [f"verification failed: {e}"]
        if root == header.state_root and not problems:
            print(f"trie OK at block {tip}: nothing to repair")
            return 0
    for msg in problems:
        print(f"REPAIRING: {msg}", file=sys.stderr)
    with factory.provider_rw() as p:
        from .storage.tables import Tables

        p.tx.clear(Tables.AccountsTrie.name)
        p.tx.clear(Tables.StoragesTrie.name)
        new_root = full_state_root(p, committer)
        if new_root != header.state_root:
            print(f"REPAIR FAILED: rebuilt 0x{new_root.hex()} != header "
                  f"0x{header.state_root.hex()} — hashed state itself is bad",
                  file=sys.stderr)
            return 1
    factory.db.flush()
    print(f"trie repaired at block {tip}: 0x{new_root.hex()}")
    return 0


def cmd_init_state(args):
    """Initialise a database from a state dump at a given block (reference
    `reth init-state`: sync-from-state for chains with huge history)."""
    from .storage import ProviderFactory
    from .storage.genesis import init_genesis
    from .primitives.types import Header

    with open(args.state) as f:
        dump = json.load(f)
    unhex = lambda x: bytes.fromhex(x.removeprefix("0x"))  # noqa: E731
    header = Header.decode(unhex(dump["header"]))
    alloc, storage, codes = {}, {}, {}
    from .primitives.types import Account
    from .primitives.keccak import keccak256

    for addr_hex, acct in dump.get("accounts", {}).items():
        addr = unhex(addr_hex)
        code = unhex(acct["code"]) if acct.get("code") else b""
        if code:
            codes[keccak256(code)] = code
        alloc[addr] = Account(
            nonce=int(acct.get("nonce", "0x0"), 16),
            balance=int(acct.get("balance", "0x0"), 16),
        )
        slots = {unhex(k): int(v, 16)
                 for k, v in acct.get("storage", {}).items()}
        if slots:
            storage[addr] = slots
    factory = ProviderFactory(_open_db(args))
    committer = _make_committer(args)
    got = init_genesis(factory, header, alloc, storage, codes,
                       committer=committer)
    factory.db.flush()
    print(f"state initialised at block {header.number}: 0x{got.hex()}")
    return 0


def cmd_test_vectors(args):
    """Generate deterministic codec/table test vectors (reference
    `reth test-vectors compact|tables`): random typed values round-tripped
    through the codecs, written as JSON for cross-version compatibility
    checks."""
    import numpy as np

    from .primitives.types import Account, Header
    from .storage.tables import (
        decode_account,
        encode_account,
        be64,
        from_be64,
    )

    rng = np.random.default_rng(args.seed)
    vectors = {"accounts": [], "headers": [], "be64": []}
    for _ in range(args.count):
        acct = Account(
            nonce=int(rng.integers(0, 2**40)),
            balance=int(rng.integers(0, 2**60)) * int(rng.integers(1, 2**30)),
            storage_root=bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
            code_hash=bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
        )
        enc = encode_account(acct)
        assert decode_account(enc) == acct
        vectors["accounts"].append("0x" + enc.hex())
        h = Header(
            number=int(rng.integers(0, 2**32)),
            timestamp=int(rng.integers(0, 2**32)),
            gas_limit=int(rng.integers(0, 2**30)),
            gas_used=int(rng.integers(0, 2**30)),
            base_fee_per_gas=int(rng.integers(0, 2**40)),
            state_root=bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
        )
        enc = h.encode()
        assert Header.decode(enc).hash == h.hash
        vectors["headers"].append("0x" + enc.hex())
        n = int(rng.integers(0, 2**63))
        assert from_be64(be64(n)) == n
        vectors["be64"].append(n)
    out = json.dumps(vectors, indent=None)
    if args.out:
        Path(args.out).write_text(out)
        print(f"{args.count} vectors x 3 codecs -> {args.out}")
    else:
        print(out)
    return 0


def cmd_bb_bench(args):
    """Big-block execution benchmark (reference bin/reth-bb): execute one
    synthetic maximum-size block and report Mgas/s — serial vs BAL waves."""
    from .engine.bal import execute_block_bal, record_access_list
    from .evm import BlockExecutor, EvmConfig
    from .evm.executor import InMemoryStateSource
    from .primitives import Account
    from .primitives.keccak import keccak256
    from .primitives.types import Block, Header
    from .testing import Wallet

    n_transfer = args.transfers
    n_store = args.stores
    # PUSH0 CALLDATALOAD PUSH0 SSTORE STOP — a storage write per call
    store_code = bytes.fromhex("5f355f5500")
    wallets = [Wallet(0x10000 + i) for i in range(n_transfer + n_store)]
    accounts = {w.address: Account(balance=10**20) for w in wallets}
    contracts = []
    for i in range(max(1, n_store // 8)):  # 8 callers share a contract
        c = bytes([0x5C]) + i.to_bytes(19, "big")
        accounts[c] = Account(code_hash=keccak256(store_code))
        contracts.append(c)
    src = InMemoryStateSource(accounts, codes={keccak256(store_code): store_code})
    txs = [w.transfer(bytes([0xD0]) + i.to_bytes(19, "big"), 1 + i)
           for i, w in enumerate(wallets[:n_transfer])]
    txs += [w.call(contracts[i % len(contracts)], i.to_bytes(32, "big"))
            for i, w in enumerate(wallets[n_transfer:])]
    header = Header(number=1, gas_limit=2_000_000_000, base_fee_per_gas=7,
                    beneficiary=b"\xcb" * 20)
    block = Block(header, tuple(txs), (), ())
    senders = [w.address for w in wallets]

    cfg = EvmConfig(chain_id=1)
    t0 = time.time()
    out = BlockExecutor(src, cfg).execute(block, senders)
    dt_serial = time.time() - t0
    mgas = out.gas_used / 1e6
    print(f"serial:   {len(txs)} txs, {mgas:.2f} Mgas in {dt_serial:.3f}s "
          f"= {mgas / dt_serial:.2f} Mgas/s")
    bal = record_access_list(src, block, senders, cfg)
    t0 = time.time()
    out2, stats = execute_block_bal(src, block, senders, bal, cfg)
    dt_bal = time.time() - t0
    assert out2.gas_used == out.gas_used
    print(f"bal:      {mgas:.2f} Mgas in {dt_bal:.3f}s = "
          f"{mgas / dt_bal:.2f} Mgas/s  waves={stats['waves']} "
          f"parallel={stats['parallel']} serial={stats['serial']} "
          f"native={stats.get('native', 0)}")
    print(json.dumps({"metric": "execution_mgas_per_sec",
                      "value": round(mgas / dt_serial, 3),
                      "unit": "Mgas/s",
                      "bal_mgas_per_sec": round(mgas / dt_bal, 3)}))
    return 0


def cmd_config(args):
    """Print the effective TOML-style config (reference `reth config`)."""
    from .config import load_config

    cfg = load_config(args.config)
    lines = [
        "[stages.merkle]",
        f"rebuild_threshold = {cfg.stages.merkle.rebuild_threshold}",
        f"incremental_threshold = {cfg.stages.merkle.incremental_threshold}",
        "",
        "[stages.account_hashing]",
        f"clean_threshold = {cfg.stages.account_hashing.clean_threshold}",
        "",
        "[stages.storage_hashing]",
        f"clean_threshold = {cfg.stages.storage_hashing.clean_threshold}",
        "",
        "[stages.execution]",
        f"max_blocks_per_commit = {cfg.stages.execution.max_blocks_per_commit}",
        "",
        "[node]",
        f"persistence_threshold = {cfg.persistence_threshold}",
        f'hasher = "{cfg.hasher}"',
        f"hash_service = {'true' if cfg.hash_service else 'false'}",
        f"mesh_devices = {cfg.mesh_devices}",
        f'warmup = "{cfg.warmup}"',
        f'compile_cache_dir = "{cfg.compile_cache_dir}"',
        f"sparse_workers = {cfg.sparse_workers}",
        f"subtrie_levels = {cfg.subtrie_levels}",
        f"parallel_exec = {'true' if cfg.parallel_exec else 'false'}",
        f"pipeline_depth = {cfg.pipeline_depth}",
        f"continuous_build = {'true' if cfg.continuous_build else 'false'}",
        f"hot_state = {'true' if cfg.hot_state else 'false'}",
        f"trace_blocks = {'true' if cfg.trace_blocks else 'false'}",
        f"health = {'true' if cfg.health else 'false'}",
        f"slo_interval = {cfg.slo_interval}",
        f"slo_window = {cfg.slo_window}",
        f"invalid_cache_size = {cfg.invalid_cache_size}",
        "",
        "[rpc]",
        f"gateway = {'true' if cfg.rpc.gateway else 'false'}",
        f"gateway_cache = {cfg.rpc.gateway_cache}",
        "",
        "[prune]",
    ]
    for seg in ("sender_recovery", "receipts", "transaction_lookup",
                "account_history", "storage_history"):
        mode = getattr(cfg.prune, seg, None)
        if mode is not None and (mode.distance is not None or mode.before is not None):
            which = (f"distance = {mode.distance}" if mode.distance is not None
                     else f"before = {mode.before}")
            lines.append(f"{seg} = {{ {which} }}")
    print("\n".join(lines))
    return 0


def cmd_db_verify_trie(args):
    """Recompute the state root from hashed tables; compare with the tip
    header (reference `reth db repair-trie` / trie verify iterator)."""
    from .storage import ProviderFactory
    from .trie.incremental import verify_state_root

    factory = ProviderFactory(_open_db(args))
    committer = _make_committer(args)
    with factory.provider() as p:
        # the hashed/trie tables are current as of the MERKLE checkpoint,
        # not the canonical tip (a lagging pipeline is not corruption)
        tip = p.stage_checkpoint("MerkleExecute")
        header = p.header_by_number(tip)
        if header is None:
            print("empty database (no merkle checkpoint)", file=sys.stderr)
            return 1
        # READ-ONLY full rebuild + structural cross-checks
        root, problems = verify_state_root(p, committer)
        for msg in problems:
            print(f"PROBLEM: {msg}", file=sys.stderr)
        if root == header.state_root and not problems:
            print(f"trie OK at block {tip}: 0x{root.hex()}")
            return 0
        if root != header.state_root:
            print(f"TRIE MISMATCH at block {tip}: computed 0x{root.hex()} "
                  f"header 0x{header.state_root.hex()}", file=sys.stderr)
        return 1


def cmd_db_stats(args):
    from .storage.tables import Tables

    db = _open_db(args)
    tx = db.tx()
    print(f"{'table':<28}{'entries':>12}")
    from .storage.tables import TableDef

    names = (sorted(db._tables) if hasattr(db, "_tables")
             else sorted(v.name for v in vars(Tables).values()
                         if isinstance(v, TableDef)))
    for name in names:
        print(f"{name:<28}{tx.entry_count(name):>12}")
    return 0


def cmd_stage_run(args):
    from .stages import Pipeline, default_stages
    from .storage import ProviderFactory

    factory = ProviderFactory(_open_db(args))
    committer = _make_committer(args)
    stages = [s for s in default_stages(committer=committer)
              if args.stage in ("all", s.id)]
    if not stages:
        print(f"unknown stage {args.stage}", file=sys.stderr)
        return 1
    with factory.provider() as p:
        target = args.to if args.to is not None else p.last_block_number()
    t0 = time.time()
    Pipeline(factory, stages).run(target)
    factory.db.flush()
    print(f"stage(s) {[s.id for s in stages]} ran to {target} in {time.time()-t0:.2f}s")
    return 0


def cmd_dump_genesis(args):
    """Print the built-in dev genesis JSON (reference `reth dump-genesis`)."""
    print(json.dumps(_dev_genesis_spec(), indent=2))
    return 0


def cmd_prune(args):
    """Run the pruner once to the configured targets (reference `reth prune`)."""
    from .config import load_config
    from .prune import Pruner
    from .storage import ProviderFactory

    cfg = load_config(args.config)
    factory = ProviderFactory(_open_db(args))
    pruner = Pruner(factory, cfg.prune)
    with factory.provider() as p:
        tip = p.last_block_number()
    out = pruner.run(tip)
    factory.db.flush()
    for prog in out:
        print(f"{prog.segment:<24}{prog.pruned:>10} entries pruned"
              + ("" if prog.done else " (more remain)"))
    return 0


def cmd_re_execute(args):
    """Re-execute a block range against historical state and compare
    receipts/gas with what is stored (reference `reth re-execute`)."""
    from .consensus import EthBeaconConsensus
    from .evm import BlockExecutor, EvmConfig
    from .evm.executor import ProviderStateSource
    from .storage import ProviderFactory
    from .storage.historical import HistoricalStateProvider

    factory = ProviderFactory(_open_db(args))
    mismatches = 0
    with factory.provider() as p:
        tip = p.last_block_number()
        first = max(args.from_block if args.from_block is not None else 1, 1)
        last = min(args.to_block if args.to_block is not None else tip, tip)
        if last < first:
            print(f"nothing to re-execute (range [{first}, {last}], tip {tip})")
            return 0
        for n in range(first, last + 1):
            block = p.block_by_number(n)
            parent_state = HistoricalStateProvider(p, n - 1)
            executor = BlockExecutor(ProviderStateSource(parent_state),
                                     EvmConfig())
            out = executor.execute(block)
            if out.gas_used != block.header.gas_used:
                mismatches += 1
                print(f"block {n}: gas {out.gas_used} != header "
                      f"{block.header.gas_used}", file=sys.stderr)
            idx = p.block_body_indices(n)
            for i, r in enumerate(out.receipts):
                stored = p.receipt(idx.first_tx_num + i)
                if stored is not None and (
                        stored.success != r.success
                        or stored.cumulative_gas_used != r.cumulative_gas_used):
                    mismatches += 1
                    print(f"block {n} tx {i}: receipt mismatch", file=sys.stderr)
    span = last - first + 1
    print(f"re-executed {span} blocks: "
          + ("all match" if not mismatches else f"{mismatches} MISMATCHES"))
    return 1 if mismatches else 0


def cmd_p2p(args):
    """Fetch a header/body from a peer over RLPx (reference `reth p2p`)."""
    from .net.p2p import PeerConnection, random_node_key
    from .net.server import parse_enode
    from .net.wire import Status

    pub, host, port = parse_enode(args.enode)
    status = Status(network_id=args.chain_id)
    if args.genesis_hash:
        status.genesis = bytes.fromhex(args.genesis_hash.removeprefix("0x"))
        status.head = status.genesis
    peer = PeerConnection.connect(host, port, status, pub,
                                  node_priv=random_node_key())
    try:
        if args.what == "header":
            start = (bytes.fromhex(args.id.removeprefix("0x"))
                     if args.id.startswith("0x") else int(args.id))
            headers = peer.get_headers(start, 1)
            if not headers:
                print("no header returned", file=sys.stderr)
                return 1
            h = headers[0]
            print(f"number={h.number} hash=0x{h.hash.hex()} "
                  f"state_root=0x{h.state_root.hex()} gas_used={h.gas_used}")
        else:  # body
            bodies = peer.get_bodies([bytes.fromhex(args.id.removeprefix("0x"))])
            if not bodies:
                print("no body returned", file=sys.stderr)
                return 1
            b = bodies[0]
            print(f"transactions={len(b.transactions)} "
                  f"withdrawals={len(b.withdrawals or ())}")
        return 0
    finally:
        peer.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="reth-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    def add_hasher(p):
        p.add_argument("--hasher", choices=["device", "cpu", "auto"],
                       default="device",
                       help="keccak backend: device (TPU/XLA, the "
                            "--state-root.backend analogue), cpu (numpy), "
                            "or auto (device behind the health-probe + "
                            "circuit-breaker supervisor; falls over to cpu "
                            "on wedged dispatches — see RETH_TPU_FAULT_* "
                            "env knobs for drill/testing)")
        p.add_argument("--hash-service", action="store_true", default=None,
                       help="multiplex every keccak client over ONE shared "
                            "background hash service (ops/hash_service.py): "
                            "priority lanes (live > payload > rebuild > "
                            "proof), continuous batching with a coalescing "
                            "window, bounded per-lane backpressure, and an "
                            "exclusive lease for rebuild streaming; "
                            "composes with --hasher auto (breaker trips / "
                            "CPU failover apply to the shared service) — "
                            "see RETH_TPU_FAULT_SERVICE_* drill knobs")
        p.add_argument("--mesh", type=int, default=None,
                       help="shard the hashing data plane over a device "
                            "MESH of this many devices (parallel/mesh.py): "
                            "fused per-depth level windows batch-shard "
                            "across the mesh (digest arena replicated, XLA "
                            "inserts the all-gather) while scalar requests "
                            "stay on one device (partition-rule table); "
                            "with --hash-service the rebuild takes a "
                            "SUB-MESH lease (k of n devices, live lanes "
                            "keep the rest; RETH_TPU_MESH_REBUILD_DEVICES) "
                            "and per-device circuit breakers shrink the "
                            "mesh around a wedged device before any CPU "
                            "failover (RETH_TPU_FAULT_DEVICE_WEDGE drills "
                            "it). Default: RETH_TPU_MESH or off; also "
                            "[node] mesh_devices in reth.toml")
        p.add_argument("--warmup", choices=["off", "background", "block"],
                       default=None,
                       help="device warm-up manager (ops/warmup.py): AOT-"
                            "compile the declared kernel shape menu one "
                            "shape at a time under per-shape watchdog "
                            "budgets with retry + backoff, sequenced "
                            "behind the supervisor's health probe. "
                            "'background' serves degraded on the CPU twin "
                            "meanwhile, promoting each shape as it warms; "
                            "'block' finishes warm-up before serving. "
                            "Default: RETH_TPU_WARMUP or off. See "
                            "RETH_TPU_FAULT_COMPILE_WEDGE for the drill, "
                            "RETH_TPU_WARMUP_{BUDGET,ATTEMPTS,BACKOFF} "
                            "for the knobs; also [node] warmup in "
                            "reth.toml")
        p.add_argument("--compile-cache-dir", dest="compile_cache_dir",
                       default=None,
                       help="persistent XLA compilation cache directory "
                            "for --warmup (versioned by kernel-source "
                            "digest; corrupt entries are quarantined and "
                            "rebuilt; only enabled after a subprocess "
                            "probe proves the cache loads). Default: "
                            "<datadir>/compile-cache when --warmup is on; "
                            "also RETH_TPU_COMPILE_CACHE_DIR or [node] "
                            "compile_cache_dir in reth.toml")

    def add_db_arg(p):
        # paged (the COW B+tree / MDBX analogue) is the DEFAULT everywhere
        # a datadir exists — memdb is a test fixture (reference: libmdbx is
        # the only production backend)
        p.add_argument("--storage.v2", dest="storage_v2",
                       action="store_true", default=None,
                       help="split layout: history/lookup tables on a "
                            "dedicated second store (reference "
                            "StorageSettings storage-v2); persisted per "
                            "datadir on first init")
        p.add_argument("--db", dest="db_backend",
                       choices=["memdb", "native", "paged"], default=None,
                       help="storage backend (paged = mmap COW B+tree "
                            "engine, the default; native = C++ WAL engine; "
                            "memdb = in-process test store). Unset: an "
                            "initialised datadir keeps its engine")

    p = sub.add_parser("init", help="initialise the database from a genesis file")
    p.add_argument("--datadir", required=True)
    p.add_argument("--genesis", required=True)
    add_hasher(p)
    add_db_arg(p)
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("import", help="import an RLP chain file and sync")
    p.add_argument("--datadir", required=True)
    p.add_argument("--genesis", required=True)
    p.add_argument("file")
    add_hasher(p)
    add_db_arg(p)
    p.set_defaults(fn=cmd_import)

    p = sub.add_parser("import-era", help="import era1 history archives")
    p.add_argument("--datadir", required=True)
    p.add_argument("--genesis", required=True)
    p.add_argument("file", nargs="?", default=None,
                   help="single era1 file (or use --source)")
    p.add_argument("--source", default=None,
                   help="directory of era1 archives + index.txt checksums")
    add_hasher(p)
    add_db_arg(p)
    p.set_defaults(fn=cmd_import_era)

    p = sub.add_parser("export-era", help="export canonical blocks to era1")
    p.add_argument("--datadir", required=True)
    p.add_argument("--first", type=int, required=True)
    p.add_argument("--last", type=int, required=True)
    p.add_argument("file")
    add_db_arg(p)
    p.set_defaults(fn=cmd_export_era)

    p = sub.add_parser("node", help="run the node (RPC + engine API)")
    p.add_argument("--role", choices=["full", "replica", "standby"],
                   default="full",
                   help="full: the usual node. replica: a stateless "
                        "witness-fed read replica (no database) — needs "
                        "--feed HOST:PORT; serves eth_call/eth_estimateGas/"
                        "eth_getProof/eth_getLogs/eth_getBlockBy* from "
                        "witness-backed state (fleet/replica.py). standby: "
                        "a WAL-shipped hot standby — needs --feed and "
                        "--datadir; replays the leader's durable stream and "
                        "promotes itself on heartbeat loss or fleet_promote "
                        "(fleet/standby.py)")
    p.add_argument("--feed", default=None,
                   help="(replica/standby role) HOST:PORT of the full "
                        "node's witness feed")
    p.add_argument("--replica-retention", dest="replica_retention",
                   type=int, default=128,
                   help="(replica role) validated blocks retained")
    p.add_argument("--register", default=None,
                   help="(replica role) full-node RPC URL to self-register "
                        "with (fleet_register)")
    p.add_argument("--takeover-feed-port", dest="takeover_feed_port",
                   type=int, default=0,
                   help="(standby role) feed port the promoted node binds "
                        "(0 = ephemeral)")
    p.add_argument("--no-auto-promote", dest="no_auto_promote",
                   action="store_true",
                   help="(standby role) only promote on explicit "
                        "fleet_promote (no heartbeat-loss trigger)")
    p.add_argument("--heartbeat-timeout", dest="heartbeat_timeout",
                   type=float, default=2.0,
                   help="(standby role) seconds without a leader heartbeat "
                        "before auto-promotion fires")
    p.add_argument("--ha-peer-feed", dest="ha_peer_feeds",
                   action="append", default=None,
                   help="(full role) HOST:PORT of a peer feed to probe for "
                        "a higher leader epoch at startup — if one is "
                        "serving, this node starts fenced (repeatable)")
    p.add_argument("--fleet", dest="fleet", action="store_true",
                   default=None,
                   help="read-replica fleet mode: start the witness feed "
                        "server, route gateway reads over a consistent-"
                        "hash replica ring with health-driven draining, "
                        "and expose the fleet_* admin methods (implies "
                        "--rpc-gateway; fleet/)")
    p.add_argument("--feed-port", dest="feed_port", type=int, default=0,
                   help="witness feed TCP port (0 = ephemeral)")
    p.add_argument("--fleet-max-lag", dest="fleet_max_lag", type=int,
                   default=None,
                   help="heads a replica may trail before the ring sheds "
                        "it (default 4)")
    p.add_argument("--datadir", default=None)
    p.add_argument("--genesis", default=None)
    p.add_argument("--dev", action="store_true")
    p.add_argument("--block-time", type=int, default=2)
    p.add_argument("--http-port", type=int, default=8545)
    p.add_argument("--authrpc-port", type=int, default=8551)
    p.add_argument("--ws-port", type=int, default=None,
                   help="WebSocket RPC port (omit to disable)")
    p.add_argument("--enable-admin", action="store_true",
                   help="expose the admin_ namespace (node control)")
    p.add_argument("--ipc-path", default=None,
                   help="Unix-socket RPC path (omit to disable)")
    p.add_argument("--authrpc-jwtsecret", default=None,
                   help="path to the 32-byte hex JWT secret for the engine "
                        "port (default: <datadir>/jwt.hex, created if absent)")
    p.add_argument("--port", type=int, default=30303, help="RLPx TCP port")
    p.add_argument("--addr", default="127.0.0.1",
                   help="P2P bind/advertise address (0.0.0.0 for all)")
    p.add_argument("--disable-p2p", action="store_true")
    p.add_argument("--no-discovery", action="store_true")
    p.add_argument("--bootnodes", default="", help="comma-separated enode urls")
    p.add_argument("--bootnodes-v5", default="", dest="bootnodes_v5",
                   help="comma-separated enr:... records (discv5)")
    p.add_argument("--nat", default="any",
                   help="NAT resolution: any | none | extip:<ip> | upnp | natpmp")
    add_db_arg(p)
    p.add_argument("--ethstats", default=None,
                   help="report to an ethstats server (node:secret@host:port)")
    add_hasher(p)
    p.add_argument("--sparse-workers", dest="sparse_workers", type=int,
                   default=None,
                   help="parallel sparse commit: worker count for the "
                        "live-tip finish path's RLP encode pool AND the "
                        "multiproof proof-worker pool (trie/sparse.py + "
                        "trie/proof.py). Default: RETH_TPU_SPARSE_WORKERS "
                        "or a cpu-derived value; 1 disables the pools "
                        "(the cross-trie packed hash dispatch stays on). "
                        "Also settable as [node] sparse_workers in "
                        "reth.toml")
    p.add_argument("--subtrie-levels", dest="subtrie_levels", type=int,
                   default=None,
                   help="whole-subtrie fused tree-hash kernels "
                        "(ops/fused_commit.py SubtrieFusedEngine): commit "
                        "k packed trie levels per device dispatch — the "
                        "depth loop runs INSIDE the jitted program with "
                        "the resident digest buffer as the carry, so "
                        "dispatches per block drop from O(depth) to "
                        "O(depth/k). Applies to the turbo rebuild, the "
                        "parallel sparse finish, and hash-service window "
                        "requests; un-warm k-shapes route to the "
                        "per-level path, and failures replay per-level "
                        "then on the CPU twin, roots bit-identical "
                        "(RETH_TPU_FAULT_SUBTRIE_{WEDGE,ABORT} drills). "
                        "Default: RETH_TPU_SUBTRIE_LEVELS or off (0/1 = "
                        "per-level). Also [node] subtrie_levels in "
                        "reth.toml")
    p.add_argument("--parallel-exec", dest="parallel_exec",
                   action="store_true", default=False,
                   help="optimistic parallel EVM execution on the no-BAL "
                        "newPayload path (engine/optimistic.py): "
                        "Block-STM-style speculation through the native "
                        "wave core with read/write-set validation, "
                        "deterministic serial re-execution of invalidated "
                        "ranks, and async storage prefetch; receipts stay "
                        "bit-identical to the serial executor, any "
                        "scheduler error falls back to it. Speculation "
                        "width: RETH_TPU_EXEC_WORKERS (default "
                        "cpu-derived). Also settable as [node] "
                        "parallel_exec in reth.toml")
    p.add_argument("--pipeline-depth", dest="pipeline_depth", type=int,
                   default=None, metavar="N",
                   help="cross-block import pipeline depth "
                        "(engine/block_pipeline.py): 2 = start optimistic "
                        "execution of payload N+1 over block N's frozen "
                        "commit window while N's fused state-root "
                        "dispatches run, with speculative prewarm + "
                        "multiproof prefetch on a double-buffered hash "
                        "sub-mesh lease; adoption re-runs every consensus "
                        "and root check, so results stay bit-identical to "
                        "serial imports, and fcU reorgs / invalid parents "
                        "abort the speculation through the cooperative "
                        "cancellation ladder. 1 = strictly serial "
                        "(default). Env fallback: RETH_TPU_PIPELINE_DEPTH. "
                        "Also settable as [node] pipeline_depth in "
                        "reth.toml")
    p.add_argument("--continuous-build", dest="continuous_build",
                   action="store_true", default=False,
                   help="standing block producer (payload/producer.py): "
                        "stream the pool's best transactions into a hot "
                        "candidate payload refreshed incrementally on pool "
                        "events and head changes — only ranks a pool delta "
                        "or new head invalidates re-execute, and with "
                        "--pipeline-depth 2 the N+1 candidate builds over "
                        "block N's commit window while N's root dispatches "
                        "run. getPayload / dev mining seal the candidate "
                        "(inclusion set bit-identical to the one-shot "
                        "serial greedy builder) instead of building from "
                        "scratch. producer_status reports the candidate. "
                        "Also settable as [node] continuous_build in "
                        "reth.toml")
    p.add_argument("--hot-state", dest="hot_state", action="store_true",
                   default=False,
                   help="hot-state plane (trie/hot_cache.py): cross-block "
                        "trie-node cache shared across forks — sparse "
                        "root tasks reveal from it before fetching "
                        "proofs, every entry is keccak-validated at "
                        "lookup — plus a device-resident digest arena "
                        "(ops/fused_commit.py) that keeps subtree digest "
                        "rows on the accelerator across blocks so sparse "
                        "finishes upload only dirty rows; roots stay "
                        "bit-identical, any arena fault evicts and "
                        "reruns the full-upload path. Invalidated on "
                        "deep reorgs/storms. Env fallback: "
                        "RETH_TPU_HOT_STATE. Also settable as [node] "
                        "hot_state in reth.toml")
    p.add_argument("--rpc-gateway", dest="rpc_gateway", action="store_true",
                   default=False,
                   help="route every RPC transport (HTTP/WS/IPC + the "
                        "engine port) through the serving gateway "
                        "(rpc/gateway.py): per-class admission control "
                        "with priority engine > eth-read > tx-submit > "
                        "debug and bounded queues (-32005 shedding when "
                        "full), in-flight coalescing of identical reads, "
                        "and a head-invalidated response cache. Also "
                        "settable as [rpc] gateway in reth.toml — see "
                        "RETH_TPU_FAULT_GATEWAY_* drill knobs")
    p.add_argument("--trace-blocks", dest="trace_blocks", action="store_true",
                   default=None,
                   help="block-lifecycle tracing (tracing.py): a trace "
                        "context (trace_id = block hash) propagated across "
                        "every queue/pool handoff yields a per-block span "
                        "timeline — gateway admission, prewarm, execution, "
                        "sparse commit, hash-service queue-wait vs "
                        "dispatch — exported as Chrome-trace JSON under "
                        "<datadir>/traces (open in Perfetto), plus the "
                        "debug_blockTimeline / debug_flightRecorder RPCs "
                        "and a per-block wall-budget events line. Also "
                        "RETH_TPU_TRACE=1 or [node] trace_blocks in "
                        "reth.toml")
    p.add_argument("--trace-file", dest="trace_file", default=None,
                   help="Chrome-trace output path override for "
                        "--trace-blocks (default <datadir>/traces/"
                        "blocks.trace.json)")
    p.add_argument("--health", dest="health", action="store_true",
                   default=False,
                   help="node health & SLO engine (health.py): sample "
                        "every metric into bounded ring buffers and "
                        "evaluate the burn-rate SLO rule table (block "
                        "import wall, hash-service per-lane p99 wait, "
                        "gateway shed/cache rates, sparse finish wall, "
                        "exec conflict/fallback rate, warm-up failures, "
                        "breaker state); breaches flip the component to "
                        "degraded/failing, dump the flight recorder, "
                        "and surface at GET /health and the "
                        "debug_healthCheck / debug_sloStatus / "
                        "debug_metricsHistory RPCs. Also [node] health "
                        "in reth.toml; RETH_TPU_FAULT_SLO_BREACH drills "
                        "a forced breach")
    p.add_argument("--slo-interval", dest="slo_interval", type=float,
                   default=1.0,
                   help="seconds between health sampler/evaluator "
                        "passes (default 1.0; also RETH_TPU_SLO_INTERVAL "
                        "/ [node] slo_interval)")
    p.add_argument("--slo-window", dest="slo_window", type=int,
                   default=300,
                   help="retained ring-buffer samples per metric series "
                        "(default 300 = 5 min at 1 Hz; also "
                        "RETH_TPU_SLO_WINDOW / [node] slo_window)")
    p.add_argument("--wal", dest="wal", action="store_true", default=None,
                   help="write-ahead log for the memdb store (default ON "
                        "with a datadir): every commit fsync-appends its "
                        "table delta to <datadir>/wal/<gen>.wal before "
                        "publish, checkpoints (image + fsync'd manifest) "
                        "truncate the log — a kill -9 loses at most "
                        "persistence_threshold blocks. Also [node] wal / "
                        "RETH_TPU_WAL; the native/paged engines carry "
                        "their own durability")
    p.add_argument("--no-wal", dest="wal", action="store_false",
                   help="disable the memdb write-ahead log (durability "
                        "falls back to image flushes at each persistence "
                        "advance)")
    p.add_argument("--wal-checkpoint-blocks", dest="wal_checkpoint_blocks",
                   type=int, default=8,
                   help="persisted blocks between WAL checkpoints "
                        "(default 8; also [node] wal_checkpoint_blocks)")
    p.add_argument("--no-recovery-verify", dest="recovery_verify_root",
                   action="store_false", default=True,
                   help="skip the startup recovery's full state-root "
                        "recomputation through the committer (large "
                        "datadirs trade the proof for boot time; also "
                        "RETH_TPU_RECOVERY_VERIFY=0)")
    p.add_argument("--invalid-cache-size", dest="invalid_cache_size",
                   type=int, default=None,
                   help="bound of the engine tree's invalid-header LRU "
                        "(default 512): an invalid-payload flood plateaus "
                        "here instead of leaking memory. Also "
                        "RETH_TPU_INVALID_CACHE / [node] invalid_cache_size")
    p.set_defaults(fn=cmd_node)

    p = sub.add_parser("dump-genesis", help="print the dev genesis JSON")
    p.set_defaults(fn=cmd_dump_genesis)

    p = sub.add_parser("prune", help="prune history per the config's targets")
    p.add_argument("--datadir", required=True)
    p.add_argument("--config", default=None, help="reth.toml path")
    add_db_arg(p)
    p.set_defaults(fn=cmd_prune)

    p = sub.add_parser("re-execute",
                       help="re-run blocks against historical state and "
                            "compare receipts/gas")
    p.add_argument("--datadir", required=True)
    p.add_argument("--from", dest="from_block", type=int, default=None)
    p.add_argument("--to", dest="to_block", type=int, default=None)
    add_db_arg(p)
    p.set_defaults(fn=cmd_re_execute)

    p = sub.add_parser("p2p", help="fetch a header/body from a peer")
    p.add_argument("what", choices=["header", "body"])
    p.add_argument("id", help="block number, or 0x hash")
    p.add_argument("--enode", required=True)
    p.add_argument("--chain-id", dest="chain_id", type=int, default=1)
    p.add_argument("--genesis-hash", dest="genesis_hash", default=None)
    p.set_defaults(fn=cmd_p2p)

    p = sub.add_parser("db", help="database tools")
    dbsub = p.add_subparsers(dest="db_command", required=True)

    def add_db_args(sp):
        sp.add_argument("--datadir", required=True)
        add_db_arg(sp)

    ps = dbsub.add_parser("stats")
    add_db_args(ps)
    ps.set_defaults(fn=cmd_db_stats)
    pv = dbsub.add_parser("verify-trie")
    add_db_args(pv)
    add_hasher(pv)
    pv.set_defaults(fn=cmd_db_verify_trie)
    pg = dbsub.add_parser("get", help="print one table entry")
    add_db_args(pg)
    pg.add_argument("table")
    pg.add_argument("key")
    pg.add_argument("--subkey", default=None)
    pg.set_defaults(fn=cmd_db_get)
    pl = dbsub.add_parser("list", help="list table entries")
    add_db_args(pl)
    pl.add_argument("table")
    pl.add_argument("--start", default=None)
    pl.add_argument("--limit", type=int, default=20)
    pl.add_argument("--value-bytes", dest="value_bytes", type=int, default=32)
    pl.set_defaults(fn=cmd_db_list)
    pd = dbsub.add_parser("diff", help="compare two databases")
    add_db_args(pd)
    pd.add_argument("other", help="second datadir")
    pd.add_argument("--table", default=None, help="comma-separated subset")
    pd.add_argument("--limit", type=int, default=10)
    pd.set_defaults(fn=cmd_db_diff)
    pr2 = dbsub.add_parser("repair-trie", help="rebuild trie tables from hashed state")
    add_db_args(pr2)
    add_hasher(pr2)
    pr2.set_defaults(fn=cmd_db_repair_trie)

    p = sub.add_parser("init-state",
                       help="initialise from a state dump at a block")
    p.add_argument("state", help="state dump JSON")
    p.add_argument("--datadir", required=True)
    add_db_arg(p)
    add_hasher(p)
    p.set_defaults(fn=cmd_init_state)

    p = sub.add_parser("test-vectors", help="generate codec test vectors")
    p.add_argument("--count", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None)
    p.set_defaults(fn=cmd_test_vectors)

    p = sub.add_parser("bb-bench",
                       help="big-block execution benchmark (reth-bb analogue)")
    p.add_argument("--transfers", type=int, default=400)
    p.add_argument("--stores", type=int, default=100)
    p.set_defaults(fn=cmd_bb_bench)

    p = sub.add_parser("config", help="print the effective config")
    p.add_argument("--config", default=None)
    p.set_defaults(fn=cmd_config)

    p = sub.add_parser("stage", help="run a single stage")
    stsub = p.add_subparsers(dest="stage_command", required=True)
    pr = stsub.add_parser("run")
    pr.add_argument("--datadir", required=True)
    pr.add_argument("--stage", default="all")
    pr.add_argument("--to", type=int, default=None)
    add_hasher(pr)
    add_db_arg(pr)
    pr.set_defaults(fn=cmd_stage_run)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
