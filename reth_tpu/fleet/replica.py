"""The stateless read-replica role: no database, witness-fed serving.

A replica subscribes to a full node's witness feed (:mod:`.feed`),
validates every block through ``engine/stateless.py``'s
:class:`~reth_tpu.engine.stateless.StatelessChain` (preserved sparse
trie carried block-to-block, roots bit-identical to the full node by
construction), and serves the read RPC surface from witness-backed
state:

- ``eth_call`` / ``eth_estimateGas`` — the interpreter runs against a
  :class:`ReplicaStateSource` whose every read comes from the preserved
  sparse trie's revealed nodes and the accumulated witness bytecodes.
- ``eth_getProof`` — EIP-1186 proofs straight off the sparse trie's
  spines (the trie IS the proof material).
- ``eth_getLogs`` / ``eth_getBlockByNumber`` / ``eth_getBlockByHash`` —
  from the retained window of validated blocks + their re-executed
  receipts (stateless re-execution yields the same receipts the full
  node committed; the root check proves it).

A read that needs state the witness never revealed raises
``BlindedNodeError`` inside the handler and maps to a clean JSON-RPC
``-32001`` resource-not-found — the fleet gateway fails the request
over to the next ring position or the local full node, so the client
never sees it. A block outside the retained window answers ``-32001``
the same way. The replica deliberately errs instead of approximating:
every answer it does give is bit-identical to the full node's.

The pending view (:class:`ReplicaPoolView`): the replica also
subscribes to the leader pool's ``pt_*`` record family (feed.py) —
snapshot + incremental admissions/replacements/drops keyed by a
monotonic pool ``seq`` — and serves ``eth_getTransactionByHash`` for
unmined txs, pending-tag ``eth_getTransactionCount``, and the
``txpool_*`` namespace from it instead of answering ``-32001``. The
same doctrine applies: a seq gap (records shed upstream under
backpressure, or lost across a reconnect) makes the view unsynced and
the replica re-subscribes for a fresh snapshot rather than serving a
silently-divergent pending set; while unsynced, pool reads answer
``-32001`` and fail over.

Fault injection (:class:`ReplicaFaultInjector`):
``RETH_TPU_FAULT_REPLICA_WEDGE=1`` stops feed processing (the replica
keeps serving its stale head — the lag the gateway ring must shed);
``RETH_TPU_FAULT_REPLICA_LAG=<seconds>`` delays each block record (a
slow replica that falls progressively behind).
"""

from __future__ import annotations

import os
import threading
import time

from .. import tracing
from ..engine.stateless import StatelessChain, StatelessValidationError, \
    _decode_account_leaf
from ..evm import EvmConfig
from ..evm.executor import intrinsic_gas
from ..evm.interpreter import BlockEnv, Interpreter, Revert, TxEnv
from ..evm.state import EvmState, StateSource
from ..primitives.keccak import keccak256, keccak256_batch_np
from ..primitives.rlp import decode_int, rlp_decode
from ..primitives.types import (
    Block,
    EMPTY_ROOT_HASH,
    Header,
    KECCAK_EMPTY,
    Transaction,
)
from ..rpc.convert import block_to_rpc, data, parse_data, parse_qty, qty, \
    tx_to_rpc
from ..rpc.server import RpcError, RpcServer
from ..trie.sparse import BlindedNodeError
from .feed import WitnessFeedClient

# JSON-RPC resource-not-found: the replica's "I cannot answer this
# bit-identically" code — the fleet router treats it as a failover
# signal, never a client-visible failure
NOT_IN_WITNESS = -32001

DEFAULT_RETENTION = 128


class ReplicaFaultInjector:
    """Feed-processing fault policies, in the style of the gateway's
    injector: ``wedge`` drops every block record from the
    ``wedge_after``-th onward (serving continues on the stale head —
    ``RETH_TPU_FAULT_REPLICA_WEDGE=N`` wedges a replica MID-stream, N=1
    from birth), ``lag_s`` sleeps before each one."""

    def __init__(self, wedge: bool = False, lag_s: float = 0.0,
                 wedge_after: int = 1):
        self.wedge = wedge
        self.wedge_after = max(1, wedge_after)
        self.lag_s = lag_s
        self.seen = 0
        self.dropped = 0
        self.lagged = 0

    @classmethod
    def from_env(cls, env=None) -> "ReplicaFaultInjector | None":
        env = os.environ if env is None else env
        wedge_raw = env.get("RETH_TPU_FAULT_REPLICA_WEDGE", "")
        wedge = wedge_raw not in ("", "0")
        wedge_after = int(wedge_raw) if wedge_raw.isdigit() and wedge else 1
        lag = float(env.get("RETH_TPU_FAULT_REPLICA_LAG", "0") or 0)
        if not (wedge or lag):
            return None
        return cls(wedge=wedge, lag_s=lag, wedge_after=wedge_after)

    def active(self) -> bool:
        return bool(self.wedge or self.lag_s)

    @property
    def wedging(self) -> bool:
        """True while the wedge is live (the flag a probe reports) —
        deferred wedges stay healthy until their Nth block record."""
        return self.wedge and self.seen + 1 >= self.wedge_after

    def on_block(self, number: int) -> bool:
        """Called per block record; True = drop it (wedge drill)."""
        if self.lag_s:
            self.lagged += 1
            tracing.fault_event("RETH_TPU_FAULT_REPLICA_LAG",
                                target="fleet::replica", number=number,
                                lag_s=self.lag_s)
            time.sleep(self.lag_s)
        self.seen += 1
        if self.wedge and self.seen >= self.wedge_after:
            self.dropped += 1
            tracing.fault_event("RETH_TPU_FAULT_REPLICA_WEDGE",
                                target="fleet::replica", number=number)
            return True
        return False


class ReplicaStateSource(StateSource):
    """EVM state source over the preserved sparse trie + witness
    bytecodes: every read comes from revealed nodes, an unrevealed path
    raises ``BlindedNodeError`` (mapped to ``-32001`` by the API)."""

    def __init__(self, trie, codes: dict[bytes, bytes]):
        self.trie = trie
        self.codes = codes

    def account(self, address: bytes):
        leaf = self.trie.account_trie.get(keccak256(address))
        return _decode_account_leaf(leaf) if leaf is not None else None

    def storage(self, address: bytes, slot: bytes) -> int:
        acct = self.account(address)
        if acct is None:
            return 0
        ha = keccak256(address)
        stg = self.trie.storage_tries.get(ha)
        if stg is None:
            if acct.storage_root == EMPTY_ROOT_HASH:
                return 0
            raise BlindedNodeError(
                b"", f"storage trie of {address.hex()} not in witness")
        leaf = stg.get(keccak256(slot))
        return decode_int(rlp_decode(leaf)) if leaf is not None else 0

    def bytecode(self, code_hash: bytes) -> bytes:
        if code_hash == KECCAK_EMPTY:
            return b""
        code = self.codes.get(code_hash)
        if code is None:
            raise BlindedNodeError(
                b"", f"bytecode {code_hash.hex()} not in witness")
        return code


class ReplicaPoolView:
    """The fleet-propagated pending-tx set, rebuilt from ``pt_*``
    records: hash → ``(tx, sender)`` plus a per-sender nonce map, bounded
    by ``limit`` (oldest admission evicted first — same pressure
    direction as the leader pool's own eviction). ``seq`` tracks the
    leader pool's event sequence; -1 means "no snapshot yet" and every
    incremental record is ignored until one lands (the snapshot
    supersedes whatever those records would have said). Mutated only
    under the owning replica's lock."""

    def __init__(self, limit: int = 8192):
        self.limit = limit
        self.seq = -1
        self.base_fee = 0
        self.blob_base_fee = 0
        # hash -> (tx, sender); insertion-ordered = admission-ordered
        self.txs: dict[bytes, tuple[Transaction, bytes]] = {}
        self.by_sender: dict[bytes, dict[int, bytes]] = {}
        self.records = 0
        self.snapshots = 0
        self.evicted = 0
        self.decode_errors = 0

    def _insert(self, tx: Transaction, sender: bytes) -> None:
        nonces = self.by_sender.setdefault(sender, {})
        old = nonces.get(tx.nonce)
        if old is not None and old != tx.hash:
            self.txs.pop(old, None)
        self.txs[tx.hash] = (tx, sender)
        nonces[tx.nonce] = tx.hash
        while len(self.txs) > self.limit:
            h, (otx, osender) = next(iter(self.txs.items()))
            self._remove(h, otx, osender)
            self.evicted += 1

    def _remove(self, h: bytes, tx=None, sender=None) -> None:
        entry = self.txs.pop(h, None)
        if entry is not None:
            tx, sender = entry
        if tx is None or sender is None:
            return
        nonces = self.by_sender.get(sender)
        if nonces is not None and nonces.get(tx.nonce) == h:
            del nonces[tx.nonce]
            if not nonces:
                del self.by_sender[sender]

    def apply(self, record: dict) -> str:
        """Apply one ``pt_*`` record; returns ``"ok"`` or ``"gap"``.
        After a gap the view resets to unsynced (seq -1) so the caller's
        re-subscribe races no further gap reports."""
        kind = record.get("type")
        seq = int(record.get("seq") or 0)
        if kind == "pt_snapshot":
            self.txs.clear()
            self.by_sender.clear()
            self.base_fee = record.get("base_fee") or 0
            self.blob_base_fee = record.get("blob_base_fee") or 0
            for raw, sender in record.get("txs") or ():
                try:
                    self._insert(Transaction.decode(raw), sender)
                except Exception:  # noqa: BLE001 - skip the bad entry
                    self.decode_errors += 1
            self.seq = seq
            self.snapshots += 1
            return "ok"
        if self.seq < 0 or seq <= self.seq:
            # not yet snapshotted, or a record the snapshot already
            # folded in (the subscribe/broadcast enqueue race)
            return "ok"
        if seq != self.seq + 1:
            self.seq = -1
            return "gap"
        self.records += 1
        self.seq = seq
        if kind in ("pt_add", "pt_replace"):
            try:
                tx = Transaction.decode(record["tx"])
            except Exception:  # noqa: BLE001
                self.decode_errors += 1
                return "ok"
            if kind == "pt_replace":
                old = record.get("old_hash")
                if old:
                    self._remove(old)
            self._insert(tx, record.get("sender"))
        elif kind == "pt_drop":
            self._remove(record.get("hash"))
        elif kind == "pt_canon":
            self.base_fee = record.get("base_fee") or 0
            self.blob_base_fee = record.get("blob_base_fee") or 0
        return "ok"


class _PoolViewContent:
    """Duck-typed ``pool`` for :class:`~reth_tpu.rpc.net.TxpoolApi`:
    ``content()`` computed from the replica's pending view so the
    txpool_* response shapes come from the one canonical formatter."""

    def __init__(self, api: "ReplicaEthApi"):
        self.api = api

    def content(self):
        return self.api._pool_content()


class ReplicaEthApi:
    """The replica's read surface. Handlers mirror ``rpc/eth.py``'s
    exactly (same env construction, same frame building, same response
    shapes) so every answer is bit-identical to the full node's — the
    only divergence allowed is ``-32001`` for state/blocks the replica
    does not hold, which the fleet router converts into a failover."""

    def __init__(self, replica: "ReplicaNode"):
        from ..rpc.net import TxpoolApi

        self.r = replica
        self._txpool = TxpoolApi(_PoolViewContent(self))

    # -- helpers ------------------------------------------------------------

    def _head(self) -> Header:
        h = self.r.head_header
        if h is None:
            raise RpcError(NOT_IN_WITNESS, "replica has no validated head")
        return h

    def _resolve_number(self, tag) -> int:
        head = self._head().number
        if tag in (None, "latest", "pending", "safe", "finalized"):
            return head
        if tag == "earliest":
            return 0
        return parse_qty(tag)

    def _record(self, n: int) -> dict:
        rec = self.r.blocks.get(n)
        if rec is None:
            raise RpcError(NOT_IN_WITNESS,
                           f"block {n} outside the replica window")
        return rec

    def _state_trie(self, tag):
        """The witness-backed state trie — latest only: a replica holds
        exactly one materialized state, the head's."""
        head = self._head()
        if self._resolve_number(tag) != head.number:
            raise RpcError(NOT_IN_WITNESS,
                           "replica serves latest state only")
        trie = self.r.state_trie()
        if trie is None:
            raise RpcError(NOT_IN_WITNESS, "replica state not materialized")
        return head, trie

    def _blinded(self, e: BlindedNodeError) -> RpcError:
        self.r.blinded_reads += 1
        self.r.metrics.record_blinded()
        return RpcError(NOT_IN_WITNESS,
                        f"state not in witness: {e}")

    # -- chain meta ---------------------------------------------------------

    def eth_chainId(self):
        return qty(self.r.chain_id)

    def eth_blockNumber(self):
        return qty(self._head().number)

    def eth_syncing(self):
        return False

    # -- blocks -------------------------------------------------------------

    def eth_getBlockByNumber(self, tag, full=False):
        n = self._resolve_number(tag)
        if n > self._head().number:
            return None  # the full node answers None for future blocks
        rec = self._record(n)
        return block_to_rpc(rec["block"], full,
                            rec["senders"] if full else None)

    def eth_getBlockByHash(self, block_hash, full=False):
        n = self.r.by_hash.get(parse_data(block_hash))
        if n is None:
            raise RpcError(NOT_IN_WITNESS,
                           "block hash outside the replica window")
        return self.eth_getBlockByNumber(qty(n), full)

    # -- logs ---------------------------------------------------------------

    def eth_getLogs(self, filt):
        from ..rpc.eth import _topics_match

        start = self._resolve_number(filt.get("fromBlock", "earliest"))
        end = self._resolve_number(filt.get("toBlock", "latest"))
        want_addr = None
        if filt.get("address"):
            a = filt["address"]
            want_addr = {parse_data(x)
                         for x in (a if isinstance(a, list) else [a])}
        topics = filt.get("topics") or []
        out = []
        for n in range(start, end + 1):
            rec = self._record(n)  # -32001 when outside the window
            block: Block = rec["block"]
            if not block.transactions:
                continue
            header = block.header
            log_base = 0
            for i, (tx, receipt) in enumerate(zip(block.transactions,
                                                  rec["receipts"])):
                for j, log in enumerate(receipt.logs):
                    if want_addr and log.address not in want_addr:
                        continue
                    if not _topics_match(log.topics, topics):
                        continue
                    out.append({
                        "address": data(log.address),
                        "topics": [data(x) for x in log.topics],
                        "data": data(log.data),
                        "blockNumber": qty(n),
                        "blockHash": data(header.hash),
                        "transactionHash": data(tx.hash),
                        "transactionIndex": qty(i),
                        "logIndex": qty(log_base + j),
                        "removed": False,
                    })
                log_base += len(receipt.logs)
        return out

    # -- proofs -------------------------------------------------------------

    def eth_getProof(self, address, slots, tag="latest"):
        _head, st = self._state_trie(tag)
        addr = parse_data(address)
        ha = keccak256(addr)
        try:
            # refs must be clean for spine(): a no-op when already clean
            st.account_trie.root_hash_compute(self.r.hasher)
            leaf = st.account_trie.get(ha)
            acc = _decode_account_leaf(leaf) if leaf is not None else None
            proof = st.account_trie.spine(ha)
            storage_root = acc.storage_root if acc else EMPTY_ROOT_HASH
            stg = st.storage_tries.get(ha)
            storage_proofs = []
            for s in slots:
                key_b = parse_qty(s).to_bytes(32, "big")
                if acc is None or storage_root == EMPTY_ROOT_HASH:
                    storage_proofs.append((key_b, 0, []))
                    continue
                if stg is None:
                    raise BlindedNodeError(
                        b"", f"storage trie of {addr.hex()} not in witness")
                stg.root_hash_compute(self.r.hasher)
                hs = keccak256(key_b)
                sleaf = stg.get(hs)
                value = (decode_int(rlp_decode(sleaf))
                         if sleaf is not None else 0)
                storage_proofs.append((key_b, value, stg.spine(hs)))
        except BlindedNodeError as e:
            raise self._blinded(e) from None
        return {
            "address": address,
            "accountProof": [data(n) for n in proof],
            "balance": qty(acc.balance if acc else 0),
            "nonce": qty(acc.nonce if acc else 0),
            "codeHash": data(acc.code_hash if acc else KECCAK_EMPTY),
            "storageHash": data(storage_root),
            "storageProof": [
                {"key": data(k), "value": qty(v),
                 "proof": [data(n) for n in p]}
                for k, v, p in storage_proofs
            ],
        }

    # -- execution (read-only) ----------------------------------------------

    def _call_env(self, header: Header) -> BlockEnv:
        return BlockEnv(
            number=header.number,
            timestamp=header.timestamp,
            coinbase=header.beneficiary,
            gas_limit=header.gas_limit,
            base_fee=header.base_fee_per_gas or 0,
            prev_randao=header.mix_hash,
            chain_id=self.r.chain_id,
        )

    def eth_call(self, call, tag="latest"):
        from ..rpc.eth import EthApi

        header, st = self._state_trie(tag)
        env = self._call_env(header)
        try:
            state = EvmState(ReplicaStateSource(st, self.r.codes))
            interp = Interpreter(state, env, TxEnv(
                origin=parse_data(call.get("from", "0x" + "00" * 20))))
            frame = EthApi._build_call_frame(call, state, env)
            try:
                ok, _gas_left, out = interp.call(frame)
            except Revert as r:
                raise RpcError(3, "execution reverted: 0x" + r.output.hex())
            if not ok:
                raise RpcError(-32000, "execution failed")
            return data(out)
        except BlindedNodeError as e:
            raise self._blinded(e) from None

    def eth_estimateGas(self, call, tag="latest"):
        from ..rpc.eth import EthApi

        header, st = self._state_trie(tag)
        env = self._call_env(header)
        sender = parse_data(call.get("from", "0x" + "00" * 20))
        try:
            state = EvmState(ReplicaStateSource(st, self.r.codes))
            interp = Interpreter(state, env, TxEnv(origin=sender))
            frame = EthApi._build_call_frame(call, state, env)
            to, gas = frame.address if call.get("to") else None, frame.gas
            try:
                ok, gas_left, _ = interp.call(frame)
            except Revert:
                raise RpcError(3, "execution reverted")
            if not ok:
                raise RpcError(-32000, "execution failed")
            used = gas - gas_left
            fake_tx = Transaction(
                to=to, data=parse_data(call.get("data",
                                                call.get("input", "0x"))))
            return qty(used + intrinsic_gas(fake_tx) + used // 16)
        except BlindedNodeError as e:
            raise self._blinded(e) from None

    # -- pending txs (fleet pool view) --------------------------------------

    def _view(self) -> ReplicaPoolView:
        v = self.r.pool_view
        if v is None or v.seq < 0:
            raise RpcError(NOT_IN_WITNESS, "replica pool view not synced")
        return v

    def eth_getTransactionByHash(self, tx_hash):
        h = parse_data(tx_hash)
        v = self.r.pool_view
        if v is not None and v.seq >= 0:
            entry = v.txs.get(h)
            if entry is not None:
                tx, sender = entry
                return tx_to_rpc(tx, sender=sender)  # pending: null block
        # mined within the retained window: the records hold everything
        for n, rec in self.r.blocks.items():
            block: Block = rec["block"]
            for i, tx in enumerate(block.transactions):
                if tx.hash == h:
                    return tx_to_rpc(tx, block.header, i,
                                     rec["senders"][i])
        # outside both views: fail over rather than answer None — the
        # full node may know it (older block, or a pool gap here)
        raise RpcError(NOT_IN_WITNESS,
                       "tx not in the replica's pending view or window")

    def eth_getTransactionCount(self, address, tag="latest"):
        addr = parse_data(address)
        pending = tag == "pending"
        _head, st = self._state_trie("latest" if pending else tag)
        try:
            acc = ReplicaStateSource(st, self.r.codes).account(addr)
        except BlindedNodeError as e:
            raise self._blinded(e) from None
        nonce = acc.nonce if acc else 0
        if pending:
            # mirror pool.pooled_nonce: highest contiguous pooled
            # nonce + 1; an unsynced view must fail over, not undercount
            nonces = self._view().by_sender.get(addr, {})
            while nonce in nonces:
                nonce += 1
        return qty(nonce)

    def _pool_content(self):
        """``pool.content()``-shaped view over the propagated pending
        set, mirroring the leader's bucketing: nonce-gapped or
        under-base-fee txs are "queued", the executable rest "pending".
        A sender whose account the witness never revealed buckets from
        its lowest propagated nonce — admission-level records carry no
        on-chain nonce, and guessing lower would fabricate a gap."""
        v = self._view()
        st = self.r.state_trie()
        src = (ReplicaStateSource(st, self.r.codes)
               if st is not None else None)
        out: dict = {"pending": {}, "queued": {}}
        for sender, nonces in v.by_sender.items():
            next_nonce = None
            if src is not None:
                try:
                    acc = src.account(sender)
                    next_nonce = acc.nonce if acc else 0
                except BlindedNodeError:
                    next_nonce = None
            if next_nonce is None:
                next_nonce = min(nonces)
            for nonce in sorted(nonces):
                tx, _sender = v.txs[nonces[nonce]]
                gap = nonce > next_nonce
                if tx.tx_type >= 2:
                    tip = (-1 if tx.max_fee_per_gas < v.base_fee
                           else min(tx.max_priority_fee_per_gas,
                                    tx.max_fee_per_gas - v.base_fee))
                else:
                    tip = tx.gas_price - v.base_fee
                key = "pending" if not gap and tip >= 0 else "queued"
                out[key].setdefault(sender, {})[nonce] = tx
                if not gap:
                    next_nonce = nonce + 1
        return out

    def txpool_status(self):
        return self._txpool.txpool_status()

    def txpool_content(self):
        return self._txpool.txpool_content()

    def txpool_contentFrom(self, address):
        return self._txpool.txpool_contentFrom(address)

    def txpool_inspect(self):
        return self._txpool.txpool_inspect()

    # -- fleet control ------------------------------------------------------

    def fleet_status(self):
        """The probe the gateway ring polls to drive draining: validated
        head vs the feed's announced head (the lag), liveness, and the
        counters a fleet operator reads."""
        return self.r.status()

    def fleet_metricsSnapshot(self, cursor=None):
        """Metrics federation pull (obs/federation.py): this replica's
        registry as a delta-encoded snapshot against ``cursor`` (None or
        a stale cursor returns the full absolute state). Classified into
        the gateway's engine admission class with the other fleet_*
        methods — federation pulls must never starve behind a debug
        trace."""
        return self.r.federation_source.snapshot(cursor)


class ReplicaNode:
    """A witness-fed stateless replica: feed client + StatelessChain +
    the read RPC surface, with no database anywhere."""

    def __init__(self, feed_host: str, feed_port: int, *,
                 http_port: int = 0, retention: int = DEFAULT_RETENTION,
                 replica_id: str | None = None,
                 injector: ReplicaFaultInjector | None = None,
                 gateway: bool = True, registry=None,
                 failover_feeds=None, auto_register: bool = False):
        from ..metrics import ReplicaMetrics

        self.replica_id = replica_id or f"replica-{os.getpid()}"
        self.retention = retention
        self.lock = threading.RLock()
        self.hasher = keccak256_batch_np
        self.chain: StatelessChain | None = None
        self.chain_id = 1
        self.head_header: Header | None = None
        self.announced: tuple[int, bytes] | None = None
        self.blocks: dict[int, dict] = {}
        self.by_hash: dict[bytes, int] = {}
        self.codes: dict[bytes, bytes] = {}
        self.started_at = time.time()
        self.blocks_validated = 0
        self.validation_failures = 0
        self.blinded_reads = 0
        # pending view fed by the leader pool's pt_* records; unsynced
        # (seq -1) until the first pt_snapshot lands post-subscribe
        self.pool_view: ReplicaPoolView | None = ReplicaPoolView()
        self.pool_resubscribes = 0
        self.injector = (injector if injector is not None
                         else ReplicaFaultInjector.from_env())
        self.metrics = ReplicaMetrics(registry)
        # metrics federation source: the full node pulls this replica's
        # registry (delta-encoded) via fleet_metricsSnapshot
        from ..obs.federation import FederationSource

        self.federation_source = FederationSource(registry)
        # correlated flight dumps seen (fan-out dedupe: a dump this
        # replica initiated comes back on the feed and must not re-dump)
        self._corr_seen: dict[str, bool] = {}
        # HA failover: extra feed endpoints (the standby's takeover
        # feed) the client rotates to when the leader dies; on hello
        # from a NEW leader epoch, auto_register re-anchors this
        # replica into the promoted leader's gateway ring
        self.auto_register = auto_register
        self.leader_epoch = 0
        self.reregistrations = 0
        self.client = WitnessFeedClient(
            feed_host, feed_port,
            on_hello=self._on_hello, on_record=self._on_record,
            endpoints=failover_feeds)
        self.gateway = None
        if gateway:
            # the replica runs its OWN serving gateway: identical reads
            # routed here by the ring coalesce and cache next to the
            # state they read (keys embed the replica's validated head)
            from ..rpc.gateway import RpcGateway

            self.gateway = RpcGateway(
                head_supplier=lambda: (self.head_header.hash
                                       if self.head_header is not None
                                       else b""),
                registry=registry)
        self.rpc = RpcServer(port=http_port, lock=self.lock,
                             gateway=self.gateway)
        self.rpc.register(ReplicaEthApi(self))
        self.http_port: int | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        self.http_port = self.rpc.start()
        # correlated dumps: a replica-side fault event notifies the full
        # node upstream over the feed socket so the WHOLE fleet dumps
        # under the initiating incident's correlation id
        tracing.add_fault_observer(self._on_local_fault)
        self.client.start()
        return self.http_port

    def stop(self) -> None:
        tracing.remove_fault_observer(self._on_local_fault)
        self.client.stop()
        self.rpc.stop()

    def _on_local_fault(self, reason: str, correlation_id: str,
                        window) -> None:
        self._corr_seen[correlation_id] = True
        while len(self._corr_seen) > 256:
            del self._corr_seen[next(iter(self._corr_seen))]
        self.client.send({"type": "flight_dump", "reason": reason,
                          "correlation_id": correlation_id,
                          "window": list(window) if window else None,
                          "origin": {"role": "replica",
                                     "id": self.replica_id,
                                     "pid": os.getpid()}})

    def wait_synced(self, target: int, timeout: float = 15.0) -> bool:
        """Test/CLI helper: wait until the validated head reaches
        ``target``."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self.lock:
                h = self.head_header
            if h is not None and h.number >= target:
                return True
            time.sleep(0.02)
        return False

    # -- feed intake --------------------------------------------------------

    def _on_hello(self, hello: dict) -> None:
        epoch = int(hello.get("epoch") or 0)
        rpc_port = hello.get("rpc_port")
        register_target = None
        with self.lock:
            if epoch and epoch != self.leader_epoch:
                # a new leader lineage (first connect, or a promoted
                # standby after failover): re-anchor this replica into
                # the leader's gateway ring so reads keep routing here
                if self.auto_register and rpc_port and self.http_port:
                    ep = self.client.endpoint
                    if ep is not None:
                        register_target = f"http://{ep[0]}:{rpc_port}"
                self.leader_epoch = epoch
            self.chain_id = hello.get("chain_id", 1)
            spec = hello.get("spec")
            exec_spec = None
            if spec is not None:
                from ..chainspec import ChainSpec

                exec_spec = ChainSpec.from_json(spec).execution_spec
            config = EvmConfig(chain_id=self.chain_id, chainspec=exec_spec)
            if self.chain is None:
                self.chain = StatelessChain(config=config,
                                            hasher=self.hasher)
            if hello.get("head") is not None:
                self.announced = tuple(hello["head"])
            if self.pool_view is not None:
                # a new session starts unsynced: the server-side pool
                # flag died with the old socket, and the fresh snapshot
                # the re-subscribe earns resets the view wholesale
                self.pool_view.seq = -1
        if self.pool_view is not None:
            self.client.send({"type": "subscribe_pool"})
        if register_target is not None:
            threading.Thread(target=self._register_with,
                             args=(register_target,), daemon=True,
                             name="replica-reanchor").start()

    def _register_with(self, url: str) -> None:
        """Best-effort ``fleet_register`` against the (new) leader's
        gateway — the ring re-anchor half of a failover."""
        import json
        import urllib.request

        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "fleet_register",
            "params": [f"http://127.0.0.1:{self.http_port}"],
        }).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10).read()
            self.reregistrations += 1
            tracing.event("fleet::replica", "reanchored", leader=url)
        except Exception:  # noqa: BLE001 - the prober will retry reads
            pass

    def _on_record(self, record: dict) -> None:
        kind = record.get("type")
        if kind == "head":
            with self.lock:
                self.announced = (record["number"], record["hash"])
                self._update_lag()
            return
        if kind == "flight_dump":
            # correlated dump request fanned out by the full node: dump
            # this replica's ring under the SAME correlation id (skip if
            # this replica initiated it — it already dumped)
            cid = record.get("correlation_id")
            if cid and cid not in self._corr_seen:
                self._corr_seen[cid] = True
                while len(self._corr_seen) > 256:
                    del self._corr_seen[next(iter(self._corr_seen))]
                tracing.flight_dump(str(record.get("reason") or "fleet"),
                                    correlation_id=cid,
                                    window=record.get("window"))
            return
        if kind and kind.startswith("pt_"):
            self._apply_pool_record(record)
            return
        if kind != "block":
            return
        # the announcement is the block itself: lag accounting must see
        # it even when the injector drops the record
        with self.lock:
            if (self.announced is None
                    or record["number"] >= self.announced[0]):
                self.announced = (record["number"], record["hash"])
        if self.injector is not None and self.injector.on_block(
                record["number"]):
            with self.lock:
                self._update_lag()
            return
        self._apply_block(record)

    def _apply_pool_record(self, record: dict) -> None:
        view = self.pool_view
        if view is None:
            return
        with self.lock:
            outcome = view.apply(record)
        if outcome == "gap":
            # records were shed upstream (drop-oldest backpressure) or
            # lost in a partition drill: re-subscribe for a fresh
            # snapshot instead of serving a silently-divergent view
            # (apply() already reset the view to unsynced, so reads
            # answer -32001 and fail over until the snapshot lands)
            self.pool_resubscribes += 1
            tracing.event("fleet::replica", "pool_view_gap",
                          seq=record.get("seq"))
            self.client.send({"type": "subscribe_pool"})

    def _apply_block(self, record: dict) -> None:
        from ..engine.witness import ExecutionWitness

        block = Block.decode(record["block_rlp"])
        with self.lock:
            if block.hash in self.by_hash:
                return  # duplicate record (reconnect catch-up overlap)
        w = record["witness"]
        witness = ExecutionWitness(state=list(w["state"]),
                                   codes=list(w["codes"]),
                                   keys=list(w["keys"]),
                                   headers=list(w["headers"]))
        with self.lock:
            if self.chain is None:
                self.chain = StatelessChain(config=EvmConfig(
                    chain_id=self.chain_id), hasher=self.hasher)
            if not witness.headers:
                self.validation_failures += 1
                self.metrics.record_validation_failure()
                return
            parent_header = Header.decode(witness.headers[0])
            t0 = time.monotonic()
            # cross-process trace adoption: the record's wire-form
            # context (trace id = block hash, parent = the full node's
            # witness.generate span) makes this validation part of the
            # SAME block lifecycle trace the full node recorded
            remote_ctx = tracing.context_from_wire(record.get("tp"))
            try:
                with tracing.use_context(remote_ctx or
                                         tracing.current_context()):
                    with tracing.span("fleet::replica",
                                      "stateless.validate",
                                      number=block.header.number):
                        self.chain.validate(block, witness, parent_header)
            except (StatelessValidationError, Exception) as e:  # noqa: BLE001
                # a replica must never crash on a bad record: count it,
                # keep serving the last good head, re-anchor on the next
                self.validation_failures += 1
                self.metrics.record_validation_failure()
                tracing.event("fleet::replica", "validation_failed",
                              number=block.header.number,
                              error=f"{type(e).__name__}: {e}")
                return
            out = self.chain.last_output
            n = block.header.number
            # a reorg replaces the retained record at this height: drop
            # the stale hash index entry before installing the new one
            old = self.blocks.get(n)
            if old is not None:
                self.by_hash.pop(old["block"].hash, None)
            self.blocks[n] = {
                "block": block,
                "senders": list(record["senders"]),
                "receipts": list(out.receipts) if out is not None else [],
            }
            self.by_hash[block.hash] = n
            for floor in [k for k in self.blocks
                          if k <= n - self.retention]:
                stale = self.blocks.pop(floor)
                self.by_hash.pop(stale["block"].hash, None)
            for c in witness.codes:
                self.codes[keccak256(c)] = c
            self.head_header = block.header
            self.blocks_validated += 1
            self.metrics.record_validated(time.monotonic() - t0)
            self._update_lag()
        # head changed: retire the replica-local response cache
        if self.gateway is not None:
            self.gateway.on_head_change()

    def _update_lag(self) -> None:
        self.metrics.set_lag(self.lag_heads())

    # -- state access (under self.lock) -------------------------------------

    def state_trie(self):
        """The preserved sparse trie at the validated head (None before
        the first block validates)."""
        if self.chain is None or self.head_header is None:
            return None
        return self.chain.preserved.peek(self.head_header.hash)

    def lag_heads(self) -> int:
        if self.announced is None:
            return 0
        head = self.head_header.number if self.head_header is not None else 0
        return max(0, self.announced[0] - head)

    def status(self) -> dict:
        with self.lock:
            head = self.head_header
            return {
                "id": self.replica_id,
                "pid": os.getpid(),
                "head": ({"number": head.number, "hash": data(head.hash)}
                         if head is not None else None),
                "announced": ({"number": self.announced[0],
                               "hash": data(self.announced[1])}
                              if self.announced is not None else None),
                "lag_heads": self.lag_heads(),
                "connected": self.client.connected.is_set(),
                "blocks_validated": self.blocks_validated,
                "validation_failures": self.validation_failures,
                "blinded_reads": self.blinded_reads,
                "window": [min(self.blocks), max(self.blocks)]
                          if self.blocks else None,
                "wedged": bool(self.injector is not None
                               and self.injector.wedging),
                "pool_view": ({
                    "synced": self.pool_view.seq >= 0,
                    "seq": self.pool_view.seq,
                    "txs": len(self.pool_view.txs),
                    "records": self.pool_view.records,
                    "snapshots": self.pool_view.snapshots,
                    "resubscribes": self.pool_resubscribes,
                } if self.pool_view is not None else None),
                "uptime_s": round(time.time() - self.started_at, 1),
            }
