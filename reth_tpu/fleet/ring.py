"""Consistent-hash gateway ring over registered read replicas.

The fleet side of ``rpc/gateway.py``: pure reads admitted by the
gateway route to a replica picked by consistent-hashing the gateway's
own ``(method, canonical params, head_hash)`` cache key — identical
reads land on the same replica and therefore in its response cache,
and a fleet-size change only remaps ``1/n`` of the key space (the
classic ring property, here keeping replica caches warm across
membership churn).

Failure ladder per request: chosen replica → next ring position → the
local full node (``invoke_local``). A replica that answers with a
JSON-RPC error (``-32001`` for state outside its witness window, or
anything else) triggers the same failover — the client NEVER sees a
replica-induced failure, and every served answer is bit-identical to
the full node's by the replica's own construction.

Draining: a background prober polls each replica's ``fleet_status``
(classified into the gateway's ``engine`` admission class, so probes
can never starve behind a ``debug_traceBlock``) and sheds a replica
from the ring BEFORE users notice when it degrades — unreachable,
reporting ``wedged``, lagging more than ``max_lag`` heads behind the
full node's head, or failing its ``/health`` roll-up. A shed replica
keeps being probed and rejoins on recovery (hysteresis: ``heal_n``
consecutive good probes).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import threading
import time
import urllib.request

from .. import tracing

PROBE_INTERVAL_S = 0.5
DEFAULT_MAX_LAG = 4
DEFAULT_TIMEOUT_S = 5.0
MAX_RING_TRIES = 2  # replicas tried before falling back to the full node


def _hval(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with virtual nodes (stable key → node
    mapping under membership churn)."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = vnodes
        self._points: list[int] = []       # sorted vnode positions
        self._owner: dict[int, str] = {}   # position -> node id
        self._nodes: set[str] = set()

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node_id: str) -> bool:
        return node_id in self._nodes

    def add(self, node_id: str) -> None:
        if node_id in self._nodes:
            return
        self._nodes.add(node_id)
        for i in range(self.vnodes):
            pos = _hval(f"{node_id}#{i}".encode())
            # vanishing collision chance; last writer wins deterministically
            if pos not in self._owner:
                bisect.insort(self._points, pos)
            self._owner[pos] = node_id

    def remove(self, node_id: str) -> None:
        if node_id not in self._nodes:
            return
        self._nodes.discard(node_id)
        for i in range(self.vnodes):
            pos = _hval(f"{node_id}#{i}".encode())
            if self._owner.get(pos) == node_id:
                del self._owner[pos]
                idx = bisect.bisect_left(self._points, pos)
                if idx < len(self._points) and self._points[idx] == pos:
                    self._points.pop(idx)

    def nodes_for(self, key: bytes):
        """Distinct node ids in ring order starting at ``key``'s
        position — the failover order."""
        if not self._points:
            return
        start = bisect.bisect(self._points, _hval(key))
        seen = set()
        n = len(self._points)
        for off in range(n):
            node = self._owner[self._points[(start + off) % n]]
            if node not in seen:
                seen.add(node)
                yield node
                if len(seen) == len(self._nodes):
                    return


class ReplicaHandle:
    """One registered replica: address + probed health + route stats."""

    __slots__ = ("id", "url", "state", "lag", "routed", "failovers",
                 "errors", "probe_failures", "good_probes",
                 "registered_at", "last_probe", "last_error")

    def __init__(self, rid: str, url: str):
        self.id = rid
        self.url = url.rstrip("/")
        self.state = "healthy"  # healthy | draining | unreachable
        self.lag = 0
        self.routed = 0
        self.failovers = 0
        self.errors = 0
        self.probe_failures = 0
        self.good_probes = 0
        self.registered_at = time.time()
        self.last_probe: float | None = None
        self.last_error: str | None = None

    def snapshot(self) -> dict:
        return {"id": self.id, "url": self.url, "state": self.state,
                "lag": self.lag, "routed": self.routed,
                "failovers": self.failovers, "errors": self.errors,
                "last_error": self.last_error}


class ReplicaError(Exception):
    """A replica answered with a JSON-RPC error (failover signal)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class FleetRouter:
    """The gateway's fleet mode: ring routing + probed draining +
    failover to the local full node."""

    def __init__(self, *, max_lag: int = DEFAULT_MAX_LAG,
                 probe_interval: float = PROBE_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 heal_n: int = 2, vnodes: int = 64, registry=None):
        from ..metrics import FleetMetrics

        self.max_lag = max_lag
        self.probe_interval = probe_interval
        self.timeout_s = timeout_s
        self.heal_n = heal_n
        self.ring = HashRing(vnodes=vnodes)
        self.replicas: dict[str, ReplicaHandle] = {}
        self.head: tuple[int, bytes] | None = None
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._seq = 0
        self.metrics = FleetMetrics(registry)
        # lifetime counters surfaced via snapshot()
        self.routed = 0
        self.failovers = 0
        self.local_fallbacks = 0
        self.sheds = 0
        self.heals = 0

    # -- membership ---------------------------------------------------------

    def register(self, url: str, rid: str | None = None) -> str:
        with self._lock:
            for h in self.replicas.values():
                if h.url == url.rstrip("/"):
                    return h.id  # idempotent re-registration
            if rid is None:
                self._seq += 1
                rid = f"replica-{self._seq}"
            h = ReplicaHandle(rid, url)
            self.replicas[rid] = h
            self.ring.add(rid)
            self._publish()
        tracing.event("fleet::ring", "register", id=rid, url=url)
        return rid

    def deregister(self, rid: str) -> bool:
        with self._lock:
            h = self.replicas.pop(rid, None)
            if h is None:
                return False
            self.ring.remove(rid)
            self._publish()
        tracing.event("fleet::ring", "deregister", id=rid)
        return True

    def drain(self, rid: str, why: str = "manual") -> bool:
        """Shed a replica from the ring (kept registered + probed; a
        recovered replica rejoins)."""
        with self._lock:
            h = self.replicas.get(rid)
            if h is None:
                return False
            if h.state != "draining":
                h.state = "draining"
                h.good_probes = 0
                self.ring.remove(rid)
                self.sheds += 1
                self.metrics.record_shed()
                self._publish()
        tracing.event("fleet::ring", "drain", id=rid, why=why)
        return True

    def _heal(self, h: ReplicaHandle) -> None:
        # caller holds the lock
        if h.state != "healthy":
            h.state = "healthy"
            self.ring.add(h.id)
            self.heals += 1
            self.metrics.record_heal()
            self._publish()
            tracing.event("fleet::ring", "heal", id=h.id)

    def _publish(self) -> None:
        # caller holds the lock
        states = [h.state for h in self.replicas.values()]
        self.metrics.set_replicas(
            registered=len(states),
            healthy=states.count("healthy"),
            draining=states.count("draining"),
            unreachable=states.count("unreachable"),
            max_lag=max((h.lag for h in self.replicas.values()), default=0))

    # -- head fanout --------------------------------------------------------

    def on_head_change(self, chain=None) -> None:
        """Engine canon listener: record the authoritative head for lag
        accounting. (Response invalidation is structural — every routed
        key embeds the head hash, and replicas retire their own caches
        off the feed's head announcements.)"""
        if chain:
            tip = chain[-1]
            self.head = (tip.number, tip.hash)

    # -- routing ------------------------------------------------------------

    def route(self, method: str, params, key, invoke_local):
        """One read: ring replica → next ring position → local node.

        Each replica attempt runs under a ``fleet.route`` span tagged
        with the serving replica's id (a hot or flappy replica shows in
        the trace, not just the logs), and the span's context rides the
        request as its ``traceparent`` — the replica adopts it, so the
        remote handler's spans stitch under this one cross-process."""
        kb = repr(key).encode()
        tried = 0
        with self._lock:
            order = list(self.ring.nodes_for(kb))
        for rid in order:
            if tried >= MAX_RING_TRIES:
                break
            with self._lock:
                h = self.replicas.get(rid)
                if h is None or h.state != "healthy":
                    continue
            tried += 1
            try:
                with tracing.span("fleet::ring", "fleet.route",
                                  replica=rid, method=method) as sctx:
                    result = self._rpc(h.url, method, params,
                                       ctx=tracing.context_to_wire(sctx))
            except ReplicaError as e:
                # the replica is healthy but cannot answer THIS read
                # bit-identically (-32001 witness miss, or any error):
                # fail over without shedding it
                with self._lock:
                    h.failovers += 1
                    self.failovers += 1
                self.metrics.record_failover(rid)
                tracing.event("fleet::ring", "failover", id=rid,
                              method=method, code=e.code)
                continue
            except OSError as e:
                # transport failure: shed NOW, the prober re-admits
                with self._lock:
                    h.errors += 1
                    h.last_error = f"{type(e).__name__}: {e}"
                    h.failovers += 1
                    self.failovers += 1
                self.metrics.record_failover(rid)
                self._mark_unreachable(rid)
                continue
            with self._lock:
                h.routed += 1
                self.routed += 1
            self.metrics.record_routed(rid)
            return result
        self.local_fallbacks += 1
        self.metrics.record_local_fallback()
        return invoke_local()

    def _mark_unreachable(self, rid: str) -> None:
        with self._lock:
            h = self.replicas.get(rid)
            if h is None:
                return
            if h.state != "unreachable":
                h.state = "unreachable"
                h.good_probes = 0
                self.ring.remove(rid)
                self.sheds += 1
                self.metrics.record_shed()
                self._publish()
        tracing.event("fleet::ring", "shed", id=rid, why="unreachable")

    def _rpc(self, url: str, method: str, params, ctx: dict | None = None):
        req_obj = {"jsonrpc": "2.0", "id": 1, "method": method,
                   "params": params}
        if ctx is not None:
            # wire-form trace context (tracing.context_to_wire): the
            # replica's RpcServer adopts it, stitching its handler spans
            # under this gateway's fleet.route span
            req_obj["traceparent"] = ctx
        body = json.dumps(req_obj).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            obj = json.loads(resp.read())
        if "error" in obj:
            err = obj["error"] or {}
            raise ReplicaError(err.get("code", -32000),
                               err.get("message", "replica error"))
        return obj.get("result")

    # -- probing / draining -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or self.probe_interval <= 0:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._probe_loop,
                                        daemon=True, name="fleet-prober")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — probing must never die
                pass

    def probe_once(self) -> None:
        """One probe pass over every registered replica (the thread
        body; tests drive it directly for determinism)."""
        with self._lock:
            handles = list(self.replicas.values())
        head_n = self.head[0] if self.head is not None else None
        for h in handles:
            verdict, why = self._probe(h, head_n)
            with self._lock:
                if h.id not in self.replicas:
                    continue  # deregistered mid-probe
                h.last_probe = time.time()
                if verdict:
                    h.probe_failures = 0
                    h.good_probes += 1
                    if (h.state in ("draining", "unreachable")
                            and h.good_probes >= self.heal_n):
                        self._heal(h)
                else:
                    h.good_probes = 0
                    h.probe_failures += 1
                    h.last_error = why
                    if h.state == "healthy":
                        state = ("unreachable" if why.startswith("probe ")
                                 else "draining")
                        h.state = state
                        self.ring.remove(h.id)
                        self.sheds += 1
                        self.metrics.record_shed()
                        self._publish()
                        tracing.event("fleet::ring", "shed", id=h.id,
                                      why=why)
                self._publish()

    def _probe(self, h: ReplicaHandle, head_n: int | None):
        """(healthy?, reason) for one replica: fleet_status + lag +
        /health roll-up."""
        try:
            status = self._rpc(h.url, "fleet_status", [])
        except (ReplicaError, OSError) as e:
            return False, f"probe {type(e).__name__}: {e}"
        h.lag = int(status.get("lag_heads", 0) or 0)
        if head_n is not None and status.get("head"):
            h.lag = max(h.lag, head_n - int(status["head"]["number"]))
        elif head_n is not None and not status.get("head"):
            h.lag = max(h.lag, head_n)
        if status.get("wedged"):
            return False, "replica wedged"
        if not status.get("connected", True):
            return False, "feed disconnected"
        if h.lag > self.max_lag:
            return False, f"feed lag {h.lag} > {self.max_lag} heads"
        # /health roll-up (liveness answered even without --health)
        try:
            with urllib.request.urlopen(f"{h.url}/health",
                                        timeout=self.timeout_s) as resp:
                health = json.loads(resp.read())
            if health.get("status") == "failing":
                return False, "health failing"
        except OSError:
            return False, "probe /health unreachable"
        return True, ""

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            reps = [h.snapshot() for h in self.replicas.values()]
        states = [r["state"] for r in reps]
        return {
            "replicas": reps,
            "registered": len(reps),
            "healthy": states.count("healthy"),
            "draining": states.count("draining"),
            "unreachable": states.count("unreachable"),
            "ring_size": len(self.ring),
            "routed": self.routed,
            "failovers": self.failovers,
            "local_fallbacks": self.local_fallbacks,
            "sheds": self.sheds,
            "heals": self.heals,
            "max_lag": max((r["lag"] for r in reps), default=0),
            "head": (self.head[0] if self.head is not None else None),
        }


class FleetAdminApi:
    """fleet_* control surface registered on the full node's public
    server (classified into the gateway's ``engine`` admission class —
    registration and draining must never starve behind a debug trace)."""

    def __init__(self, router: FleetRouter, feed_server=None):
        self.router = router
        self.feed = feed_server

    def fleet_register(self, url):
        return self.router.register(url)

    def fleet_deregister(self, rid):
        return self.router.deregister(rid)

    def fleet_drain(self, rid):
        return self.router.drain(rid)

    def fleet_status(self):
        out = self.router.snapshot()
        if self.feed is not None:
            out["feed"] = self.feed.snapshot()
        return out
