"""``python -m reth_tpu.fleet`` — run fleet roles standalone.

``replica``: the stateless read-replica process (`--role replica` on
the main CLI delegates here). It holds no database: everything it
serves comes over the witness feed.

``standby``: the WAL-shipped hot standby (`--role standby` delegates
here). It replays the leader's durable stream into its own datadir and
promotes itself to leader on heartbeat loss or ``fleet_promote``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _parse_hostport(spec: str, flag: str) -> tuple[str, int] | None:
    host, _, port = spec.rpartition(":")
    if not host or not port.isdigit():
        print(f"error: {flag} must be HOST:PORT, got {spec!r}",
              file=sys.stderr)
        return None
    return host, int(port)


def run_replica(args) -> int:
    from .. import tracing
    from .replica import ReplicaNode

    # fleet-wide trace attribution: this process IS a replica — the
    # role rides every exported span's resource attributes, the Chrome
    # process metadata, and the wire form it stamps on outgoing context
    tracing.set_process_role("replica")
    if args.trace_file:
        # cross-process stitching needs the replica's half of the trace
        # on disk: enable span recording + the Chrome exporter (the
        # full node's side comes from --trace-blocks); flight dumps go
        # wherever RETH_TPU_FLIGHT_DIR points (a fleet shares one dir
        # so correlated dumps land together)
        tracing.init_block_tracing(chrome_path=args.trace_file)
    ep = _parse_hostport(args.feed, "--feed")
    if ep is None:
        return 1
    failover = []
    for spec in (args.failover_feed or ()):
        fep = _parse_hostport(spec, "--failover-feed")
        if fep is None:
            return 1
        failover.append(fep)
    replica = ReplicaNode(ep[0], ep[1], http_port=args.http_port,
                          retention=args.retention,
                          replica_id=args.id,
                          failover_feeds=failover or None,
                          auto_register=args.auto_register)
    http_port = replica.start()
    print(f"replica RPC listening on 127.0.0.1:{http_port} "
          f"(feed {args.feed})", flush=True)
    if args.port_file:
        # orchestrators (bench fleet mode, the chaos fleet domain, the
        # README quick-start's registration step) read the bound port
        # from here instead of scraping stdout
        from pathlib import Path

        Path(args.port_file).write_text(json.dumps(
            {"http_port": http_port, "id": replica.replica_id}))
    if args.register:
        # self-registration with the full node's fleet gateway
        import urllib.request

        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "fleet_register",
            "params": [f"http://127.0.0.1:{http_port}"],
        }).encode()
        req = urllib.request.Request(
            args.register, data=body,
            headers={"Content-Type": "application/json"})
        rid = json.loads(urllib.request.urlopen(
            req, timeout=10).read()).get("result")
        print(f"registered with {args.register} as {rid}", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    replica.stop()
    if args.trace_file:
        # terminate the Chrome trace into a valid JSON array
        tracing.shutdown_block_tracing()
    return 0


def run_standby(args) -> int:
    from .standby import StandbyFaultInjector, StandbyNode

    ep = _parse_hostport(args.feed, "--feed")
    if ep is None:
        return 1
    standby = StandbyNode(
        ep[0], ep[1], datadir=args.datadir, standby_id=args.id,
        http_port=args.http_port,
        takeover_feed_port=args.takeover_feed_port,
        auto_promote=not args.no_auto_promote,
        heartbeat_timeout_s=args.heartbeat_timeout,
        injector=StandbyFaultInjector.from_env())
    http_port = standby.start()
    print(f"standby admin RPC listening on 127.0.0.1:{http_port} "
          f"(feed {args.feed}, datadir {args.datadir})", flush=True)
    if args.port_file:
        from pathlib import Path

        Path(args.port_file).write_text(json.dumps(
            {"http_port": http_port, "id": standby.standby_id,
             "pid": standby.status()["pid"]}))
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    standby.stop()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m reth_tpu.fleet",
        description="stateless read-replica fleet roles")
    sub = parser.add_subparsers(dest="command", required=True)
    p = sub.add_parser("replica", help="run a witness-fed stateless "
                                       "read replica (no database)")
    p.add_argument("--feed", required=True,
                   help="HOST:PORT of the full node's witness feed")
    p.add_argument("--http-port", type=int, default=0,
                   help="RPC port (0 = ephemeral)")
    p.add_argument("--retention", type=int, default=128,
                   help="validated blocks retained for serving")
    p.add_argument("--id", default=None, help="replica id override")
    p.add_argument("--port-file", default=None,
                   help="write the bound RPC port here as JSON")
    p.add_argument("--register", default=None,
                   help="full-node RPC URL to self-register with "
                        "(fleet_register)")
    p.add_argument("--failover-feed", action="append", default=None,
                   help="additional HOST:PORT feed endpoint to rotate "
                        "to when the primary dies (a standby's "
                        "takeover feed); repeatable")
    p.add_argument("--auto-register", action="store_true",
                   help="re-register with the serving leader's gateway "
                        "whenever the feed's leader epoch changes "
                        "(failover re-anchor)")
    p.add_argument("--trace-file", dest="trace_file", default=None,
                   help="write this replica's spans as a Chrome trace "
                        "here (the replica half of a stitched fleet "
                        "trace)")
    s = sub.add_parser("standby", help="run a WAL-shipped hot standby "
                                       "(promotes to leader on "
                                       "heartbeat loss / fleet_promote)")
    s.add_argument("--feed", required=True,
                   help="HOST:PORT of the leader's witness feed")
    s.add_argument("--datadir", required=True,
                   help="standby datadir (becomes the leader datadir "
                        "on promotion)")
    s.add_argument("--http-port", type=int, default=0,
                   help="standby admin RPC port (fleet_standbyStatus / "
                        "fleet_promote; 0 = ephemeral)")
    s.add_argument("--takeover-feed-port", type=int, default=0,
                   help="feed port the promoted node binds "
                        "(0 = ephemeral)")
    s.add_argument("--id", default=None, help="standby id override")
    s.add_argument("--no-auto-promote", action="store_true",
                   help="only promote on explicit fleet_promote (no "
                        "heartbeat-loss trigger)")
    s.add_argument("--heartbeat-timeout", type=float, default=2.0,
                   help="seconds without a leader heartbeat before "
                        "auto-promotion fires")
    s.add_argument("--port-file", default=None,
                   help="write the bound admin RPC port here as JSON")
    args = parser.parse_args(argv)
    if args.command == "replica":
        return run_replica(args)
    if args.command == "standby":
        return run_standby(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
