"""Leader election plumbing for the HA pair: the promotion state
machine, the heartbeat monitor, and the epoch fencing probe.

There is no quorum here — the fleet runs exactly one leader and one
hot standby (ROADMAP item 2a), so "election" reduces to a deterministic
promotion ladder plus epoch fencing:

- **Promotion state machine** (:class:`PromotionStateMachine`):
  ``following → catching-up → promoting → leading`` (terminal
  ``failed`` when the recovered head root does not verify). Transitions
  are monotonic — a standby never demotes itself; a fenced OLD leader
  restarts into ``fenced`` instead.
- **Heartbeat monitor** (:class:`HeartbeatMonitor`): the leader stamps
  ``st_heartbeat`` frames onto the feed socket; the standby arms a
  deadline per beat. Missing the deadline (socket alive but silent —
  the partition case) or losing the socket entirely both funnel into
  one ``on_loss`` callback, fired once per connection epoch.
- **Fencing probe** (:func:`probe_feed_hello` / :func:`fence_check`):
  every feed hello carries the sender's monotonic ``leader_epoch``
  (persisted in the WAL manifest, storage/wal.py). A restarted old
  leader probes the standby's takeover feed before serving writes: a
  live peer advertising a HIGHER epoch means this node was superseded
  while it was dead — it must fence (refuse stale writes) rather than
  split-brain the fleet. ``RETH_TPU_FAULT_HA_NO_FENCE=1`` disables the
  check — the deliberately broken mode the chaos negative drill uses to
  prove the invariant suite notices a split brain.
"""

from __future__ import annotations

import os
import socket
import threading
import time

from .feed import FEED_MAGIC, _recv_exact, recv_frame

# promotion ladder, in order; "failed" and "fenced" are terminal
STATES = ("following", "catching-up", "promoting", "leading")


class PromotionStateMachine:
    """The standby's promotion ladder. Thread-safe; transitions are
    monotonic along :data:`STATES` (plus the terminal ``failed``), and
    every transition lands in ``history`` with a wall-clock stamp and
    the reason — the forensic trail a failover post-mortem reads."""

    def __init__(self, on_transition=None):
        self._lock = threading.Lock()
        self._state = "following"
        self.on_transition = on_transition
        self.history: list[dict] = [
            {"state": "following", "at": time.time(), "why": "start"}]

    @property
    def state(self) -> str:
        return self._state

    def is_leading(self) -> bool:
        return self._state == "leading"

    def advance(self, to: str, why: str = "") -> bool:
        """Move to ``to``; False when the transition would go backwards
        (or away from a terminal state) — promotion never regresses."""
        with self._lock:
            cur = self._state
            if cur in ("failed", "fenced"):
                return False
            if to == "failed":
                pass  # any live state may fail
            elif to not in STATES or cur not in STATES \
                    or STATES.index(to) <= STATES.index(cur):
                return False
            self._state = to
            self.history.append(
                {"state": to, "at": time.time(), "why": why})
        if self.on_transition is not None:
            try:
                self.on_transition(to, why)
            except Exception:  # noqa: BLE001 - observers never gate
                pass
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "history": [dict(h) for h in self.history]}


class HeartbeatMonitor:
    """Deadline watchdog over the leader's ``st_heartbeat`` cadence.

    ``note()`` on every received beat re-arms the deadline; a checker
    thread fires ``on_loss(age_s)`` once when the deadline lapses.
    ``reset()`` re-arms after a reconnect (a fresh session gets a fresh
    grace period). The monitor deliberately measures LOCAL receipt time
    only — no cross-host clock comparison."""

    def __init__(self, timeout_s: float = 2.0, on_loss=None,
                 interval_s: float | None = None):
        self.timeout_s = max(0.1, float(timeout_s))
        self.on_loss = on_loss
        self._interval = interval_s or min(0.25, self.timeout_s / 4)
        self._last = time.monotonic()
        self._fired = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.beats = 0
        self.losses = 0

    def note(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._fired = False
            self.beats += 1

    def reset(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._fired = False

    def age_s(self) -> float:
        with self._lock:
            return time.monotonic() - self._last

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ha-heartbeat")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            with self._lock:
                age = time.monotonic() - self._last
                lapsed = age > self.timeout_s and not self._fired
                if lapsed:
                    self._fired = True
                    self.losses += 1
            if lapsed and self.on_loss is not None:
                try:
                    self.on_loss(age)
                except Exception:  # noqa: BLE001 - callback never kills
                    pass


def probe_feed_hello(host: str, port: int,
                     timeout_s: float = 2.0) -> dict | None:
    """Connect to a witness feed just long enough to read its hello
    frame (which carries the sender's ``epoch``); None when the peer is
    unreachable or does not speak the feed protocol."""
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout_s) as sock:
            sock.settimeout(timeout_s)
            if _recv_exact(sock, len(FEED_MAGIC)) != FEED_MAGIC:
                return None
            hello = recv_frame(sock)
            if isinstance(hello, dict) and hello.get("type") == "hello":
                return hello
    except Exception:  # noqa: BLE001 - unreachable peer = no hello
        return None
    return None


def fencing_disabled(env=None) -> bool:
    env = os.environ if env is None else env
    return env.get("RETH_TPU_FAULT_HA_NO_FENCE", "") not in ("", "0")


def fence_check(own_epoch: int, peers, timeout_s: float = 2.0) -> dict:
    """Probe each ``(host, port)`` feed in ``peers``; fenced when any
    live peer advertises ``epoch > own_epoch``. Returns a report dict —
    the caller (node startup) decides what fencing means (refusing
    stale writes), this only establishes the fact."""
    report = {"fenced": False, "own_epoch": int(own_epoch),
              "peer_epoch": None, "peer": None, "probed": 0,
              "disabled": fencing_disabled()}
    for host, port in peers or ():
        hello = probe_feed_hello(host, port, timeout_s=timeout_s)
        if hello is None:
            continue
        report["probed"] += 1
        peer_epoch = int(hello.get("epoch") or 0)
        if peer_epoch > report["own_epoch"] and \
                (report["peer_epoch"] is None
                 or peer_epoch > report["peer_epoch"]):
            report["peer_epoch"] = peer_epoch
            report["peer"] = f"{host}:{port}"
            if not report["disabled"]:
                report["fenced"] = True
    return report
