"""The hot-standby role: WAL-shipped replication + failover promotion.

A standby is a full node in waiting. It subscribes to the leader's
witness feed socket with ``subscribe_wal`` and continuously replays the
``RTST1`` record stream (fleet/feed.py) into its OWN MemDb + WAL
(storage/wal.py) — every shipped record is re-appended locally with the
same fsync + torn-tail discipline the leader used, so the standby's
datadir is at all times a valid crash-recoverable datadir. Wire records
are vetted exactly like on-disk replay: the raw payload bytes must
match their shipped crc32 (torn/corrupt → rejected), the epoch must not
be stale, and the ``(gen, seq)`` position must continue the stream —
a gap or an out-of-order generation re-anchors via an upstream
``resync_request`` (the leader answers with a full consistent table
image, ordered in-stream).

Promotion (``following → catching-up → promoting → leading``,
fleet/election.py) triggers on leader heartbeat loss over the feed
socket or an explicit ``fleet_promote`` admin RPC:

1. **catching-up** — the feed client stops; the durable tail is already
   applied (application is synchronous with receipt).
2. **promoting** — the leader epoch is bumped (``old + 1``), stamped
   into every store, and checkpointed into the WAL manifest (the
   fencing token a restarted old leader will find itself behind). Then
   a full :class:`~reth_tpu.node.node.Node` is constructed over the
   standby's datadir — the standard crash-recovery startup
   (storage/recovery.py) replays the tail and **verifies the recovered
   head state root by recomputation** before anything serves.
3. **leading** — the node's RPC + witness feed start on the takeover
   ports; replicas reconnect via their failover endpoint, see the
   bumped epoch + the new leader's ``rpc_port`` in the hello, and
   re-register with the promoted node's gateway ring.

Fault injection (:class:`StandbyFaultInjector`):
``RETH_TPU_FAULT_STANDBY_LAG=<seconds>`` delays each shipped record (a
standby that falls progressively behind — the replay-lag SLO's drill);
``RETH_TPU_FAULT_STANDBY_WEDGE[=N]`` freezes replication from the Nth
record (heartbeats still count — a live but stuck standby).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
import zlib
from dataclasses import replace
from pathlib import Path

from .. import tracing
from ..rpc.server import RpcServer
from ..storage.kv import MemDb
from ..storage.wal import WalStore, _apply_delta
from .election import HeartbeatMonitor, PromotionStateMachine
from .feed import WitnessFeedClient


class StandbyFaultInjector:
    """Replication fault policies beside the replica's: ``wedge`` drops
    every shipped record from the ``wedge_after``-th onward (the
    standby keeps heartbeating but its replay lag grows unbounded),
    ``lag_s`` sleeps before each one."""

    def __init__(self, wedge: bool = False, lag_s: float = 0.0,
                 wedge_after: int = 1):
        self.wedge = wedge
        self.wedge_after = max(1, wedge_after)
        self.lag_s = lag_s
        self.seen = 0
        self.dropped = 0
        self.lagged = 0

    @classmethod
    def from_env(cls, env=None) -> "StandbyFaultInjector | None":
        env = os.environ if env is None else env
        wedge_raw = env.get("RETH_TPU_FAULT_STANDBY_WEDGE", "")
        wedge = wedge_raw not in ("", "0")
        wedge_after = int(wedge_raw) if wedge_raw.isdigit() and wedge else 1
        lag = float(env.get("RETH_TPU_FAULT_STANDBY_LAG", "0") or 0)
        if not (wedge or lag):
            return None
        return cls(wedge=wedge, lag_s=lag, wedge_after=wedge_after)

    @property
    def wedging(self) -> bool:
        return self.wedge and self.seen + 1 >= self.wedge_after

    def on_record(self, kind: str) -> bool:
        """Called per RTST1 record; True = drop it (wedge drill)."""
        if self.lag_s:
            self.lagged += 1
            tracing.fault_event("RETH_TPU_FAULT_STANDBY_LAG",
                                target="fleet::standby", kind=kind,
                                lag_s=self.lag_s)
            time.sleep(self.lag_s)
        self.seen += 1
        if self.wedge and self.seen >= self.wedge_after:
            self.dropped += 1
            tracing.fault_event("RETH_TPU_FAULT_STANDBY_WEDGE",
                                target="fleet::standby", kind=kind)
            return True
        return False


class StandbyAdminApi:
    """The standby's admin surface: ``fleet_standbyStatus`` (the probe
    the chaos drills and the HA bench poll) and ``fleet_promote`` (the
    explicit failover trigger). Both ride the gateway's ENGINE
    admission class when routed through a leader gateway — promotion
    must never queue behind a debug trace."""

    def __init__(self, standby: "StandbyNode"):
        self.s = standby

    def fleet_standbyStatus(self):
        return self.s.status()

    def fleet_promote(self):
        self.s.promote("fleet_promote rpc")
        return self.s.status()


class _StandbyStore:
    """One replicated store: the standby's own MemDb + WalStore pair
    (index 0 = main, 1 = the storage-v2 aux), plus the LEADER-side
    stream position used for continuity checks."""

    def __init__(self, db: MemDb, wal: WalStore):
        self.db = db
        self.wal = wal
        self.pos: tuple[int, int] | None = None  # leader (gen, seq)
        self.owned: set = set()  # tables cloned since the last image
        self.awaiting_resync = True


class StandbyNode:
    """A WAL-fed hot standby with a promotion state machine."""

    def __init__(self, feed_host: str, feed_port: int, *,
                 datadir: str | Path, standby_id: str | None = None,
                 http_port: int = 0, takeover_feed_port: int = 0,
                 auto_promote: bool = True,
                 heartbeat_timeout_s: float = 2.0,
                 injector: StandbyFaultInjector | None = None,
                 promote_config=None, registry=None):
        from ..metrics import StandbyMetrics

        self.standby_id = standby_id or f"standby-{os.getpid()}"
        self.datadir = Path(datadir)
        self.datadir.mkdir(parents=True, exist_ok=True)
        self.takeover_feed_port = takeover_feed_port
        self.auto_promote = auto_promote
        self.promote_config = promote_config
        self.lock = threading.RLock()
        self.started_at = time.time()
        self.injector = (injector if injector is not None
                         else StandbyFaultInjector.from_env())
        self.metrics = StandbyMetrics(registry)
        # store 0 opens eagerly (replays any prior standby session —
        # the standby's datadir is always crash-recoverable); the aux
        # store materializes on the first store=1 record
        self.stores: dict[int, _StandbyStore] = {0: self._open_store(0)}
        self.leader_epoch = self.stores[0].wal.epoch
        self.leader_head: tuple[int, bytes] | None = None   # heartbeat
        self.applied_head: tuple[int, bytes] | None = None  # last st_fcu
        self.persisted_head: tuple[int, str] | None = None  # st_manifest
        # counters — the wire-vetting ledger (satellite: wire corruption
        # handled exactly like on-disk replay)
        self.records_applied = 0
        self.records_duplicate = 0
        self.crc_rejected = 0
        self.stale_epoch_rejected = 0
        self.gen_rejected = 0
        self.gap_detected = 0
        self.resyncs_requested = 0
        self.resyncs_applied = 0
        self.manifests_applied = 0
        self.promote_ms: float | None = None
        self.promote_error: str | None = None
        self.node = None  # the promoted full Node, once leading
        self.node_ports: tuple[int, int] | None = None
        self.promotion = PromotionStateMachine(
            on_transition=self._on_transition)
        self.monitor = HeartbeatMonitor(
            timeout_s=heartbeat_timeout_s, on_loss=self._on_heartbeat_loss)
        self.client = WitnessFeedClient(
            feed_host, feed_port,
            on_hello=self._on_hello, on_record=self._on_record)
        self.rpc = RpcServer(port=http_port, lock=self.lock)
        self.rpc.register(StandbyAdminApi(self))
        self.http_port: int | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        tracing.set_process_role("standby")
        self.http_port = self.rpc.start()
        self.monitor.start()
        self.client.start()
        return self.http_port

    def stop(self) -> None:
        self.monitor.stop()
        self.client.stop()
        self.rpc.stop()
        if self.node is not None:
            self.node.stop()
            self.node = None
        else:
            for st in self.stores.values():
                st.wal.close()

    def _open_store(self, idx: int) -> _StandbyStore:
        # layout mirrors the full node's (storage/__init__.py +
        # storage/wal.attach_wal): the promoted Node opens the SAME
        # files this standby wrote
        name = "db.bin" if idx == 0 else "db-aux.bin"
        wal_dir = self.datadir / ("wal" if idx == 0 else "wal-aux")
        db = MemDb(self.datadir / name)
        return _StandbyStore(db, WalStore.open(db, wal_dir))

    def _store(self, idx: int) -> _StandbyStore:
        st = self.stores.get(idx)
        if st is None:
            st = self.stores[idx] = self._open_store(idx)
        return st

    def _on_transition(self, state: str, why: str) -> None:
        tracing.event("fleet::standby", "promotion", state=state, why=why)
        self.metrics.set_state(state)

    # -- feed intake --------------------------------------------------------

    def _on_hello(self, hello: dict) -> None:
        ep = int(hello.get("epoch") or 0)
        with self.lock:
            if ep > self.leader_epoch:
                self.leader_epoch = ep
                self.metrics.set_epoch(ep)
        self.monitor.reset()
        # subscribe to the WAL stream; a tail-exact position skips the
        # image, anything else (first connect, restart, gap) resyncs
        frm = None
        with self.lock:
            if all(not st.awaiting_resync and st.pos is not None
                   for st in self.stores.values()):
                frm = {i: list(st.pos) for i, st in self.stores.items()}
            else:
                for st in self.stores.values():
                    st.awaiting_resync = True
        self.client.send({"type": "subscribe_wal", "from": frm})

    def _check_epoch(self, frame: dict) -> bool:
        """False = frame rejected. A STALE epoch is a fenced old leader
        still talking — refused like an on-disk stale-generation
        segment. A HIGHER epoch is a new leader lineage: adopt it and
        re-anchor from a fresh image."""
        ep = int(frame.get("epoch") or 0)
        with self.lock:
            if ep < self.leader_epoch:
                self.stale_epoch_rejected += 1
                self.metrics.record_rejected("stale_epoch")
                return False
            if ep > self.leader_epoch:
                self.leader_epoch = ep
                self.metrics.set_epoch(ep)
                self._request_resync()
                return False
        return True

    def _request_resync(self) -> None:
        for st in self.stores.values():
            st.awaiting_resync = True
        self.resyncs_requested += 1
        self.metrics.record_resync_request()
        self.client.send({"type": "resync_request"})

    def _on_record(self, frame: dict) -> None:
        if not isinstance(frame, dict):
            return
        kind = frame.get("type")
        if kind == "st_heartbeat":
            self.monitor.note()
            head = frame.get("head")
            if head is not None:
                with self.lock:
                    self.leader_head = (head[0], head[1])
                    self._update_lag()
            self._check_epoch(frame)
            return
        if kind not in ("st_wal", "st_manifest", "st_fcu", "st_resync"):
            return  # witness traffic / flight dumps: not ours
        if self.promotion.state != "following":
            return  # promotion in flight: the stream is closed
        if self.injector is not None and self.injector.on_record(kind):
            return  # wedged: frozen replication, lag grows
        if not self._check_epoch(frame):
            return
        if kind == "st_wal":
            self._on_wal(frame)
        elif kind == "st_manifest":
            self._on_manifest(frame)
        elif kind == "st_fcu":
            with self.lock:
                self.applied_head = (frame["number"], frame["hash"])
                self._update_lag()
        elif kind == "st_resync":
            self._on_resync(frame)

    def _on_wal(self, frame: dict) -> None:
        st = self._store(int(frame.get("store", 0)))
        payload = frame.get("payload")
        # the on-disk discipline, applied to the wire: a record is
        # usable iff its raw bytes verify against their crc32 — a torn
        # or bit-rotted payload is rejected, never applied
        if not isinstance(payload, (bytes, bytearray)) \
                or zlib.crc32(payload) != frame.get("crc"):
            self.crc_rejected += 1
            self.metrics.record_rejected("crc")
            if not st.awaiting_resync:
                self._request_resync()
            return
        gen, seq = int(frame.get("gen", 0)), int(frame.get("seq", 0))
        with self.lock:
            if st.awaiting_resync:
                return  # the in-stream image will anchor us
            pgen, pseq = st.pos
            if gen < pgen:
                # out-of-order generation: a record from BEFORE a
                # checkpoint the stream already crossed — the wire
                # analogue of a mis-renamed segment, refused the same way
                self.gen_rejected += 1
                self.metrics.record_rejected("generation")
                self._request_resync()
                return
            if seq <= pseq:
                self.records_duplicate += 1
                return
            if seq != pseq + 1:
                self.gap_detected += 1
                self.metrics.record_rejected("gap")
                self._request_resync()
                return
            try:
                rec = pickle.loads(bytes(payload))
            except Exception:  # noqa: BLE001 - undecodable = torn
                self.crc_rejected += 1
                self.metrics.record_rejected("crc")
                self._request_resync()
                return
            delta = rec.get("tables", {})

            def _publish():
                _apply_delta(st.db._tables, delta, st.owned)
                st.db._dirty = True

            # durable-tail discipline: the shipped delta is re-appended
            # to the standby's OWN WAL (fsync'd, same framing) before
            # the in-memory publish — a standby killed at any byte
            # boundary recovers to its last complete shipped commit
            st.wal.append(delta, publish=_publish)
            st.pos = (gen, seq)
            self.records_applied += 1
            self.metrics.record_applied()

    def _on_manifest(self, frame: dict) -> None:
        st = self._store(int(frame.get("store", 0)))
        manifest = frame.get("manifest") or {}
        with self.lock:
            if st.awaiting_resync:
                return
            head = None
            if manifest.get("head_number") is not None \
                    and manifest.get("head_hash"):
                head = (manifest["head_number"], manifest["head_hash"])
                if int(frame.get("store", 0)) == 0:
                    self.persisted_head = head
            # checkpoint the standby's own WAL at the leader's boundary
            # (image + manifest swap + log truncation), then track the
            # leader's new generation for continuity
            st.wal.checkpoint(head=head)
            if st.pos is not None:
                st.pos = (max(st.pos[0], int(manifest.get("gen", 0))),
                          st.pos[1])
            self.manifests_applied += 1

    def _on_resync(self, frame: dict) -> None:
        st = self._store(int(frame.get("store", 0)))
        tables = frame.get("tables")
        if not isinstance(tables, dict):
            return
        with self.lock:
            # absolute-image re-anchor: replace the whole table map,
            # then checkpoint so the image is durable immediately —
            # exactly the quarantine-then-checkpoint shape of on-disk
            # replay after mid-log corruption
            st.db._tables = {k: dict(v) for k, v in tables.items()}
            st.db._dirty = True
            st.owned = set(st.db._tables)
            st.pos = (int(frame.get("gen", 1)), int(frame.get("seq", 0)))
            st.awaiting_resync = False
            head = frame.get("head")
            if head is not None and int(frame.get("store", 0)) == 0:
                self.applied_head = (head[0], head[1])
            st.wal.checkpoint(head=tuple(head) if head else None)
            self.resyncs_applied += 1
            self.metrics.record_resync_applied()
            self._update_lag()

    def _update_lag(self) -> None:
        self.metrics.set_lag(self.lag_heads())

    def lag_heads(self) -> int:
        if self.leader_head is None:
            return 0
        applied = self.applied_head[0] if self.applied_head else 0
        return max(0, self.leader_head[0] - applied)

    # -- promotion ----------------------------------------------------------

    def _on_heartbeat_loss(self, age_s: float) -> None:
        if self.monitor.beats == 0 and self.resyncs_applied == 0:
            # never saw a leader at all (started first / leader still
            # booting): nothing to promote over — keep waiting
            self.monitor.reset()
            return
        tracing.event("fleet::standby", "heartbeat_loss", age_s=age_s)
        if self.auto_promote:
            threading.Thread(
                target=self.promote,
                args=(f"heartbeat loss ({age_s:.2f}s)",),
                daemon=True, name="ha-promote").start()

    def promote(self, why: str = "manual") -> bool:
        """Run the promotion ladder to ``leading``; idempotent — a
        second trigger (heartbeat loss racing fleet_promote) returns
        once the first finishes. False when promotion failed (root
        verification) or was never applicable."""
        if not self.promotion.advance("catching-up", why):
            # already past following: wait for the in-flight promotion
            deadline = time.time() + 60
            while time.time() < deadline and self.promotion.state in (
                    "catching-up", "promoting"):
                time.sleep(0.05)
            return self.promotion.is_leading()
        t0 = time.monotonic()
        # catching-up: stop the stream — application is synchronous
        # with receipt, so once the client thread exits, the durable
        # tail IS fully applied
        self.monitor.stop()
        self.client.stop()
        self.promotion.advance("promoting", "durable tail applied")
        with self.lock:
            new_epoch = self.leader_epoch + 1
            head = self.applied_head
            for st in self.stores.values():
                # the fencing token: the bumped epoch lands in every
                # store's manifest BEFORE anything serves
                st.wal.epoch = new_epoch
                st.wal.checkpoint(
                    head=head if st is self.stores[0] else None)
                st.wal.close()
            for st in self.stores.values():
                st.db._wal = None
        try:
            node, ports = self._launch_node()
        except Exception as e:  # noqa: BLE001 - surfaced, state = failed
            self.promote_error = f"{type(e).__name__}: {e}"
            self.promotion.advance("failed", self.promote_error)
            self.metrics.record_promotion(failed=True)
            return False
        recovery = node.recovery or {}
        if recovery.get("status") == "failed" or \
                (recovery.get("root_verified") is False):
            self.promote_error = (
                f"recovered head root failed verification: "
                f"{recovery.get('problems')}")
            node.stop()
            self.promotion.advance("failed", self.promote_error)
            self.metrics.record_promotion(failed=True)
            return False
        self.node = node
        self.node_ports = ports
        self.leader_epoch = new_epoch
        self.metrics.set_epoch(new_epoch)
        self.promote_ms = (time.monotonic() - t0) * 1000.0
        self.metrics.record_promotion(wall_s=self.promote_ms / 1000.0)
        self.promotion.advance(
            "leading", f"feed serving on :{node.feed_server.port}")
        return True

    def _launch_node(self):
        """Construct the full Node over the standby's datadir: the
        standard crash-recovery startup replays the durable tail and
        verifies the recovered head root by recomputation — promotion
        reuses the read-only verify path wholesale."""
        from ..node.node import Node, NodeConfig

        cfg = self.promote_config or NodeConfig()
        cfg = replace(
            cfg, datadir=str(self.datadir), db_backend="memdb",
            dev=True, wal=True, fleet=True, rpc_gateway=True,
            recovery_verify_root=True, feed_port=self.takeover_feed_port,
            http_port=0, authrpc_port=0, genesis_header=None,
            genesis_alloc={}, genesis_storage=None, genesis_codes=None)
        node = Node(cfg)
        ports = node.start_rpc()
        return node, ports

    # -- observability ------------------------------------------------------

    def wait_state(self, state: str, timeout: float = 30.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.promotion.state == state:
                return True
            time.sleep(0.02)
        return False

    def status(self) -> dict:
        with self.lock:
            node = self.node
            return {
                "id": self.standby_id,
                "pid": os.getpid(),
                "state": self.promotion.state,
                "leader_epoch": self.leader_epoch,
                "connected": self.client.connected.is_set(),
                "applied_head": ({"number": self.applied_head[0],
                                  "hash": self.applied_head[1].hex()
                                  if isinstance(self.applied_head[1], bytes)
                                  else self.applied_head[1]}
                                 if self.applied_head else None),
                "leader_head": ({"number": self.leader_head[0]}
                                if self.leader_head else None),
                "lag_heads": self.lag_heads(),
                "records_applied": self.records_applied,
                "records_duplicate": self.records_duplicate,
                "crc_rejected": self.crc_rejected,
                "stale_epoch_rejected": self.stale_epoch_rejected,
                "gen_rejected": self.gen_rejected,
                "gap_detected": self.gap_detected,
                "resyncs_requested": self.resyncs_requested,
                "resyncs_applied": self.resyncs_applied,
                "manifests_applied": self.manifests_applied,
                "awaiting_resync": any(st.awaiting_resync
                                       for st in self.stores.values()),
                "stores": len(self.stores),
                "wedged": bool(self.injector is not None
                               and self.injector.wedging),
                "promote_ms": self.promote_ms,
                "promote_error": self.promote_error,
                "history": self.promotion.snapshot()["history"],
                "node": ({"http_port": self.node_ports[0],
                          "authrpc_port": self.node_ports[1],
                          "feed_port": node.feed_server.port,
                          "epoch": node.feed_server.epoch,
                          "recovery": {
                              "status": (node.recovery or {}).get("status"),
                              "root_verified": (node.recovery or {}).get(
                                  "root_verified"),
                              "head_number": (node.recovery or {}).get(
                                  "head_number")}}
                         if node is not None else None),
                "uptime_s": round(time.time() - self.started_at, 1),
            }
