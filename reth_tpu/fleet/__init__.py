"""Stateless read-replica fleet: witness-fed replica nodes behind a
consistent-hash gateway ring.

Reference analogue: reth's layer map splits serving from consensus —
RPC reads should not compete with block import for the one full node's
lock. This package assembles the substrate PRs 6–12 built
(`engine/witness.py` closed witnesses, `engine/stateless.py`
StatelessChain validation, the `rpc/gateway.py` admission/coalescing/
caching front door, the health engine) into a genuinely new role:

- :mod:`.feed` — the witness feed protocol: the full node streams
  per-block ``ExecutionWitness`` + header announcements to subscribed
  replicas over a length-prefixed CRC-framed socket protocol (the WAL's
  record shape, storage/wal.py).
- :mod:`.replica` — the stateless replica role: a process with NO
  database that validates every fed block through ``StatelessChain``
  (preserved sparse trie carried block-to-block) and serves
  ``eth_call``/``eth_estimateGas``/``eth_getProof``/``eth_getLogs``/
  ``eth_getBlockBy*`` from witness-backed state.
- :mod:`.ring` — the fleet side of the gateway: a consistent-hash ring
  over registered replicas keyed by the gateway's
  ``(method, canonical params, head_hash)`` cache key, health-probed
  per-replica draining, and failover replica → ring neighbor → the
  local full node.
- :mod:`.standby` — the WAL-shipped hot standby role: a full node's
  durable stream (``RTST1`` records over the same feed framing) replayed
  continuously into a second datadir, with heartbeat-loss / RPC-driven
  promotion to leader (:mod:`.election` holds the state machine and the
  epoch fencing probe).

``python -m reth_tpu.fleet replica --feed HOST:PORT`` runs a replica and
``python -m reth_tpu.fleet standby --feed HOST:PORT --datadir DIR`` a hot
standby (the ``--role replica`` / ``--role standby`` CLI entries delegate
here).
"""

from .election import (HeartbeatMonitor, PromotionStateMachine, fence_check,
                       probe_feed_hello)
from .feed import FeedError, WitnessFeedClient, WitnessFeedServer
from .replica import ReplicaFaultInjector, ReplicaNode
from .ring import FleetRouter, HashRing, ReplicaHandle
from .standby import StandbyAdminApi, StandbyFaultInjector, StandbyNode

__all__ = [
    "FeedError",
    "FleetRouter",
    "HashRing",
    "HeartbeatMonitor",
    "PromotionStateMachine",
    "ReplicaFaultInjector",
    "ReplicaHandle",
    "ReplicaNode",
    "StandbyAdminApi",
    "StandbyFaultInjector",
    "StandbyNode",
    "WitnessFeedClient",
    "WitnessFeedServer",
    "fence_check",
    "probe_feed_hello",
]
