"""Witness feed: the full node streams per-block execution witnesses +
head announcements to subscribed replicas.

Reference analogue: reth's layer map serves `debug_executionWitness` on
demand; a replica fleet needs the PUSH form — every canonical block's
witness generated once at the source and fanned out, so N replicas cost
one witness generation, not N RPC round-trips that each re-execute the
block.

Wire format: a TCP stream opening with the ``RTFD1\\n`` magic, then
length-prefixed CRC-checked frames — the WAL's record shape
(storage/wal.py)::

    u32 payload_len | u32 crc32(payload) | payload (pickle)

Frames:

- ``{"type": "hello", "chain_id", "head": (number, hash), "spec": json}``
  — first frame after the magic; anchors the subscriber.
- ``{"type": "block", "number", "hash", "parent", "block_rlp",
  "senders", "witness": {state, codes, keys, headers}}`` — one
  self-contained stateless validation input per canonical block: the
  witness is closed under the block's own trie edits
  (engine/witness.py), so the replica can anchor on the parent header
  it ships and replay with no state source.
- ``{"type": "head", "number", "hash"}`` — head announcement (fanout
  invalidation: replicas and the gateway ring key responses by head
  hash, so a new head retires every cached read).
- ``{"type": "flight_dump", "correlation_id", "reason", "window"}`` —
  correlated flight-recorder fan-out: any process's fault event or SLO
  breach stamps a correlation id + time window and this frame carries
  the dump request across the fleet. The server broadcasts it to every
  replica; replicas send it UPSTREAM on the same socket (the feed is
  the fleet's one standing channel), and the server re-fans it to the
  others — every process dumps under the SAME correlation id, deduped
  by a bounded seen-set so fan-out cannot loop.

``RTST1`` record family (leader → standby WAL shipping, fleet/standby.py):
rides the same framing, delivered only to subscribers that sent
``{"type": "subscribe_wal", "from": {store: [gen, seq]} | None}``
upstream:

- ``{"type": "st_wal", "st": "RTST1", "epoch", "store", "gen", "seq",
  "payload", "crc"}`` — one durable WAL record, shipped post-fsync with
  the RAW on-disk payload bytes + their crc32, so the standby applies
  the exact torn/CRC discipline of ``storage/wal.py`` replay to the
  wire stream.
- ``{"type": "st_manifest", "st": "RTST1", "epoch", "store",
  "manifest"}`` — the leader checkpointed; the standby checkpoints its
  own WAL at the same boundary.
- ``{"type": "st_fcu", "st": "RTST1", "epoch", "number", "hash"}`` —
  fork-choice forwarding: the leader's canonical head, the standby's
  lag anchor and recovered-head target.
- ``{"type": "st_heartbeat", "st": "RTST1", "epoch", "head"}`` —
  leader liveness at a fixed cadence; the standby's promotion trigger
  is this beat going silent (election.HeartbeatMonitor).
- ``{"type": "st_resync", "st": "RTST1", "epoch", "store", "tables",
  "gen", "seq", "head"}`` — a full consistent table image (records
  carry absolute values, so replacing the standby's state with the
  image and continuing from ``(gen, seq)`` converges exactly); sent
  when a subscriber's ``from`` position cannot be continued, or on an
  upstream ``{"type": "resync_request"}``.

``RTPT1`` record family (pool propagation, pool/pool.py listeners):
rides the same framing, delivered only to subscribers that sent
``{"type": "subscribe_pool"}`` upstream. Every record carries the
pool's monotonic ``seq``; a subscriber that observes a gap (ship-queue
drop-oldest fired) re-subscribes and gets a fresh snapshot:

- ``{"type": "pt_snapshot", "pt": "RTPT1", "seq", "base_fee",
  "blob_base_fee", "txs": [(tx_rlp, sender), ...]}`` — the full
  pending set at subscribe time; anchors the replica's pending view.
- ``{"type": "pt_add", "pt": "RTPT1", "seq", "tx": tx_rlp, "hash",
  "sender", "nonce"}`` — one admission.
- ``{"type": "pt_replace", ... , "old_hash"}`` — a same-nonce
  replacement that out-bid the incumbent.
- ``{"type": "pt_drop", "pt": "RTPT1", "seq", "hash", "sender",
  "reason": mined|invalid|evicted|underfunded}`` — one eviction.
- ``{"type": "pt_canon", "pt": "RTPT1", "seq", "base_fee",
  "blob_base_fee"}`` — fee markets moved with the head.

This is what lets replicas answer ``eth_getTransactionByHash``,
pending-tag nonces, and ``txpool_*`` for UNMINED txs instead of
``-32001``: the write population's reads stay on the fleet.

Every hello additionally carries ``epoch`` (the sender's monotonic
leader epoch, persisted in the WAL manifest) and ``rpc_port`` — the
fencing handshake: a restarted old leader probing a live peer whose
epoch is higher knows it was superseded and must not serve writes.

Block records additionally carry a ``"tp"`` member — the wire form of
the block's trace context (:func:`reth_tpu.tracing.context_to_wire`,
trace id = block hash hex, parent = the ``witness.generate`` span) — so
a replica's ``stateless.validate`` span stitches into the SAME trace as
the full node's block lifecycle, cross-process.

The server generates witnesses on a dedicated worker thread fed by a
bounded queue from the engine tree's canon listeners — witness
generation re-executes the block, and that cost must never land on the
consensus path. A full queue drops the oldest pending block (counted):
every block record is self-contained, so a replica simply re-anchors on
the next record's parent instead of desyncing.
"""

from __future__ import annotations

import os
import pickle
import queue
import random
import socket
import struct
import threading
import time
import zlib
from collections import OrderedDict, deque

from .. import tracing

FEED_MAGIC = b"RTFD1\n"
ST_MAGIC = "RTST1"  # the WAL-shipping record family tag
PT_MAGIC = "RTPT1"  # the pool-propagation record family tag
_HDR = struct.Struct("<II")
MAX_FRAME = 256 * 1024 * 1024  # sanity bound: no witness comes close


class FeedError(Exception):
    """Broken framing (torn frame, CRC mismatch, oversized payload)."""


def send_frame(sock: socket.socket, obj) -> int:
    """Write one CRC-framed pickled frame; returns bytes sent."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
    sock.sendall(frame)
    return len(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("feed closed mid-frame"
                                  if buf else "feed closed")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket):
    """Read one frame; raises FeedError on torn/corrupt framing and
    ConnectionError on a clean close."""
    hdr = _recv_exact(sock, _HDR.size)
    length, crc = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise FeedError(f"frame length {length} exceeds bound")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise FeedError("frame CRC mismatch")
    try:
        return pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — corrupt payload = torn frame
        raise FeedError(f"undecodable frame: {type(e).__name__}: {e}") from e


class _Subscriber:
    __slots__ = ("sock", "lock", "addr", "wal", "pool")

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.lock = threading.Lock()  # one frame at a time per socket
        self.addr = addr
        self.wal = False   # True once the peer sent subscribe_wal
        self.pool = False  # True once the peer sent subscribe_pool


class WitnessFeedServer:
    """Per-block witness generation + fanout for a full node.

    ``on_canon_change`` installs as an engine-tree canon listener: it
    only enqueues (bounded, drop-oldest) — generation and broadcast run
    on this server's worker thread. ``tree`` supplies overlay views and
    the committer; ``chain_spec`` rides the hello frame so replicas
    execute under the same fork schedule.
    """

    def __init__(self, tree, *, chain_id: int = 1, chain_spec=None,
                 host: str = "127.0.0.1", port: int = 0,
                 backlog: int = 64, queue_cap: int = 32, registry=None):
        self.tree = tree
        self.chain_id = chain_id
        self.chain_spec = chain_spec
        self.host = host
        self.port = port
        self.backlog_cap = backlog
        self._queue: queue.Queue = queue.Queue(maxsize=max(2, queue_cap))
        self._backlog: list[dict] = []  # last N block records, for catch-up
        self._subs: list[_Subscriber] = []
        self._lock = threading.Lock()
        self._srv: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self.head: tuple[int, bytes] | None = None
        # canon notifications overlap (each carries the whole in-memory
        # chain segment): dedupe by hash so every block feeds exactly once
        self._seen: "OrderedDict[bytes, bool]" = OrderedDict()
        # correlated flight dumps seen (bounded): fan-out dedupe so a
        # replica-initiated dump re-fanned to the fleet cannot loop
        self._corr_seen: "OrderedDict[str, bool]" = OrderedDict()
        self.flight_requests = 0
        self.flight_fanouts = 0
        # counters surfaced via snapshot() + fleet_* metrics
        self.blocks_sent = 0
        self.heads_sent = 0
        self.witness_failures = 0
        self.dropped_blocks = 0
        self.last_witness_bytes = 0
        self.total_witness_bytes = 0
        # -- HA / WAL shipping (RTST1 family, fleet/standby.py) -----------
        # monotonic leader epoch: set from the WAL manifest by
        # attach_durability; rides every hello (the fencing handshake)
        self.epoch = 1
        # this node's public RPC port (hello field): a re-anchoring
        # replica registers with the promoted leader's gateway here
        self.rpc_port: int | None = None
        self._durability = None
        # shipped records queue, drained by the feed-ship thread so a
        # slow/wedged standby socket can never stall the append path;
        # items: ("rec", frame) | ("resync", subscriber)
        self._st_queue: deque = deque()
        self._st_cond = threading.Condition()
        self._st_cap = int(os.environ.get("RETH_TPU_HA_SHIP_QUEUE", 4096))
        self.heartbeat_s = float(
            os.environ.get("RETH_TPU_HA_HEARTBEAT_S", "0.25"))
        self.st_records_sent = 0
        self.st_manifests_sent = 0
        self.st_fcu_sent = 0
        self.st_dropped = 0
        self.heartbeats_sent = 0
        self.resyncs_sent = 0
        # -- pool propagation (RTPT1 family, pool/pool.py listeners) ------
        self._pool = None
        self.pt_records_sent = 0
        self.pt_dropped = 0
        self.pt_snapshots_sent = 0
        # RETH_TPU_FAULT_LEADER_PARTITION=<dur_s>[:<start_s>] — suppress
        # every RTST1 frame (records AND heartbeats) for dur_s starting
        # start_s (default 1.0) after the server starts: the network
        # partition the standby must survive via gap-detect + resync
        self._partition: tuple[float, float] | None = None
        self.partition_suppressed = 0
        raw = os.environ.get("RETH_TPU_FAULT_LEADER_PARTITION", "")
        if raw not in ("", "0"):
            dur, _, start = raw.partition(":")
            try:
                self._partition = (float(start or 1.0),
                                   float(start or 1.0) + float(dur))
            except ValueError:
                self._partition = None
        self._started_at = time.monotonic()
        from ..metrics import FleetMetrics

        self.metrics = FleetMetrics(registry)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, self.port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._started_at = time.monotonic()
        for name, fn in (("feed-accept", self._accept_loop),
                         ("feed-worker", self._worker),
                         ("feed-ship", self._ship_loop)):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        self._queue.put(None)  # wake the worker
        with self._st_cond:
            self._st_cond.notify_all()  # wake the ship loop
        with self._lock:
            subs, self._subs = self._subs, []
        for s in subs:
            try:
                s.sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)

    # -- intake (engine canon listener) -------------------------------------

    def on_canon_change(self, chain) -> None:
        """Bounded enqueue of newly-canonical executed blocks; never
        blocks the consensus path."""
        if not chain:
            return
        tip = chain[-1]
        self.head = (tip.number, tip.hash)
        for eb in chain:
            if eb.hash in self._seen:
                continue
            self._seen[eb.hash] = True
            while len(self._seen) > 4 * self.backlog_cap:
                self._seen.popitem(last=False)
            try:
                self._queue.put_nowait(eb)
            except queue.Full:
                # drop the OLDEST pending block: records are
                # self-contained, replicas re-anchor on the next one
                try:
                    self._queue.get_nowait()
                    self.dropped_blocks += 1
                    self.metrics.record_feed_drop()
                except queue.Empty:
                    pass
                try:
                    self._queue.put_nowait(eb)
                except queue.Full:
                    self.dropped_blocks += 1
                    self.metrics.record_feed_drop()

    # -- worker -------------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            eb = self._queue.get()
            if eb is None or self._stop.is_set():
                return
            try:
                record = self._build_record(eb)
            except Exception as e:  # noqa: BLE001 — skip, surfaced below
                self.witness_failures += 1
                self.metrics.record_witness_failure()
                tracing.event("fleet::feed", "witness_failed",
                              number=eb.number, error=f"{type(e).__name__}: {e}")
                record = None
            if record is not None:
                with self._lock:
                    self._backlog.append(record)
                    del self._backlog[:-self.backlog_cap]
                self._broadcast(record)
                self.blocks_sent += 1
            # head announcement after the newest queued block drains:
            # the fanout-invalidation signal even when a witness failed
            if self._queue.empty() and self.head is not None:
                self._broadcast({"type": "head", "number": self.head[0],
                                 "hash": self.head[1]})
                self.heads_sent += 1

    def _build_record(self, eb) -> dict:
        from ..engine.witness import generate_witness

        header = eb.block.header
        parent_hash = header.parent_hash
        provider = self.tree.overlay_provider(parent_hash)
        parent_header = provider.header_by_number(header.number - 1)
        hashes = {}
        for k in range(max(0, header.number - 256), header.number):
            bh = provider.canonical_hash(k)
            if bh:
                hashes[k] = bh
        # witness generation joins the block's lifecycle trace (trace id
        # = block hash hex, the engine's trace_block convention) so the
        # record's wire-form context stitches the replica's validation
        # spans into the SAME trace cross-process
        with tracing.use_context(
                tracing.TraceContext(header.hash.hex(), None)):
            with tracing.span("fleet::feed", "witness.generate",
                              number=header.number) as wctx:
                w = generate_witness(
                    provider, eb.block, self.tree.committer,
                    list(eb.senders), parent_header, self.tree.config,
                    block_hashes=hashes)
            # only when span recording is on: untraced feeds carry zero
            # extra bytes per record
            traceparent = (tracing.context_to_wire(wctx)
                           if wctx is not None else None)
        record = {
            "type": "block",
            "number": header.number,
            "hash": header.hash,
            "parent": parent_hash,
            "block_rlp": eb.block.encode(),
            "senders": list(eb.senders),
            "witness": {"state": w.state, "codes": w.codes,
                        "keys": w.keys, "headers": w.headers},
        }
        if traceparent is not None:
            record["tp"] = traceparent
        size = (sum(map(len, w.state)) + sum(map(len, w.codes))
                + sum(map(len, w.headers)) + len(record["block_rlp"]))
        self.last_witness_bytes = size
        self.total_witness_bytes += size
        self.metrics.record_witness(size)
        return record

    def _broadcast(self, record: dict, exclude=None) -> None:
        # witness traffic (block/head) skips WAL subscribers: the
        # standby replicates state from RTST1 records, not witnesses —
        # shipping both would double its ingest for nothing
        skip_wal = record.get("type") in ("block", "head")
        with self._lock:
            subs = list(self._subs)
        for s in subs:
            if s is exclude or (skip_wal and s.wal):
                continue
            try:
                with s.lock:
                    send_frame(s.sock, record)
            except OSError:
                self._drop(s)

    # -- WAL shipping (RTST1, the HA standby's replication stream) ----------

    def attach_durability(self, durability) -> None:
        """Hook the node's DurabilityManager: every post-fsync append
        and checkpoint manifest lands on the ship queue; the manifest's
        persisted leader epoch becomes this feed's advertised epoch."""
        self._durability = durability
        self.epoch = durability.epoch
        durability.attach_shipper(self._ship_record, self._ship_manifest)

    def _partition_active(self) -> bool:
        if self._partition is None:
            return False
        now = time.monotonic() - self._started_at
        active = self._partition[0] <= now < self._partition[1]
        if active:
            tracing.fault_event("RETH_TPU_FAULT_LEADER_PARTITION",
                                target="fleet::feed")
        return active

    def _st_enqueue(self, item) -> None:
        with self._st_cond:
            while len(self._st_queue) >= self._st_cap:
                # drop the OLDEST shipped record: a standby detects the
                # seq gap and re-anchors via resync; a pool subscriber
                # detects its pt seq gap and re-subscribes for a snapshot
                dropped = self._st_queue.popleft()
                if dropped[0] in ("pool", "pool_snapshot"):
                    self.pt_dropped += 1
                    try:
                        from ..metrics import pool_metrics

                        pool_metrics.record_feed_drop()
                    except Exception:  # noqa: BLE001
                        pass
                else:
                    self.st_dropped += 1
            self._st_queue.append(item)
            self._st_cond.notify()

    def _ship_record(self, store: int, gen: int, seq: int,
                     payload: bytes) -> None:
        """DurabilityManager append observer: runs under the WAL append
        lock, so it only enqueues — the ship thread does the socket
        work."""
        self._st_enqueue(("rec", {
            "type": "st_wal", "st": ST_MAGIC, "epoch": self.epoch,
            "store": store, "gen": gen, "seq": seq,
            "payload": payload, "crc": zlib.crc32(payload)}))

    def _ship_manifest(self, store: int, manifest: dict) -> None:
        self._st_enqueue(("rec", {
            "type": "st_manifest", "st": ST_MAGIC, "epoch": self.epoch,
            "store": store, "manifest": manifest}))

    def ship_fcu(self, number: int, head_hash: bytes) -> None:
        """Fork-choice forwarding (engine canon listener): the leader's
        canonical head, the standby's lag anchor."""
        self._st_enqueue(("rec", {
            "type": "st_fcu", "st": ST_MAGIC, "epoch": self.epoch,
            "number": number, "hash": head_hash}))

    # -- pool propagation (RTPT1, the replicas' pending view) ---------------

    def attach_pool(self, pool) -> None:
        """Hook the node's TransactionPool: every pool event (admission /
        replacement / drop / canon) ships as a ``pt_*`` record to pool
        subscribers. The listener runs under the pool lock, so it only
        encodes and enqueues — the ship thread does the socket work."""
        self._pool = pool
        pool.add_listener(self._pool_event)

    def _pool_event(self, ev: dict) -> None:
        kind = ev.get("kind")
        rec = {"pt": PT_MAGIC, "seq": ev["seq"]}
        if kind in ("add", "replace"):
            tx = ev["tx"]
            rec.update(type=f"pt_{kind}", tx=tx.encode(), hash=tx.hash,
                       sender=ev.get("sender"), nonce=tx.nonce)
            if kind == "replace":
                rec["old_hash"] = ev.get("old_hash")
        elif kind == "drop":
            rec.update(type="pt_drop", hash=ev.get("hash"),
                       sender=ev.get("sender"), reason=ev.get("reason"))
        elif kind == "canon":
            rec.update(type="pt_canon", base_fee=ev.get("base_fee"),
                       blob_base_fee=ev.get("blob_base_fee"))
        else:
            return
        self._st_enqueue(("pool", rec))

    def _broadcast_pool(self, record: dict) -> None:
        with self._lock:
            subs = [s for s in self._subs if s.pool]
        if not subs:
            return
        for s in subs:
            try:
                with s.lock:
                    send_frame(s.sock, record)
            except OSError:
                self._drop(s)
        try:
            from ..metrics import pool_metrics

            pool_metrics.record_shipped(len(subs))
        except Exception:  # noqa: BLE001
            pass

    def _send_pool_snapshot(self, sub: _Subscriber) -> None:
        """Full pending set for one subscriber, sent from the ship
        thread so it lands IN ORDER with the pt_* stream: every queued
        record before it carries seq <= the snapshot's, every one after
        continues from it (same discipline as st_resync)."""
        pool = self._pool
        if pool is None:
            return
        with pool._lock:
            seq = pool.event_seq
            txs = [(p.tx.encode(), p.sender)
                   for p in sorted(pool.by_hash.values(),
                                   key=lambda p: p.submission_id)]
            base_fee, blob_fee = pool.base_fee, pool.blob_base_fee
        rec = {"type": "pt_snapshot", "pt": PT_MAGIC, "seq": seq,
               "base_fee": base_fee, "blob_base_fee": blob_fee,
               "txs": txs}
        try:
            with sub.lock:
                send_frame(sub.sock, rec)
        except OSError:
            self._drop(sub)
            return
        self.pt_snapshots_sent += 1

    def _ship_loop(self) -> None:
        """Drain the ship queue to WAL subscribers; a silent queue still
        beats ``st_heartbeat`` at the configured cadence — the standby's
        liveness signal."""
        next_beat = time.monotonic() + self.heartbeat_s
        while not self._stop.is_set():
            with self._st_cond:
                if not self._st_queue:
                    self._st_cond.wait(
                        max(0.01, next_beat - time.monotonic()))
                batch = []
                while self._st_queue:
                    batch.append(self._st_queue.popleft())
            if self._stop.is_set():
                return
            partitioned = self._partition_active()
            for kind, item in batch:
                if kind == "resync":
                    self._send_resync(item)
                    continue
                if kind == "pool_snapshot":
                    self._send_pool_snapshot(item)
                    continue
                if partitioned:
                    self.partition_suppressed += 1
                    continue
                if kind == "pool":
                    self._broadcast_pool(item)
                    self.pt_records_sent += 1
                    continue
                self._broadcast_wal(item)
                if item["type"] == "st_wal":
                    self.st_records_sent += 1
                elif item["type"] == "st_manifest":
                    self.st_manifests_sent += 1
                elif item["type"] == "st_fcu":
                    self.st_fcu_sent += 1
            if time.monotonic() >= next_beat:
                next_beat = time.monotonic() + self.heartbeat_s
                if not partitioned and not self._partition_active():
                    self._broadcast_wal(
                        {"type": "st_heartbeat", "st": ST_MAGIC,
                         "epoch": self.epoch, "head": self.head})
                    self.heartbeats_sent += 1
                else:
                    self.partition_suppressed += 1

    def _broadcast_wal(self, record: dict) -> None:
        with self._lock:
            subs = [s for s in self._subs if s.wal]
        for s in subs:
            try:
                with s.lock:
                    send_frame(s.sock, record)
            except OSError:
                self._drop(s)

    def _send_resync(self, sub: _Subscriber) -> None:
        """Full consistent table image(s) for one subscriber — sent
        from the ship thread so it lands IN ORDER with the st_wal
        stream (every queued record before it carries seq <= the
        image's, every one after continues from it)."""
        if self._durability is None:
            return
        try:
            images = self._durability.snapshot_tables()
        except Exception:  # noqa: BLE001 - resync is best-effort
            return
        for i, (tables, gen, seq) in enumerate(images):
            rec = {"type": "st_resync", "st": ST_MAGIC,
                   "epoch": self.epoch, "store": i, "tables": tables,
                   "gen": gen, "seq": seq, "head": self.head}
            try:
                with sub.lock:
                    send_frame(sub.sock, rec)
            except OSError:
                self._drop(sub)
                return
        self.resyncs_sent += 1

    # -- correlated flight dumps --------------------------------------------

    def _corr_mark(self, cid: str) -> bool:
        """True when ``cid`` is new (mark it seen); bounded LRU."""
        if not cid:
            return False
        with self._lock:
            if cid in self._corr_seen:
                return False
            self._corr_seen[cid] = True
            while len(self._corr_seen) > 256:
                self._corr_seen.popitem(last=False)
        return True

    def request_flight_dump(self, reason: str, correlation_id: str,
                            window=None) -> None:
        """Initiator path (this node's own fault event / SLO breach just
        dumped locally): fan the dump request to every replica so the
        whole fleet snapshots the same incident under one id."""
        if not self._corr_mark(correlation_id):
            return
        self.flight_fanouts += 1
        self._broadcast({"type": "flight_dump", "reason": reason,
                         "correlation_id": correlation_id,
                         "window": list(window) if window else None,
                         "origin": {"role": tracing.process_role(),
                                    "pid": os.getpid()}})

    def fault_observer(self):
        """The ``tracing.add_fault_observer`` hook for a fleet-mode full
        node: local dump written -> fan the request out."""
        def _observer(reason: str, correlation_id: str, window) -> None:
            self.request_flight_dump(reason, correlation_id, window)
        return _observer

    def _on_upstream(self, frame: dict, sub: _Subscriber) -> None:
        """A frame a replica sent UPSTREAM on its feed socket: a
        replica-side incident asks the fleet to dump, a standby
        subscribes to the WAL stream, a reconnecting replica asks for
        the backlog since its last seen head."""
        if not isinstance(frame, dict):
            return
        kind = frame.get("type")
        if kind == "subscribe_wal":
            # mark BEFORE queuing the resync so every record shipped
            # from now on reaches this subscriber; the image then lands
            # in-stream and seq-anchors the tail. A tail-exact ``from``
            # (nothing missed across the reconnect) skips the image.
            sub.wal = True
            if not self._wal_tail_matches(frame.get("from")):
                self._st_enqueue(("resync", sub))
            return
        if kind == "resync_request":
            if sub.wal:
                self._st_enqueue(("resync", sub))
            return
        if kind == "subscribe_pool":
            # mark BEFORE queuing the snapshot so every pt record shipped
            # from now on reaches this subscriber; the snapshot lands
            # in-stream and seq-anchors the tail (a re-subscribe after a
            # detected gap follows the same path)
            sub.pool = True
            self._st_enqueue(("pool_snapshot", sub))
            return
        if kind == "resubscribe":
            # reconnect catch-up: re-send retained block records above
            # the subscriber's last seen head (records are
            # self-contained; the replica dedupes by hash)
            since = frame.get("number")
            with self._lock:
                backlog = [r for r in self._backlog
                           if since is None or r["number"] > since]
            try:
                with sub.lock:
                    for record in backlog:
                        send_frame(sub.sock, record)
            except OSError:
                self._drop(sub)
            return
        if kind != "flight_dump":
            return
        cid = frame.get("correlation_id")
        if not self._corr_mark(cid):
            return
        self.flight_requests += 1
        tracing.event("fleet::feed", "flight_dump_request",
                      correlation_id=cid, reason=frame.get("reason"),
                      origin=str(frame.get("origin")))
        tracing.flight_dump(str(frame.get("reason") or "fleet"),
                            correlation_id=cid,
                            window=frame.get("window"))
        self.flight_fanouts += 1
        self._broadcast(frame, exclude=sub)

    def _wal_tail_matches(self, frm) -> bool:
        """True when ``frm`` (``{store: [gen, seq]}``) equals every
        store's live tail — the reconnecting standby missed nothing, so
        no image is needed."""
        if self._durability is None or not isinstance(frm, dict):
            return False
        stores = self._durability.stores
        if len(frm) != len(stores):
            return False
        for i, store in enumerate(stores):
            pos = frm.get(i) or frm.get(str(i))
            if not pos or tuple(pos) != (store.gen, store.seq):
                return False
        return True

    def _sub_reader(self, sub: _Subscriber) -> None:
        """Per-subscriber upstream reader (the feed socket is the
        fleet's one standing bidirectional channel). A dead socket just
        ends the reader — the next broadcast drops the subscriber."""
        while not self._stop.is_set():
            try:
                frame = recv_frame(sub.sock)
            except (ConnectionError, OSError, FeedError):
                # dead or desynced upstream stream: end the reader; the
                # next broadcast drops the subscriber if it is gone
                return
            try:
                self._on_upstream(frame, sub)
            except Exception:  # noqa: BLE001 — diagnostics only
                pass

    def _drop(self, sub: _Subscriber) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
                self.metrics.set_subscribers(len(self._subs))
        try:
            sub.sock.close()
        except OSError:
            pass

    # -- accept -------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handshake, args=(sock, addr),
                             daemon=True, name="feed-handshake").start()

    def _handshake(self, sock: socket.socket, addr) -> None:
        sub = _Subscriber(sock, addr)
        try:
            sock.sendall(FEED_MAGIC)
            hello = {"type": "hello", "chain_id": self.chain_id,
                     "head": self.head,
                     # HA fencing handshake: the sender's monotonic
                     # leader epoch + its public RPC port (where a
                     # re-anchoring replica registers with the ring)
                     "epoch": self.epoch,
                     "rpc_port": self.rpc_port,
                     "spec": (self.chain_spec.to_json()
                              if self.chain_spec is not None else None),
                     # feed-side process identity (wire-form fields):
                     # replicas stamp it on their own telemetry so a
                     # merged fleet view knows which full node fed them
                     "peer": {"role": tracing.process_role(),
                              "pid": os.getpid()}}
            with self._lock:
                backlog = list(self._backlog)
            with sub.lock:
                send_frame(sock, hello)
                # catch-up: every retained block record (each is
                # self-contained, so the replica anchors on the first)
                for record in backlog:
                    send_frame(sock, record)
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            return
        with self._lock:
            self._subs.append(sub)
            self.metrics.set_subscribers(len(self._subs))
        # upstream reader: replicas send flight-dump requests back on
        # this socket (the correlated-dump channel)
        threading.Thread(target=self._sub_reader, args=(sub,),
                         daemon=True, name="feed-upstream").start()

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            subs = len(self._subs)
            wal_subs = sum(1 for s in self._subs if s.wal)
            pool_subs = sum(1 for s in self._subs if s.pool)
            backlog = len(self._backlog)
        return {
            "port": self.port,
            "subscribers": subs,
            "wal_subscribers": wal_subs,
            "pool_subscribers": pool_subs,
            "pt_records_sent": self.pt_records_sent,
            "pt_snapshots_sent": self.pt_snapshots_sent,
            "pt_dropped": self.pt_dropped,
            "epoch": self.epoch,
            "st_records_sent": self.st_records_sent,
            "st_manifests_sent": self.st_manifests_sent,
            "st_fcu_sent": self.st_fcu_sent,
            "st_dropped": self.st_dropped,
            "heartbeats_sent": self.heartbeats_sent,
            "resyncs_sent": self.resyncs_sent,
            "partition_suppressed": self.partition_suppressed,
            "backlog": backlog,
            "blocks_sent": self.blocks_sent,
            "heads_sent": self.heads_sent,
            "witness_failures": self.witness_failures,
            "dropped_blocks": self.dropped_blocks,
            "last_witness_bytes": self.last_witness_bytes,
            "total_witness_bytes": self.total_witness_bytes,
            "queue_depth": self._queue.qsize(),
            "flight_requests": self.flight_requests,
            "flight_fanouts": self.flight_fanouts,
        }


class WitnessFeedClient:
    """Replica-side subscriber: connects, reads the hello, then streams
    frames into ``on_record``; reconnects with exponential backoff +
    jitter until stopped.

    Reconnect hardening: transport death resets nothing — the client
    remembers ``last_seen_head`` across sessions and resubscribes from
    it after the next hello (an upstream ``resubscribe`` frame the
    server answers with the retained block records above that head), so
    a late joiner mid-gap catches up instead of dying on the gap.
    ``endpoints`` holds failover feed addresses (the HA standby's
    takeover port): each failed attempt rotates to the next one."""

    def __init__(self, host: str, port: int, *, on_hello=None,
                 on_record=None, reconnect: bool = True,
                 backoff_s: float = 0.25, backoff_max_s: float = 5.0,
                 timeout_s: float = 10.0, endpoints=None):
        self.host = host
        self.port = port
        self.on_hello = on_hello
        self.on_record = on_record
        self.reconnect = reconnect
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.timeout_s = timeout_s
        # connection targets, primary first; set_endpoints() may extend
        # at runtime (a replica told about the standby's takeover feed)
        self._endpoints: list[tuple[str, int]] = [(host, int(port))]
        for ep in endpoints or ():
            self.add_endpoint(ep[0], int(ep[1]))
        self._ep_index = 0
        self._rng = random.Random()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sock: socket.socket | None = None
        self._send_lock = threading.Lock()
        self.connected = threading.Event()
        self.connections = 0
        self.frames = 0
        self.frame_errors = 0
        self.sent_upstream = 0
        self.resubscribes = 0
        self._session_established = False
        self.last_seen_head: tuple[int, bytes] | None = None
        # (host, port) of the live session — which endpoint is serving
        self.endpoint: tuple[str, int] | None = None

    def add_endpoint(self, host: str, port: int) -> None:
        ep = (host, int(port))
        if ep not in self._endpoints:
            self._endpoints.append(ep)

    def send(self, obj) -> bool:
        """Send one frame UPSTREAM to the feed server (the replica →
        full-node half of the correlated-dump channel). Best-effort:
        False when not connected or the socket died mid-send."""
        sock = self._sock
        if sock is None or not self.connected.is_set():
            return False
        try:
            with self._send_lock:
                send_frame(sock, obj)
            self.sent_upstream += 1
            return True
        except OSError:
            return False

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="feed-client")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        failures = 0
        while not self._stop.is_set():
            self._session_established = False
            try:
                self._session()
            except (OSError, ConnectionError):
                pass
            except FeedError:
                self.frame_errors += 1
            finally:
                established = self._session_established
                self.connected.clear()
            if not self.reconnect or self._stop.is_set():
                return
            if established:
                failures = 0  # a real session resets the backoff
            else:
                failures += 1
                # a dead endpoint rotates to the next candidate (the
                # failover ladder: primary feed -> standby takeover)
                self._ep_index = (self._ep_index + 1) % len(self._endpoints)
            # exponential backoff with full jitter: a flapping server
            # (or a whole fleet reconnecting at once after a leader
            # kill) must not see a synchronized retry stampede
            ceiling = min(self.backoff_max_s,
                          self.backoff_s * (2 ** min(failures, 10)))
            self._stop.wait(self.backoff_s / 4
                            + self._rng.random() * ceiling)

    def _session(self) -> None:
        host, port = self._endpoints[self._ep_index]
        sock = socket.create_connection((host, port),
                                        timeout=self.timeout_s)
        self._sock = sock
        try:
            magic = _recv_exact(sock, len(FEED_MAGIC))
            if magic != FEED_MAGIC:
                raise FeedError(f"bad feed magic {magic!r}")
            sock.settimeout(None)  # block on the stream once established
            hello = recv_frame(sock)
            if hello.get("type") != "hello":
                raise FeedError("feed did not open with hello")
            self.connections += 1
            self._session_established = True
            self.endpoint = (host, port)
            self.connected.set()
            if self.on_hello is not None:
                self.on_hello(hello)
            if self.last_seen_head is not None:
                # resubscribe-from-last-seen-head: ask for the retained
                # records this client missed while disconnected
                with self._send_lock:
                    send_frame(sock, {"type": "resubscribe",
                                      "number": self.last_seen_head[0]})
                self.resubscribes += 1
            while not self._stop.is_set():
                frame = recv_frame(sock)
                self.frames += 1
                if isinstance(frame, dict) and \
                        frame.get("type") in ("block", "head"):
                    n, h = frame.get("number"), frame.get("hash")
                    if isinstance(n, int) and (
                            self.last_seen_head is None
                            or n >= self.last_seen_head[0]):
                        self.last_seen_head = (n, h)
                if self.on_record is not None:
                    self.on_record(frame)
        finally:
            self._sock = None
            self.endpoint = None
            try:
                sock.close()
            except OSError:
                pass
