"""Era archive sync: checksummed era1 acquisition + the Era pipeline stage.

Reference analogue: crates/era-downloader (fetch era1 files from an index,
verify sha256 against the published checksum list, stream them to the
import) and the `EraStage` that runs FIRST in the pipeline so pre-merge
history comes from archives instead of devp2p (stage ordering
crates/stages/types/src/id.rs: Era → Headers → Bodies → …).

No egress exists in this environment, so the transport is a filesystem /
file:// source — the architecture is identical: an index names the files
and their checksums, acquisition verifies BEFORE anything is parsed, and
corrupt archives are rejected with the file name.
"""

from __future__ import annotations

import hashlib
import shutil
from pathlib import Path

from .era import EraError, read_era1
from .stages.api import ExecInput, ExecOutput, Stage, StageError, UnwindInput
from .storage.tables import Tables, be64


class EraSource:
    """An era archive source: a directory holding era1 files plus an
    ``index.txt`` of ``<filename> <sha256>`` lines (the reference's
    checksums file)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)

    def entries(self) -> list[tuple[str, str]]:
        index = self.root / "index.txt"
        if not index.exists():
            raise EraError(f"era source has no index.txt: {self.root}")
        out = []
        for line in index.read_text().splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, checksum = line.split()
            out.append((name, checksum))
        return out

    def open_path(self, name: str) -> Path:
        return self.root / name

    @staticmethod
    def build_index(root: str | Path) -> int:
        """Write index.txt for every *.era1 in ``root`` (publisher side)."""
        root = Path(root)
        lines = []
        for p in sorted(root.glob("*.era1")):
            lines.append(f"{p.name} {hashlib.sha256(p.read_bytes()).hexdigest()}")
        (root / "index.txt").write_text("\n".join(lines) + "\n")
        return len(lines)


class HttpEraSource:
    """An era archive provider over HTTP (reference
    crates/era-downloader/src/client.rs): ``index.txt`` lives at
    ``<base>/index.txt``; archives stream with RANGED requests so an
    interrupted download resumes from the existing ``.part`` bytes
    instead of restarting. Checksums still gate everything downstream —
    a lying server can only waste bandwidth, never corrupt the import."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 chunk_size: int = 1 << 20):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.chunk_size = chunk_size

    def entries(self) -> list[tuple[str, str]]:
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"{self.base_url}/index.txt", timeout=self.timeout) as r:
                text = r.read().decode()
        except OSError as e:
            raise EraError(f"era index fetch failed: {e}")
        out = []
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, checksum = line.split()
            out.append((name, checksum))
        return out

    def fetch_into(self, name: str, tmp: Path) -> None:
        """Stream ``name`` into ``tmp``, resuming from its current size
        via a Range request when the server honors it (206)."""
        import urllib.error
        import urllib.request

        offset = tmp.stat().st_size if tmp.exists() else 0
        req = urllib.request.Request(f"{self.base_url}/{name}")
        if offset:
            req.add_header("Range", f"bytes={offset}-")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                mode = "ab" if offset and r.status == 206 else "wb"
                with open(tmp, mode) as f:
                    while True:
                        chunk = r.read(self.chunk_size)
                        if not chunk:
                            break
                        f.write(chunk)
        except urllib.error.HTTPError as e:
            if e.code == 416 and offset:  # range past EOF: already complete
                return
            raise EraError(f"era file fetch failed: {name}: {e}")
        except OSError as e:
            raise EraError(f"era file fetch failed: {name}: {e}")


def era_source_for(location: str | Path):
    """Pick the source type from the location: http(s) URL or local dir."""
    if isinstance(location, str) and location.startswith(("http://", "https://")):
        return HttpEraSource(location)
    return EraSource(location)


class EraDownloader:
    """Verified acquisition into a local cache directory."""

    def __init__(self, source, dest: str | Path):
        self.source = source
        self.dest = Path(dest)
        self.dest.mkdir(parents=True, exist_ok=True)

    def fetch(self, name: str, checksum: str) -> Path:
        """The verified local path for one archive; re-fetches on checksum
        mismatch, raises EraError when the source itself is corrupt."""
        target = self.dest / name
        if target.exists() and self._ok(target, checksum):
            return target
        tmp = target.with_suffix(".part")
        if hasattr(self.source, "fetch_into"):  # remote: ranged + resumed
            self.source.fetch_into(name, tmp)
        else:
            src = self.source.open_path(name)
            if not src.exists():
                raise EraError(f"era file missing from source: {name}")
            shutil.copyfile(src, tmp)
        if not self._ok(tmp, checksum):
            tmp.unlink(missing_ok=True)
            raise EraError(f"checksum mismatch for {name}")
        tmp.replace(target)
        return target

    @staticmethod
    def _ok(path: Path, checksum: str) -> bool:
        return hashlib.sha256(path.read_bytes()).hexdigest() == checksum.lower()

    def fetch_all(self) -> list[Path]:
        return [self.fetch(n, c) for n, c in self.source.entries()]


class EraStage(Stage):
    """First pipeline stage: pre-target history from era1 archives.

    Each committed chunk is one archive (headers + bodies inserted,
    parent-linkage validated); blocks past the last archive are left to
    the online Headers/Bodies stages. Reference
    crates/stages/stages/src/stages/era.rs.
    """

    id = "Era"

    def __init__(self, downloader: EraDownloader | None,
                 consensus=None):
        self.downloader = downloader
        self.consensus = consensus

    def execute(self, provider, inp: ExecInput) -> ExecOutput:
        if self.downloader is None:
            return ExecOutput(checkpoint=inp.target, done=True)
        tip = inp.checkpoint
        entries = self.downloader.source.entries()
        for pos, (name, checksum) in enumerate(entries):
            path = self.downloader.fetch(name, checksum)
            group = read_era1(path)
            last = group.start_block + len(group.blocks) - 1
            if last <= tip or group.start_block > inp.target:
                continue
            parent = provider.header_by_number(tip)
            for block in group.blocks:
                n = block.header.number
                if n <= tip or n > inp.target:
                    continue
                if n != tip + 1:
                    raise StageError(
                        f"era archive {name} is not contiguous at {n}", block=n)
                if self.consensus is not None and parent is not None:
                    try:
                        self.consensus.validate_header_against_parent(
                            block.header, parent)
                    except Exception as e:  # ConsensusError
                        raise StageError(f"invalid era header {n}: {e}", block=n)
                provider.insert_header(block.header)
                provider.insert_block_body(block)
                parent = block.header
                tip = n
            if tip >= inp.target:
                break
            if pos + 1 < len(entries):
                # one archive per commit: restart resumes at the next file
                return ExecOutput(checkpoint=tip, done=False)
        # archives exhausted (or none relevant): this stage is done; the
        # online stages continue from here
        return ExecOutput(checkpoint=max(tip, inp.checkpoint), done=True)

    def unwind(self, provider, inp: UnwindInput) -> None:
        for n in range(inp.checkpoint, inp.unwind_to, -1):
            key = be64(n)
            h = provider.tx.get(Tables.CanonicalHeaders.name, key)
            if h is not None:
                provider.tx.delete(Tables.HeaderNumbers.name, h)
            provider.tx.delete(Tables.CanonicalHeaders.name, key)
            provider.tx.delete(Tables.Headers.name, key)
            provider.tx.delete(Tables.BlockBodyIndices.name, key)
