"""ExEx (execution extensions): durable canonical-state notifications.

Reference analogue: crates/exex — `ExExManager` fanning out
`CanonStateNotification`s with backpressure, a WAL so notifications
survive restarts (src/wal/), and `FinishedHeight` feedback that gates
pruning (src/lib.rs:17-24). Extensions register a handler; the manager
journals every notification before delivery and replays unacknowledged
ones on restart.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path


@dataclass
class CanonStateNotification:
    """A committed chain segment (hashes + numbers; state via provider)."""

    tip_number: int
    tip_hash: bytes
    blocks: list[tuple[int, bytes]]  # (number, hash) oldest first

    def to_json(self) -> dict:
        return {
            "tip_number": self.tip_number,
            "tip_hash": self.tip_hash.hex(),
            "blocks": [[n, h.hex()] for n, h in self.blocks],
        }

    @classmethod
    def from_json(cls, d: dict) -> "CanonStateNotification":
        return cls(
            d["tip_number"], bytes.fromhex(d["tip_hash"]),
            [(n, bytes.fromhex(h)) for n, h in d["blocks"]],
        )


class ExExHandle:
    def __init__(self, name: str, handler):
        self.name = name
        self.handler = handler
        self.finished_height = 0  # highest block fully processed


class ExExManager:
    """Fan-out + WAL + finished-height aggregation."""

    def __init__(self, wal_dir: str | Path | None = None):
        self.handles: list[ExExHandle] = []
        self.wal_path = Path(wal_dir) / "exex_wal.jsonl" if wal_dir else None
        self._next_seq = 0
        if self.wal_path and self.wal_path.exists():
            # count existing records so sequence numbers keep increasing
            with open(self.wal_path) as f:
                for line in f:
                    rec = json.loads(line)
                    self._next_seq = max(self._next_seq, rec["seq"] + 1)

    def register(self, name: str, handler) -> ExExHandle:
        h = ExExHandle(name, handler)
        self.handles.append(h)
        return h

    def notify(self, notification: CanonStateNotification) -> None:
        seq = self._next_seq
        self._next_seq += 1
        if self.wal_path:
            with open(self.wal_path, "a") as f:
                f.write(json.dumps({"seq": seq, "n": notification.to_json()}) + "\n")
                f.flush()
        for h in self.handles:
            h.handler(notification)
            h.finished_height = max(h.finished_height, notification.tip_number)

    def finished_height(self) -> int:
        """Lowest height every extension has finished — the pruning gate."""
        if not self.handles:
            return 1 << 62
        return min(h.finished_height for h in self.handles)

    def replay(self, from_height: int = 0) -> int:
        """Redeliver WAL'd notifications above ``from_height`` (restart)."""
        if not self.wal_path or not self.wal_path.exists():
            return 0
        count = 0
        with open(self.wal_path) as f:
            for line in f:
                rec = json.loads(line)
                n = CanonStateNotification.from_json(rec["n"])
                if n.tip_number > from_height:
                    for h in self.handles:
                        h.handler(n)
                        h.finished_height = max(h.finished_height, n.tip_number)
                    count += 1
        return count

    def prune_wal(self, below_height: int) -> None:
        """Drop WAL records at or below a height every ExEx finished."""
        if not self.wal_path or not self.wal_path.exists():
            return
        kept = []
        with open(self.wal_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["n"]["tip_number"] > below_height:
                    kept.append(line)
        tmp = self.wal_path.with_suffix(".tmp")
        with open(tmp, "w") as f:
            f.writelines(kept)
        tmp.replace(self.wal_path)
