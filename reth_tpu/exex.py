"""ExEx (execution extensions): durable canonical-state notifications.

Reference analogue: crates/exex — `ExExManager` fanning out
`CanonStateNotification`s with backpressure, a WAL so notifications
survive restarts (src/wal/), and `FinishedHeight` feedback that gates
pruning (src/lib.rs:17-24). Extensions register a handler; the manager
journals every notification before delivery and replays unacknowledged
ones on restart.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path


@dataclass
class CanonStateNotification:
    """A committed chain segment (hashes + numbers; state via provider)."""

    tip_number: int
    tip_hash: bytes
    blocks: list[tuple[int, bytes]]  # (number, hash) oldest first

    def to_json(self) -> dict:
        return {
            "tip_number": self.tip_number,
            "tip_hash": self.tip_hash.hex(),
            "blocks": [[n, h.hex()] for n, h in self.blocks],
        }

    @classmethod
    def from_json(cls, d: dict) -> "CanonStateNotification":
        return cls(
            d["tip_number"], bytes.fromhex(d["tip_hash"]),
            [(n, bytes.fromhex(h)) for n, h in d["blocks"]],
        )


class ExExHandle:
    def __init__(self, name: str, handler):
        self.name = name
        self.handler = handler
        self.finished_height = 0  # highest block fully processed
        # a backfilling ExEx pins finished_height at its backfill progress
        # so the pruner cannot outrun it (reference FinishedHeight gate,
        # exex/src/lib.rs:17-24)
        self.backfilling = False


class ExExManager:
    """Fan-out + WAL + finished-height aggregation."""

    def __init__(self, wal_dir: str | Path | None = None):
        import threading

        # serializes finished-height bookkeeping between live notify and
        # a concurrent backfill (the pruning gate must never observe a
        # torn backfilling/finished_height pair)
        self._lock = threading.Lock()
        self.handles: list[ExExHandle] = []
        self.wal_path = Path(wal_dir) / "exex_wal.jsonl" if wal_dir else None
        self._next_seq = 0
        if self.wal_path and self.wal_path.exists():
            # count existing records so sequence numbers keep increasing
            with open(self.wal_path) as f:
                for line in f:
                    rec = json.loads(line)
                    self._next_seq = max(self._next_seq, rec["seq"] + 1)

    def register(self, name: str, handler) -> ExExHandle:
        h = ExExHandle(name, handler)
        self.handles.append(h)
        return h

    def notify(self, notification: CanonStateNotification) -> None:
        seq = self._next_seq
        self._next_seq += 1
        if self.wal_path:
            with open(self.wal_path, "a") as f:
                f.write(json.dumps({"seq": seq, "n": notification.to_json()}) + "\n")
                f.flush()
        for h in self.handles:
            h.handler(notification)
            with self._lock:
                if not h.backfilling:
                    h.finished_height = max(h.finished_height,
                                            notification.tip_number)

    def backfill(self, handle: ExExHandle, factory, first: int, last: int,
                 **job_kw) -> int:
        """Catch a late-registered ExEx up over ``[first, last]``: the
        historical chunks re-execute and deliver to THAT handle only,
        while live notifications keep flowing to everyone else. The
        handle's finished_height tracks backfill progress, holding the
        pruning gate down until the backfill completes. Each delivered
        notification carries the chunk's re-executed
        ``BlockExecutionOutput``s as ``notification.outputs``."""
        with self._lock:
            handle.backfilling = True
            handle.finished_height = min(handle.finished_height, first - 1)
        delivered = 0
        try:
            for notification, outputs in BackfillJob(factory, first, last,
                                                     **job_kw):
                notification.outputs = outputs  # historical state changes
                handle.handler(notification)
                with self._lock:
                    handle.finished_height = notification.tip_number
                delivered += 1
        finally:
            with self._lock:
                handle.backfilling = False
        return delivered

    def finished_height(self) -> int:
        """Lowest height every extension has finished — the pruning gate."""
        if not self.handles:
            return 1 << 62
        return min(h.finished_height for h in self.handles)

    def replay(self, from_height: int = 0) -> int:
        """Redeliver WAL'd notifications above ``from_height`` (restart)."""
        if not self.wal_path or not self.wal_path.exists():
            return 0
        count = 0
        with open(self.wal_path) as f:
            for line in f:
                rec = json.loads(line)
                n = CanonStateNotification.from_json(rec["n"])
                if n.tip_number > from_height:
                    for h in self.handles:
                        h.handler(n)
                        h.finished_height = max(h.finished_height, n.tip_number)
                    count += 1
        return count

    def prune_wal(self, below_height: int) -> None:
        """Drop WAL records at or below a height every ExEx finished."""
        if not self.wal_path or not self.wal_path.exists():
            return
        kept = []
        with open(self.wal_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["n"]["tip_number"] > below_height:
                    kept.append(line)
        tmp = self.wal_path.with_suffix(".tmp")
        with open(tmp, "w") as f:
            f.writelines(kept)
        tmp.replace(self.wal_path)


class BackfillJob:
    """Historical-range re-execution feeding a late-registered ExEx.

    Reference analogue: `BackfillJob` (crates/exex/exex/src/backfill/job.rs)
    — iterate a block range, re-execute each block against HISTORICAL
    state, and yield committed chunks (here: a CanonStateNotification plus
    the real BlockExecutionOutputs) in batches bounded by
    ``batch_blocks``/``batch_gas`` (the ExecutionStageThresholds analogue).
    """

    def __init__(self, factory, first: int, last: int,
                 batch_blocks: int = 64, batch_gas: int = 500_000_000,
                 config=None):
        self.factory = factory
        self.first = first
        self.last = last
        self.batch_blocks = batch_blocks
        self.batch_gas = batch_gas
        self.config = config

    def __iter__(self):
        from .evm import BlockExecutor, EvmConfig
        from .evm.executor import ProviderStateSource
        from .storage.historical import HistoricalStateProvider

        cfg = self.config or EvmConfig()
        n = self.first
        while n <= self.last:
            blocks: list[tuple[int, bytes]] = []
            outputs = []
            gas = 0
            with self.factory.provider() as p:
                while n <= self.last and len(blocks) < self.batch_blocks \
                        and gas < self.batch_gas:
                    block = p.block_by_number(n)
                    if block is None:
                        raise ValueError(f"missing canonical block {n}")
                    parent_state = HistoricalStateProvider(p, n - 1)
                    executor = BlockExecutor(
                        ProviderStateSource(parent_state), cfg)
                    hashes = {}
                    for k in range(max(0, n - 256), n):
                        bh = p.canonical_hash(k)
                        if bh:
                            hashes[k] = bh
                    out = executor.execute(block, block_hashes=hashes)
                    blocks.append((n, block.hash))
                    outputs.append(out)
                    gas += out.gas_used
                    n += 1
            yield CanonStateNotification(
                tip_number=blocks[-1][0], tip_hash=blocks[-1][1],
                blocks=blocks,
            ), outputs
