"""Native block execution: ctypes bridge to native/evmexec.cpp.

Reference analogue: revm v41 as reth's native interpreter
(Cargo.toml:430). Maximal runs ("segments") of native-eligible
transactions execute entirely in C++ — wave-parallel speculation on OS
threads, in-order actual-access validation, serial re-run of conflicts,
inter-wave write merging — with ONE marshal round-trip per segment, so
the GIL only sees the per-tx fold into the block output. A transaction
the native core can't take (unsupported opcode, key outside the access
hint, non-latest fork rules, coinbase access) ends the segment and runs
through the Python interpreter instead: the native path either
reproduces the interpreter bit-for-bit (asserted by
tests/test_native_exec.py differential runs and test_bal.py's
serial-equality suite) or it declines.

Two drivers share the marshaling here:

* :func:`native_flow` — the BAL segment flow (engine/bal.py): access
  hints are known up front, segments are clipped to hint-eligible runs;
* the optimistic scheduler (engine/optimistic.py) — no hints: it calls
  :func:`snapshot_buffer` / :func:`txs_buffer` / :func:`call_segment`
  directly with a snapshot grown round-by-round from the read sets the
  results report back (misses keep their partial reads exactly so the
  async storage layer knows what to prefetch before the retry).
"""

from __future__ import annotations

import ctypes
import struct
import subprocess
import threading
from pathlib import Path

from ..evm.executor import calldata_floor_gas, intrinsic_gas
from ..evm.spec import LATEST_SPEC
from ..primitives.types import Account, KECCAK_EMPTY, Log

_SRC = Path(__file__).resolve().parent.parent.parent / "native" / "evmexec.cpp"
_SO = _SRC.parent / "build" / "libevmexec.so"
_build_lock = threading.Lock()
_lib = None

_u8p = ctypes.POINTER(ctypes.c_uint8)


def load_library():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            _SO.parent.mkdir(parents=True, exist_ok=True)
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   str(_SRC), "-o", str(_SO)]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(f"g++ failed building evmexec:\n{proc.stderr}")
        lib = ctypes.CDLL(str(_SO))
        lib.evm_execute_block.restype = _u8p
        lib.evm_execute_block.argtypes = [
            _u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64, _u8p,
            ctypes.c_uint64, _u8p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)]
        lib.evm_free.argtypes = [_u8p]
        _lib = lib
        return lib


def _b32(v: int) -> bytes:
    return v.to_bytes(32, "big")


# -- marshaling (shared by the BAL flow and the optimistic scheduler) --------


def env_buffer(env) -> bytes:
    """Serialize a BlockEnv for the native core."""
    return (env.coinbase
            + struct.pack("<QQQ", env.number, env.timestamp, env.gas_limit)
            + _b32(env.base_fee) + env.prev_randao.rjust(32, b"\x00")
            + struct.pack("<Q", env.chain_id) + _b32(env.blob_base_fee))


def snapshot_buffer(merged, acct_keys, slot_keys):
    """Serialize a state snapshot read through ``merged`` (any StateSource
    with account/storage/bytecode). Returns ``(buf, prev_accounts,
    prev_slots)`` — the previous images the commit fold needs for
    first-touch changesets."""
    prev_accounts: dict[bytes, Account | None] = {}
    code_ids: dict[bytes, int] = {}
    codes: list[bytes] = []
    sparts = [struct.pack("<I", len(acct_keys))]
    for a in acct_keys:
        acc = merged.account(a)
        prev_accounts[a] = acc
        code_id = -1
        if acc is not None and acc.code_hash != KECCAK_EMPTY:
            cid = code_ids.get(acc.code_hash)
            if cid is None:
                cid = len(codes)
                codes.append(merged.bytecode(acc.code_hash))
                code_ids[acc.code_hash] = cid
            code_id = cid
        sparts.append(a + struct.pack("<Q", acc.nonce if acc else 0)
                      + _b32(acc.balance if acc else 0)
                      + struct.pack("<iB", code_id, 1 if acc else 0))
    prev_slots: dict[tuple[bytes, bytes], int] = {}
    sparts.append(struct.pack("<I", len(slot_keys)))
    for a, s in slot_keys:
        v = merged.storage(a, s)
        prev_slots[(a, s)] = v
        sparts.append(a + s + _b32(v))
    sparts.append(struct.pack("<I", len(codes)))
    for c in codes:
        sparts.append(struct.pack("<I", len(c)) + c)
    return b"".join(sparts), prev_accounts, prev_slots


_TX_HEAD = struct.Struct("<I20sB20s32sQQ32s32sQQBI")


def txs_buffer(txs, senders, indices, spec, env) -> bytes:
    """Serialize the transactions at ``indices`` (absolute block ranks)."""
    tparts = [struct.pack("<I", len(indices))]
    floorable = spec.calldata_floor
    for i in indices:
        tx = txs[i]
        eff = tx.effective_gas_price(env.base_fee)
        cap = tx.max_fee_per_gas if tx.tx_type >= 2 else tx.gas_price
        floor = calldata_floor_gas(tx) if floorable else 0
        tparts.append(_TX_HEAD.pack(
            i, senders[i], 1, tx.to, tx.value.to_bytes(32, "big"),
            tx.nonce, tx.gas_limit, eff.to_bytes(32, "big"),
            cap.to_bytes(32, "big"), intrinsic_gas(tx, spec), floor,
            tx.tx_type, len(tx.data)))
        tparts.append(tx.data)
        tparts.append(struct.pack("<I", len(tx.access_list)))
        for addr, slots in tx.access_list:
            tparts.append(addr + struct.pack("<I", len(slots)))
            for s in slots:
                tparts.append(s)
    return b"".join(tparts)


def call_segment(lib, snap_buf: bytes, env_buf: bytes, txs_buf: bytes,
                 wave_sizes, remaining_gas: int, n_threads: int) -> bytes:
    """One evm_execute_block round trip; the call releases the GIL for its
    whole duration (ctypes), so speculation threads AND the async storage
    prefetchers run concurrently with the C++ crunch."""
    waves_buf = struct.pack("<I", len(wave_sizes)) + b"".join(
        struct.pack("<I", s) for s in wave_sizes)
    out_len = ctypes.c_uint64()
    sb = (ctypes.c_uint8 * len(snap_buf)).from_buffer_copy(snap_buf)
    eb = (ctypes.c_uint8 * len(env_buf)).from_buffer_copy(env_buf)
    tb = (ctypes.c_uint8 * len(txs_buf)).from_buffer_copy(txs_buf)
    wb = (ctypes.c_uint8 * len(waves_buf)).from_buffer_copy(waves_buf)
    ptr = lib.evm_execute_block(sb, len(snap_buf), eb, len(env_buf),
                                tb, len(txs_buf), wb, len(waves_buf),
                                remaining_gas, n_threads,
                                ctypes.byref(out_len))
    try:
        return ctypes.string_at(ptr, out_len.value)
    finally:
        lib.evm_free(ptr)


def parse_results(raw: bytes) -> list[dict]:
    """Decode the result buffer: one dict per tx, in submission order.
    Statuses: 0 fail, 1 ok, 2 miss (native declined), 3 not run. Missed /
    not-run txs still carry the partial read sets their speculation
    managed — the optimistic scheduler's prefetch hints."""
    (n_results,) = struct.unpack_from("<I", raw, 0)
    off = 4
    out = []
    for _ in range(n_results):
        idx, status, mode, cb_sens, gas_used = struct.unpack_from(
            "<IBBBQ", raw, off)
        off += 15
        fee_delta = int.from_bytes(raw[off:off + 32], "big"); off += 32
        (olen,) = struct.unpack_from("<I", raw, off); off += 4
        output = raw[off:off + olen]; off += olen
        (nlogs,) = struct.unpack_from("<I", raw, off); off += 4
        logs = []
        for _l in range(nlogs):
            laddr = raw[off:off + 20]; off += 20
            nt = raw[off]; off += 1
            topics = []
            for _t in range(nt):
                topics.append(raw[off:off + 32]); off += 32
            (dlen,) = struct.unpack_from("<I", raw, off); off += 4
            logs.append(Log(laddr, tuple(topics), raw[off:off + dlen]))
            off += dlen
        (nar,) = struct.unpack_from("<I", raw, off); off += 4
        acct_reads = set()
        for _a in range(nar):
            acct_reads.add(raw[off:off + 20]); off += 20
        (naw,) = struct.unpack_from("<I", raw, off); off += 4
        acct_writes = []
        for _a in range(naw):
            wa = raw[off:off + 20]; off += 20
            deleted = raw[off]; off += 1
            (nonce,) = struct.unpack_from("<Q", raw, off); off += 8
            balance = int.from_bytes(raw[off:off + 32], "big"); off += 32
            acct_writes.append((wa, deleted, nonce, balance))
        (nsr,) = struct.unpack_from("<I", raw, off); off += 4
        slot_reads = set()
        for _s in range(nsr):
            ra = raw[off:off + 20]; off += 20
            rs = raw[off:off + 32]; off += 32
            slot_reads.add((ra, rs))
        (nsw,) = struct.unpack_from("<I", raw, off); off += 4
        slot_writes = []
        for _s in range(nsw):
            ka = raw[off:off + 20]; off += 20
            ks = raw[off:off + 32]; off += 32
            v = int.from_bytes(raw[off:off + 32], "big"); off += 32
            slot_writes.append((ka, ks, v))
        out.append({
            "index": idx, "status": status, "mode": mode,
            "coinbase_sensitive": bool(cb_sens), "gas_used": gas_used,
            "fee_delta": fee_delta, "output": output, "logs": tuple(logs),
            "acct_reads": acct_reads, "acct_writes": acct_writes,
            "slot_reads": slot_reads, "slot_writes": slot_writes,
        })
    return out


# -- the BAL segment flow ----------------------------------------------------


def native_flow(block, senders, waves, entries, config, env, merged,
                n_threads, stats, commit_tx, commit_native, run_python,
                remaining_gas) -> bool:
    """Drive the whole block: native segments + Python interludes.
    Returns False when the native core can't participate at all (the
    caller then runs its pure-Python wave loop from scratch)."""
    spec = (config.spec_for(env.number, env.timestamp)
            if config is not None else LATEST_SPEC)
    # compare by fork NAME, not identity: a chainspec blobSchedule yields
    # a replaced Spec copy, but blob params are irrelevant natively
    # (type-3 txs are ineligible) — only the rule set must be >= the
    # latest one the C++ core implements (Osaka adds no EVM delta)
    if not spec.at_least(LATEST_SPEC.name):
        return False
    lib = load_library()

    txs = block.transactions
    n = len(txs)
    eligible = []
    for i in range(n):
        tx = txs[i]
        entry = entries.get(i)
        ok = (entry is not None and not entry.coinbase_sensitive
              and tx.tx_type <= 2 and tx.to is not None
              and not tx.authorization_list
              and (tx.chain_id is None or tx.chain_id == env.chain_id)
              and not (tx.tx_type >= 2 and tx.max_fee_per_gas < env.base_fee)
              and not (tx.tx_type < 2 and tx.gas_price < env.base_fee))
        if ok and env.coinbase in (entry.account_reads | entry.account_writes
                                   | {senders[i], tx.to}):
            ok = False
        if ok:
            snd = merged.account(senders[i])
            # EIP-3607 / delegated senders take the Python path (the code
            # cannot change natively, so block start is authoritative)
            if snd is not None and snd.code_hash != KECCAK_EMPTY:
                ok = False
        eligible.append(ok)

    # one wave count for the whole block, matching the Python loop's
    # accounting (segment re-clipping must not double-count)
    stats["waves"] += len(waves)

    env_buf = env_buffer(env)

    def run_segment(lo: int, hi: int) -> int:
        """Execute txs [lo, hi) natively; returns the next tx index to
        process (== hi when the whole segment committed)."""
        # snapshot from the union of the segment's access hints
        acct_keys: set[bytes] = set()
        slot_keys: set[tuple[bytes, bytes]] = set()
        for i in range(lo, hi):
            e = entries[i]
            acct_keys |= e.account_reads | e.account_writes
            acct_keys.add(senders[i])
            acct_keys.add(txs[i].to)
            slot_keys |= e.slot_reads | e.slot_writes
        snap_buf, prev_accounts, prev_slots = snapshot_buffer(
            merged, acct_keys, slot_keys)
        txs_buf = txs_buffer(txs, senders, range(lo, hi), spec, env)

        # clip the global wave partition to [lo, hi)
        sizes = []
        for w in waves:
            a, b = max(w[0], lo), min(w[-1] + 1, hi)
            if b > a:
                sizes.append(b - a)

        raw = call_segment(lib, snap_buf, env_buf, txs_buf, sizes,
                           remaining_gas(), n_threads)
        upto = hi
        for res in parse_results(raw):
            idx = res["index"]
            if res["status"] >= 2:  # miss (2) or not-run (3)
                if idx < upto:
                    upto = idx
                continue
            stats["native"] += 1
            stats["parallel" if res["mode"] == 0 else "serial"] += 1
            commit_native(txs[idx].tx_type, res["status"] == 1,
                          res["gas_used"], res["fee_delta"], res["logs"],
                          res["acct_writes"], res["slot_writes"],
                          prev_accounts, prev_slots, output=res["output"])
        return upto

    pos = 0
    while pos < n:
        if not eligible[pos]:
            _python_tx(pos, stats, commit_tx, run_python)
            pos += 1
            continue
        end = pos
        while end < n and eligible[end]:
            end += 1
        done_to = run_segment(pos, end)
        pos = done_to
        if pos < end:  # native stopped on a miss: interpreter takes it
            _python_tx(pos, stats, commit_tx, run_python)
            pos += 1
    return True


def _python_tx(i, stats, commit_tx, run_python):
    stats["serial"] += 1
    _acc, state, fee_delta, result = run_python(i)
    commit_tx(i, state, fee_delta, result)
