"""The engine tree: newPayload / forkchoiceUpdated / persistence.

Reference analogue: `EngineApiTreeHandler` (crates/engine/tree,
tree module) — `on_new_payload` (insert + validate + state root),
`on_forkchoice_updated`, `TreeState`, the orphan `BlockBuffer` and the
bounded `InvalidHeaderCache` (both in engine/block_buffer.py here), and
`advance_persistence` + `PersistenceHandle` (the persistence service).
The per-block state-root job — the reference's SparseTrieCacheTask
pipeline — is the batched incremental committer over the block's
overlay. Consensus-robustness behavior (orphan buffering/replay,
invalid-ancestor propagation, in-flight insert cancellation on
forkchoice reorgs, reorg-storm backoff) is documented in ARCHITECTURE
"Consensus robustness".
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field
from enum import Enum

from ..metrics import REGISTRY, tree_metrics
from .. import tracing
from ..chaos import crash_point

from ..consensus import ConsensusError, EthBeaconConsensus
from ..evm import BlockExecutor, EvmConfig
from ..evm.executor import InvalidTransaction, ProviderStateSource
from ..primitives.types import Block
from ..stages.execution import write_execution_output
from ..storage.overlay import Layer, OverlayTx, apply_layer
from ..storage.provider import DatabaseProvider, ProviderFactory
from ..storage.tables import Tables
from ..trie.committer import TrieCommitter
from ..trie.incremental import IncrementalStateRoot


class PayloadStatusKind(Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"


@dataclass
class PayloadStatus:
    status: PayloadStatusKind
    latest_valid_hash: bytes | None = None
    validation_error: str | None = None


class PayloadCancelled(Exception):
    """An in-flight insert was cancelled by a competing
    forkchoiceUpdated reorging away from it; the payload reports
    SYNCING instead of finishing against a dead head."""


@dataclass
class _InFlightInsert:
    """The one insert currently racing forkchoice (engine handlers may
    run on different transport threads): its identity plus the hooks a
    reorging fcU uses to abort the speculative machinery."""

    block_hash: bytes
    parent_hash: bytes
    cancel: threading.Event = field(default_factory=threading.Event)
    sparse_task: object = None
    # commit window published to the cross-block pipeline once this
    # insert enters its state-root phase (engine/block_pipeline.py)
    commit_win: object = None


@dataclass
class ExecutedBlock:
    """A validated pending block: its full effect as one overlay layer."""

    block: Block
    senders: list[bytes]
    receipts: list
    layer: Layer
    parent_hash: bytes

    @property
    def hash(self) -> bytes:
        return self.block.hash

    @property
    def number(self) -> int:
        return self.block.header.number


class EngineTree:
    """In-memory tree of pending blocks above the persisted chain."""

    def __init__(
        self,
        factory: ProviderFactory,
        committer: TrieCommitter | None = None,
        consensus: EthBeaconConsensus | None = None,
        config: EvmConfig | None = None,
        persistence_threshold: int = 2,
        unwinder=None,
        invalid_block_hooks: list | None = None,
        bal_execution: bool = False,
        state_root_strategy: str = "sparse",
        sparse_workers: int | None = None,
        parallel_exec: bool = False,
        exec_workers: int | None = None,
        invalid_cache_size: int | None = None,
        block_buffer_size: int | None = None,
        block_buffer_ttl: float | None = None,
        pipeline_depth: int | None = None,
        hot_state: bool | None = None,
    ):
        self.factory = factory
        self.committer = committer or TrieCommitter()
        self.consensus = consensus or EthBeaconConsensus(self.committer)
        self.config = config or EvmConfig()
        self.persistence_threshold = persistence_threshold
        # called with (block, reason, out=None, computed_root=None) whenever
        # a payload is rejected (reference InvalidBlockHook, witness.rs)
        self.invalid_block_hooks = list(invalid_block_hooks or [])
        # cross-block execution cache, anchored to the chain tip it was
        # warmed on (reference crates/engine/execution-cache SavedCache);
        # a payload extending a different parent resets it — stale reads
        # would be a consensus bug, so precision beats warmth
        from .execution_cache import ExecutionCache

        self.execution_cache = ExecutionCache()
        self._cache_anchor: bytes | None = None
        # parallel cache-warming pass before sequential execution (set
        # high to disable; reference gates prewarm similarly)
        self.prewarm_threshold = 4
        self.last_prewarm = None
        # Parallel execution has two schedulers. With a BAL hint
        # (bal_execution): the prewarm pass doubles as the speculative
        # access recording, then execute_block_bal schedules conflict-free
        # waves (reference payload_processor/bal/execute.rs). WITHOUT a
        # hint — every real newPayload — --parallel-exec routes through
        # the optimistic Block-STM-style scheduler (engine/optimistic.py):
        # single-wave native speculation + in-order read-set validation +
        # serial re-execution of invalidated ranks, with async storage
        # prefetch; it folds the prewarm pass into its speculative first
        # attempt. Fallback ladder: optimistic -> BAL wave -> serial.
        self.bal_execution = bal_execution
        self.last_bal_stats = None
        # --parallel-exec: optimistic scheduler on the no-BAL path
        self.parallel_exec = parallel_exec
        # scheduler speculation width (None = RETH_TPU_EXEC_WORKERS / auto)
        self.exec_workers = exec_workers
        self.last_exec = None  # per-block optimistic stats (tests/metrics)
        # live-tip state-root strategy: "sparse" overlaps the WHOLE trie
        # job with execution via a background proof-fetch + reveal task
        # (reference state_root_strategy/sparse_trie.rs); anything else
        # runs the prehash-only pipelined worker + incremental committer.
        # The sparse path falls back to the incremental committer on any
        # SparseRootError (reference engine-primitives config,
        # `state_root_fallback`).
        self.state_root_strategy = state_root_strategy
        # --sparse-workers: width of the sparse finish path's encode pool
        # AND the proof-worker pool (None = env/auto; 1 = pools off, the
        # cross-trie packed dispatch stays on)
        self.sparse_workers = sparse_workers
        from ..trie.sparse import PreservedSparseTrie

        self.preserved_trie = PreservedSparseTrie()
        self.last_sparse = None  # per-block strategy stats (tests/metrics)
        # hot-state plane (--hot-state / RETH_TPU_HOT_STATE, ISSUE 19):
        # a cross-block node/multiproof cache shared by every fork's
        # sparse task + the persistent device digest arena the fused
        # finish delta-uploads against. Both ride the same reorg
        # stand-downs as the preserved trie (deep unwind / reorg storm
        # -> wholesale invalidation).
        if hot_state is None:
            from ..trie.hot_cache import hot_state_enabled

            hot_state = hot_state_enabled()
        self.hot_cache = None
        self.hot_arena = None
        if hot_state:
            from ..trie.hot_cache import TrieNodeCache

            self.hot_cache = TrieNodeCache.from_env()
            try:
                from ..ops.fused_commit import DigestArena

                self.hot_arena = DigestArena.from_env()
            except Exception:  # noqa: BLE001 — no jax stack: cache-only
                self.hot_arena = None
        if unwinder is None:
            def unwinder(fac, target):
                from ..stages import Pipeline, default_stages

                Pipeline(fac, default_stages(committer=self.committer)).unwind(target)
        self.unwinder = unwinder
        # durability boundary (storage/wal.py DurabilityManager): when the
        # node attaches one, every persistence advance notifies it so WAL
        # checkpoints track the persistence threshold; without one, a
        # flush()-capable store is flushed at the same boundary — either
        # way durability no longer waits for graceful shutdown
        self.durability = None
        # HA fencing (fleet/election.py): a restarted old leader that
        # detects a higher leader epoch on a live peer feed sets this —
        # every write entry point (newPayload, forkchoiceUpdated)
        # refuses with the fencing reason instead of splitting the brain
        self.fenced = False
        self.fence_reason = ""
        self.blocks: dict[bytes, ExecutedBlock] = {}
        from .block_buffer import BlockBuffer, InvalidHeaderCache, ReorgTracker

        # bounded LRU of rejected payloads (--invalid-cache-size /
        # RETH_TPU_INVALID_CACHE): an invalid-payload flood plateaus at
        # the bound instead of leaking memory (reference
        # InvalidHeaderCache); dict-compatible for existing callers
        self.invalid = InvalidHeaderCache(invalid_cache_size)
        # blocks whose parent is unknown yet (reference BlockBuffer):
        # bounded + timeout-evicted, and buffered children replay the
        # moment the missing parent validates
        self.buffered = BlockBuffer(limit=block_buffer_size,
                                    ttl=block_buffer_ttl)
        # reorg-depth accounting: pathological forkchoice churn dumps
        # the flight recorder once and engages a backoff window during
        # which the speculative paths (sparse root, optimistic exec,
        # prewarm) stand down — they are exactly what the churn thrashes
        self.reorgs = ReorgTracker()
        # cross-block import pipeline (engine/block_pipeline.py): depth
        # >= 2 speculatively executes payload N+1 over N's uncommitted
        # overlay while N's state-root job runs on the device
        # (--pipeline-depth / RETH_TPU_PIPELINE_DEPTH; 1 = serial import)
        if pipeline_depth is None:
            import os

            try:
                pipeline_depth = int(
                    os.environ.get("RETH_TPU_PIPELINE_DEPTH", "1"))
            except ValueError:
                pipeline_depth = 1
        self.pipeline = None
        if pipeline_depth >= 2:
            from .block_pipeline import BlockPipeline

            self.pipeline = BlockPipeline(self, depth=pipeline_depth)
        # the insert currently in flight (engine transports may race a
        # forkchoiceUpdated against it); guarded by _inflight_lock
        self._inflight: _InFlightInsert | None = None
        self._inflight_lock = threading.Lock()
        with factory.provider() as p:
            n = p.last_block_number()
            h = p.canonical_hash(n)
        self.persisted_number = n
        self.persisted_hash = h
        self.head_hash: bytes = h  # canonical in-memory head
        self.canon_listeners: list = []  # CanonStateNotification sinks
        # fork-choice forwarding sinks (fleet HA: the witness feed ships
        # every head advance to the standby as an st_fcu record); called
        # with (number, head_hash) AFTER persistence advanced, so the
        # shipped WAL records for the head's durable prefix precede it
        self.fcu_listeners: list = []
        self._root_histogram = REGISTRY.histogram(
            "engine_state_root_duration_seconds",
            "per-block incremental state-root wall clock",
        )
        self._blocks_counter = REGISTRY.counter("engine_blocks_executed_total")

    # -- helpers --------------------------------------------------------------

    def _chain_layers(self, parent_hash: bytes) -> list[Layer] | None:
        """Overlay layers from the persisted root up to ``parent_hash``.

        Returns None when the parent is unknown (not persisted tip chain).
        """
        layers: list[Layer] = []
        h = parent_hash
        while h != self.persisted_hash:
            eb = self.blocks.get(h)
            if eb is None:
                return None
            layers.append(eb.layer)
            h = eb.parent_hash
        layers.reverse()
        return layers

    def block_by_hash(self, block_hash: bytes) -> Block | None:
        eb = self.blocks.get(block_hash)
        if eb is not None:
            return eb.block
        with self.factory.provider() as p:
            n = p.block_number(block_hash)
            return p.block_by_number(n) if n is not None else None

    def canonical_chain(self) -> list[bytes]:
        """In-memory canonical hashes, oldest first (persisted root excl.)."""
        out = []
        h = self.head_hash
        while h != self.persisted_hash:
            eb = self.blocks.get(h)
            if eb is None:
                break
            out.append(h)
            h = eb.parent_hash
        out.reverse()
        return out

    def overlay_provider(self, head: bytes | None = None) -> DatabaseProvider:
        """Read provider over the canonical in-memory state at ``head``.

        Raises KeyError when ``head`` is not a known tree block or the
        persisted root — never silently serves the wrong state.
        """
        target = head if head is not None else self.head_hash
        layers = self._chain_layers(target)
        if layers is None:
            raise KeyError(f"unknown head {target.hex()}")
        base = self.factory.db.tx()
        return DatabaseProvider(OverlayTx(base, layers), self.factory.static_files)

    # -- newPayload ------------------------------------------------------------

    def fence(self, reason: str) -> None:
        """Refuse all subsequent writes (HA epoch fencing): this node
        was superseded by a higher leader epoch while it was down."""
        self.fenced = True
        self.fence_reason = reason
        tracing.event("engine::tree", "fenced", reason=reason)

    def on_new_payload(self, block: Block) -> PayloadStatus:
        if self.fenced:
            return PayloadStatus(PayloadStatusKind.INVALID, None,
                                 f"fenced: {self.fence_reason}")
        h = block.hash
        if h in self.blocks:
            return PayloadStatus(PayloadStatusKind.VALID, h)
        reason = self.invalid.get(h)
        if reason is not None:
            return PayloadStatus(PayloadStatusKind.INVALID, None, reason)
        if block.header.parent_hash in self.invalid:
            self.invalid[h] = "invalid ancestor"
            self._invalidate_buffered_children(h)
            return PayloadStatus(PayloadStatusKind.INVALID, None, "invalid ancestor")
        # replay of an already-persisted canonical block → VALID
        with self.factory.provider() as p:
            if p.canonical_hash(block.header.number) == h:
                return PayloadStatus(PayloadStatusKind.VALID, h)
        parent_layers = self._chain_layers(block.header.parent_hash)
        if parent_layers is None and self.pipeline is not None:
            # parent may be the block currently committing: speculate —
            # execute this payload over the parent's uncommitted overlay
            # while its state-root dispatches run, adopt on VALID
            # (engine/block_pipeline.py); None means the pipeline didn't
            # handle it and the normal buffer/SYNCING path decides below
            st = self.pipeline.try_speculate(block)
            if st is not None:
                if st.status is PayloadStatusKind.VALID:
                    self._replay_buffered_children(h)
                elif st.status is PayloadStatusKind.INVALID:
                    self._invalidate_buffered_children(h)
                return st
            if block.header.parent_hash in self.invalid:
                # the parent was judged INVALID while we speculated
                self.invalid[h] = "invalid ancestor"
                self._invalidate_buffered_children(h)
                return PayloadStatus(PayloadStatusKind.INVALID, None,
                                     "invalid ancestor")
            if h in self.blocks:  # a buffered replay raced us in
                return PayloadStatus(PayloadStatusKind.VALID, h)
            parent_layers = self._chain_layers(block.header.parent_hash)
        if parent_layers is None:
            # parent unknown or below the persisted tip: buffer; the
            # parent arriving (below) or a later FCU to this branch
            # replays the buffered subtree (reference BlockBuffer)
            self.buffered.insert(block)
            return PayloadStatus(PayloadStatusKind.SYNCING)
        st = self._validate_and_insert(block, parent_layers)
        if st.status is PayloadStatusKind.VALID:
            self._replay_buffered_children(h)
        elif st.status is PayloadStatusKind.INVALID:
            self._invalidate_buffered_children(h)
        return st

    def _replay_buffered_children(self, parent_hash: bytes) -> None:
        """The missing parent just validated: replay its buffered
        children (recursing through on_new_payload, so grandchildren
        follow and an invalid child invalidates its own subtree)."""
        for child in self.buffered.take_children_of(parent_hash):
            tree_metrics.orphans_replayed()
            st = self.on_new_payload(child)
            if st.status is PayloadStatusKind.SYNCING:
                # replay interrupted (e.g. insert cancelled by a racing
                # fcU): keep the child for the next trigger
                self.buffered.insert(child)

    def _invalidate_buffered_children(self, parent_hash: bytes) -> None:
        """Invalid-ancestor propagation into the orphan buffer: children
        waiting on a block that just proved invalid are invalid too."""
        for child in self.buffered.take_children_of(parent_hash):
            self.invalid[child.hash] = "invalid ancestor"
            self._invalidate_buffered_children(child.hash)

    def _validate_and_insert(self, block: Block, parent_layers: list[Layer],
                             pre_executed=None) -> PayloadStatus:
        h = block.hash
        base = self.factory.db.tx()
        layer: Layer = {}
        overlay = DatabaseProvider(OverlayTx(base, parent_layers, layer))
        inflight = _InFlightInsert(h, block.header.parent_hash)
        with self._inflight_lock:
            self._inflight = inflight
        status = None
        try:
            # block-lifecycle trace root: trace_id = block hash; every
            # phase span below (and every queue/pool handoff that carries
            # the context) lands in this block's timeline
            with tracing.trace_block(h.hex(), number=block.header.number,
                                     txs=len(block.transactions)):
                with tracing.span("engine::tree", "validate"):
                    parent = self._header_of(block.header.parent_hash, overlay)
                    self.consensus.validate_header_against_parent(
                        block.header, parent)
                    self.consensus.validate_block_pre_execution(block)
                status, senders, receipts = self._execute_into_overlay(
                    block, overlay, parent_layers, inflight=inflight,
                    pre_executed=pre_executed)
        except (ConsensusError, InvalidTransaction) as e:
            self.invalid[h] = str(e)
            self._run_invalid_hooks(block, str(e))
            return PayloadStatus(PayloadStatusKind.INVALID, None, str(e))
        except PayloadCancelled:
            # a competing forkchoiceUpdated reorged away mid-insert: the
            # speculative work was aborted through the journaled paths;
            # the payload itself may be perfectly valid, so report
            # SYNCING (the CL re-sends if it still cares), never INVALID
            tracing.event("engine::tree", "payload_cancelled",
                          block=h.hex()[:16])
            return PayloadStatus(PayloadStatusKind.SYNCING)
        finally:
            with self._inflight_lock:
                if self._inflight is inflight:
                    self._inflight = None
            # non-VALID exit (exception, INVALID, cancel): close this
            # insert's commit window NOW so a speculating child aborts;
            # the VALID path closes below, AFTER the block is visible in
            # the tree (adoption needs it in ``blocks``)
            if (inflight.commit_win is not None and self.pipeline is not None
                    and (status is None
                         or status.status is not PayloadStatusKind.VALID)):
                self.pipeline.close_commit(inflight.commit_win, ok=False)
        if status.status is PayloadStatusKind.VALID:
            self.blocks[h] = ExecutedBlock(
                block=block, senders=senders, receipts=receipts,
                layer=layer, parent_hash=block.header.parent_hash,
            )
            self.buffered.pop(h, None)
            if inflight.commit_win is not None and self.pipeline is not None:
                self.pipeline.close_commit(inflight.commit_win, ok=True)
        return status

    def _header_of(self, block_hash: bytes, overlay: DatabaseProvider):
        eb = self.blocks.get(block_hash)
        if eb is not None:
            return eb.block.header
        n = overlay.block_number(block_hash)
        if n is None:
            raise ConsensusError("unknown parent")
        return overlay.header_by_number(n)

    def _execute_into_overlay(
        self, block: Block, overlay: DatabaseProvider,
        parent_layers: list[Layer] | None = None,
        inflight: _InFlightInsert | None = None,
        pre_executed=None,
    ) -> tuple[PayloadStatus, list[bytes], list]:
        """Execute + hash + root-check ``block``, writing into the overlay.

        Returns (status, senders, receipts); senders/receipts are empty on
        invalid payloads. With ``pre_executed`` (a SpeculationResult from
        the cross-block pipeline) execution is already done: its output
        feeds the SAME post-validation, overlay writes, and root checks a
        fresh execution would — adoption never skips a consensus check.
        """
        header = block.header
        n = header.number
        # execute (senders recovered here = SenderRecovery equivalent)
        from .execution_cache import CachedStateSource

        with tracing.span("engine::tree", "prepare"):
            # one hash computation for the whole function: Block.hash
            # re-encodes and keccaks the header on EVERY access (~ms) —
            # the block timeline made the three redundant recomputations
            # on this path visible
            block_hash = block.hash
            if pre_executed is not None:
                # adopt the speculation's warmed cache as the tree's
                # cross-block cache (it was warmed on exactly this
                # parent's state); finalize below advances its anchor
                self.execution_cache = pre_executed.cache
                self._cache_anchor = header.parent_hash
                source = executor = None
                hashes = {}
            else:
                if self._cache_anchor != header.parent_hash:
                    self.execution_cache = type(self.execution_cache)()  # reset
                    # the fresh cache is warmed with THIS parent's state:
                    # anchor it now, or a failed sibling would leave
                    # cache/anchor divergent
                    self._cache_anchor = header.parent_hash
                source = CachedStateSource(ProviderStateSource(overlay),
                                           self.execution_cache)
                executor = BlockExecutor(source, self.config)
                hashes = {}
                for k in range(max(0, n - 256), n):
                    bh = overlay.canonical_hash(k)
                    if bh:
                        hashes[k] = bh
        from ..primitives.types import recover_senders

        with tracing.span("engine::tree", "recover_senders",
                          txs=len(block.transactions)):
            senders = (pre_executed.senders if pre_executed is not None
                       else recover_senders(block.transactions))
        if any(s is None for s in senders):
            bad = next(i for i, s in enumerate(senders) if s is None)
            try:
                block.transactions[bad].recover_sender()
                reason = "recovery failed"
            except ValueError as e:
                reason = str(e)
            msg = f"bad signature: tx {bad}: {reason}"
            self.invalid[block_hash] = msg
            self._run_invalid_hooks(block, msg)
            return PayloadStatus(PayloadStatusKind.INVALID, None, msg), [], []
        # background state-root job overlapping execution: the sparse
        # strategy streams touched keys to a proof-fetch + reveal worker
        # so the whole trie job (hash, walk, reveal) overlaps the EVM
        # (reference the sparse-trie state-root strategy + the parallel
        # state-root task); the pipelined strategy overlaps key
        # prehash only (engine/pipelined_root.py). Created BEFORE prewarm
        # so the warming workers can seed its proof prefetch below.
        self.last_sparse = None
        sparse_task = None
        root_job = None
        # reorg-storm backoff: while a hostile CL churns forkchoice, the
        # speculative paths (preserved sparse trie, optimistic exec,
        # prewarm) are what every reorg invalidates — stand them down and
        # serve through the serial + pipelined/incremental paths instead
        speculate = not self.reorgs.in_backoff()
        block_ctx = tracing.current_context()  # the block's root span
        with tracing.span("engine::tree", "root_task_start"):
            if self.state_root_strategy == "sparse" and speculate:
                sparse_task = self._start_sparse_root(
                    block, parent_layers, trace_ctx=block_ctx,
                    # adoption seeds the key digests the speculative
                    # prehash already computed on the double-buffered
                    # sub-mesh — the task skips re-hashing them
                    seed_digests=(pre_executed.digests
                                  if pre_executed is not None else None))
            if sparse_task is None:
                from .pipelined_root import PipelinedStateRoot

                root_job = PipelinedStateRoot(self.committer.hasher)
        if inflight is not None:
            inflight.sparse_task = sparse_task
        state_hook = (sparse_task or root_job).on_state_update
        self.last_prewarm = None  # bind the pass to THIS block only
        self.last_exec = None
        # --parallel-exec without a BAL hint: the optimistic scheduler
        # (engine/optimistic.py) replaces BOTH the prewarm pass and the
        # serial canonical execution — its speculative first attempt IS
        # the prewarm run (reads warm the shared cache and stream to the
        # sparse task), and validation-clean speculation commits instead
        # of being discarded and re-executed.
        use_opt = (self.parallel_exec and not self.bal_execution and speculate
                   and pre_executed is None
                   and len(block.transactions) >= self.prewarm_threshold)
        # prewarm: execute txs in parallel against PARENT state first,
        # purely to populate the execution cache (reference
        # payload_processor/prewarm.rs); canonical execution below then
        # runs against warm caches
        if (len(block.transactions) >= self.prewarm_threshold and not use_opt
                and speculate and pre_executed is None):
            from ..evm.executor import blob_base_fee
            from ..evm.interpreter import BlockEnv
            from .prewarm import PrewarmTask

            env = BlockEnv(
                number=header.number, timestamp=header.timestamp,
                coinbase=header.beneficiary, gas_limit=header.gas_limit,
                base_fee=header.base_fee_per_gas or 0,
                prev_randao=header.mix_hash, chain_id=self.config.chain_id,
                blob_base_fee=blob_base_fee(
                    header.excess_blob_gas or 0,
                    self.config.blob_params_for(
                        header.number, header.timestamp).update_fraction),
            )
            self.last_prewarm = PrewarmTask(
                executor, env, record_accesses=self.bal_execution,
                # seed the sparse task's multiproof prefetch from the
                # warming workers' touched keys (key-only, independent of
                # BAL): proof fetch overlaps PREWARM, not just canonical
                # execution. on_state_update dedupes and the trie-reveal
                # path tolerates speculative extras, so racy worker-side
                # duplicates are harmless.
                key_sink=(sparse_task.on_state_update
                          if sparse_task is not None else None))
            # started, NOT joined: the canonical pass below overlaps the
            # warming workers (speculative reads only touch the shared
            # mutex-guarded cache; canonical writes stay in its journal).
            # In BAL mode the pass is joined first instead — its recorded
            # access sets become the wave schedule.
            self.last_prewarm.start(block.transactions, senders)

        def _abort_root_job():
            if sparse_task is not None:
                sparse_task.abort()
            else:
                root_job.finish([])

        def _cancel_guard():
            # cooperative cancellation boundary: a forkchoiceUpdated that
            # reorged away from this block set the in-flight event (and
            # non-blockingly cancelled the sparse task); abort the root
            # job through the journaled path instead of letting it finish
            # against a dead head
            if inflight is not None and inflight.cancel.is_set():
                _abort_root_job()
                raise PayloadCancelled(
                    "forkchoice reorged away from in-flight block")

        use_bal = (self.bal_execution and self.last_prewarm is not None
                   and self.last_prewarm.record_accesses)
        t_exec0 = _time.monotonic()
        try:
            if pre_executed is not None:
                # cross-block pipeline adoption: execution already ran
                # over this parent's uncommitted overlay while it was
                # committing; feed the root task its touched keys in one
                # burst (digests were seeded above) and reuse the output
                with tracing.span("engine::tree", "adopt_speculation",
                                  txs=len(block.transactions),
                                  keys=len(pre_executed.keys)):
                    state_hook(pre_executed.keys)
                    out = pre_executed.out
                    self.last_exec = pre_executed.stats
                    if pre_executed.stats is not None:
                        self._record_exec_metrics(
                            optimistic=pre_executed.stats)
            else:
                with tracing.span("engine::execute", "execute",
                                  txs=len(block.transactions), bal=use_bal,
                                  optimistic=use_opt):
                    if use_bal:
                        from .bal import BlockAccessList, execute_block_bal

                        self.last_prewarm.join()
                        hint = BlockAccessList(entries=[
                            self.last_prewarm.accesses[i]
                            for i in sorted(self.last_prewarm.accesses)])
                        out, self.last_bal_stats = execute_block_bal(
                            executor.source, block, senders, hint, self.config,
                            state_hook=state_hook, block_hashes=hashes)
                        self._record_exec_metrics(bal=self.last_bal_stats)
                    elif use_opt:
                        from .optimistic import ExecCancelled, execute_block_optimistic

                        try:
                            out, self.last_exec = execute_block_optimistic(
                                executor.source, block, senders, self.config,
                                max_workers=self.exec_workers,
                                state_hook=state_hook, block_hashes=hashes,
                                cancel_event=(inflight.cancel
                                              if inflight is not None else None))
                        except ExecCancelled as e:
                            # the scheduler stopped its waves mid-round; the
                            # BaseException handler below aborts the root job
                            raise PayloadCancelled(str(e)) from e
                        self._record_exec_metrics(optimistic=self.last_exec)
                    else:
                        out = executor.execute(block, senders, hashes,
                                               state_hook=state_hook)
        except BaseException:
            _abort_root_job()  # never leak the worker thread
            if self.last_prewarm is not None:
                self.last_prewarm.join()
            raise
        if self.last_prewarm is not None:
            self.last_prewarm.join()
        if self.pipeline is not None:
            self.pipeline.note_exec_wall(
                pre_executed.exec_end - pre_executed.exec_start
                if pre_executed is not None
                else _time.monotonic() - t_exec0)
        _cancel_guard()
        try:
            with tracing.span("engine::tree", "post_validate"):
                self.consensus.validate_block_post_execution(
                    block, out.receipts, out.gas_used, requests=out.requests)
        except ConsensusError as e:
            _abort_root_job()
            self.invalid[block_hash] = str(e)
            self._run_invalid_hooks(block, str(e), out)
            return PayloadStatus(PayloadStatusKind.INVALID, None, str(e)), [], []
        # body + execution output into the overlay layer
        with tracing.span("engine::tree", "write_overlay"):
            overlay.insert_header(header)
            overlay.insert_block_body(block)
            idx = overlay.block_body_indices(n)
            for i, s in enumerate(senders):
                overlay.put_sender(idx.first_tx_num + i, s)
            write_execution_output(overlay, n, idx.first_tx_num, out)
        # hashed-state delta + state root (the state-root job)
        _cancel_guard()
        if self.pipeline is not None and inflight is not None:
            # publish the commit window: from here to the root verdict
            # only hashed/trie tables are written, so the frozen layer
            # snapshot is this block's complete plain-state effect — a
            # child payload arriving now speculates over it
            # (engine/block_pipeline.py; closed in _validate_and_insert)
            inflight.commit_win = self.pipeline.open_commit(
                block, block_hash, parent_layers or [], overlay.tx.layer)
        t0 = _time.time()
        with tracing.span("engine::tree", "state_root",
                          strategy=("sparse" if sparse_task is not None
                                    else "pipelined")):
            if sparse_task is not None:
                root = self._sparse_root_or_fallback(overlay, out, sparse_task)
            else:
                root = self._state_root_job(overlay, out, root_job)
        self._root_histogram.record(_time.time() - t0)
        self._blocks_counter.increment()
        if root != header.state_root:
            msg = (
                f"state root mismatch: computed {root.hex()} header "
                f"{header.state_root.hex()}"
            )
            self.invalid[block_hash] = msg
            self._run_invalid_hooks(block, msg, out, computed_root=root)
            return PayloadStatus(PayloadStatusKind.INVALID, None, msg), [], []
        with tracing.span("engine::tree", "finalize"):
            if (sparse_task is not None
                    and self.last_sparse.get("strategy") == "sparse"):
                # preserve only AFTER the root matched: a trie mutated by
                # an invalid block would poison the next payload's anchor
                sparse_task.preserve(block_hash)
                # same rule for the shared node cache: absorb the block's
                # committed spines + revealed read paths only once valid
                try:
                    sparse_task.absorb_into_cache(out)
                except Exception:  # noqa: BLE001 — cache population must
                    pass           # never fail a validated payload
            # advance the execution cache: invalidate this block's writes
            # and anchor the warm cache on the new tip
            self.execution_cache.on_block_applied(out.changes)
            self._cache_anchor = block_hash
        return PayloadStatus(PayloadStatusKind.VALID, block_hash), senders, out.receipts

    def _record_exec_metrics(self, bal=None, optimistic=None):
        """Surface the parallel-execution stats (exec_bal_* / exec_parallel_*
        counters + the events line's exec[...] segment)."""
        try:
            from ..metrics import exec_metrics

            if bal is not None:
                exec_metrics.record_bal(bal)
            if optimistic is not None:
                exec_metrics.record_optimistic(optimistic)
        except Exception:  # noqa: BLE001 — metrics must never fail consensus
            pass

    def _run_invalid_hooks(self, block, reason, out=None, computed_root=None):
        for hook in self.invalid_block_hooks:
            try:
                hook(block, reason, out=out, computed_root=computed_root)
            except Exception:  # noqa: BLE001 — diagnostics must never kill consensus
                pass

    def _state_root_job(self, overlay: DatabaseProvider, out, root_job=None) -> bytes:
        """Hash the block's state delta and commit the trie incrementally.

        Reference analogue: the SparseTrieCacheTask pipeline
        (state updates → proof targets → sparse trie → root,
        crates/engine/tree/src/tree/state_root_strategy/sparse_trie.rs).
        With a ``root_job`` (PipelinedStateRoot) most key digests were
        already computed concurrently with execution; only stragglers
        (e.g. withdrawal targets) hash here.
        """
        changes = out.changes
        addrs = sorted(set(changes.accounts) | set(changes.storage) | set(changes.wiped_storage))
        slot_pairs = [(a, s) for a, slots in out.post_storage.items() for s in slots]
        if root_job is not None:
            slot_keys = [s for _, s in slot_pairs]
            digest_map = root_job.finish(addrs + slot_keys)
            haddr = {a: digest_map[a] for a in addrs}
            hslots = [digest_map[s] for s in slot_keys]
        else:
            digests = self.committer.hasher(addrs + [s for _, s in slot_pairs])
            haddr = dict(zip(addrs, digests[: len(addrs)]))
            hslots = digests[len(addrs) :]
        hslot = {s: hs for (_, s), hs in zip(slot_pairs, hslots)}
        changed_accts, changed_storages, wiped_hashed = \
            self._write_hashed_tables(overlay, out, haddr, hslot)
        inc = IncrementalStateRoot(overlay, self.committer)
        return inc.compute(changed_accts, changed_storages, wiped_hashed)

    def _write_hashed_tables(self, overlay: DatabaseProvider, out,
                             haddr, hslot):
        """Hashed-table writes shared by BOTH root strategies (the live-tip
        equivalent of the hashing stages) — one code path so the sparse and
        incremental strategies can never write different hashed state.
        Returns (changed_hashed_accounts, changed_hashed_storages,
        wiped_hashed) for the incremental committer."""
        changes = out.changes
        addrs = sorted(set(changes.accounts) | set(changes.storage)
                       | set(changes.wiped_storage))
        for a in addrs:
            if a in out.post_accounts:
                overlay.put_hashed_account(haddr[a], out.post_accounts[a])
        wiped_hashed = set()
        for a in changes.wiped_storage:
            wiped_hashed.add(haddr[a])
            overlay.clear_hashed_storage(haddr[a])
        changed_hashed_storages: dict[bytes, set[bytes]] = {}
        for a, slots in out.post_storage.items():
            for s, v in slots.items():
                overlay.put_hashed_storage(haddr[a], hslot[s], v)
                changed_hashed_storages.setdefault(haddr[a], set()).add(hslot[s])
        changed_hashed_accounts = {haddr[a] for a in changes.accounts}
        return changed_hashed_accounts, changed_hashed_storages, wiped_hashed

    def _start_sparse_root(self, block: Block, parent_layers,
                           trace_ctx=None, seed_digests=None):
        """Launch the background sparse-trie root task over the PARENT
        view (its proof worker reads concurrently with execution, so it
        gets its own transaction + overlay — never the in-progress layer).

        Reference analogue: spawning SparseTrieCacheTask per payload
        (crates/engine/tree, sparse-trie state-root strategy).
        """
        from .sparse_root import SparseRootTask

        if parent_layers is None:
            return None
        try:
            def parent_view() -> DatabaseProvider:
                # each proof worker gets its OWN transaction over the same
                # frozen parent layers: cursor state is per-tx, the layer
                # dicts are immutable once the parent validated
                return DatabaseProvider(
                    OverlayTx(self.factory.db.tx(), parent_layers))

            parent_provider = parent_view()
            parent = self._header_of(block.header.parent_hash, parent_provider)
            return SparseRootTask(
                parent_provider, parent.state_root, self.preserved_trie,
                self.committer, parent_hash=block.header.parent_hash,
                provider_factory=parent_view, workers=self.sparse_workers,
                trace_ctx=trace_ctx, seed_digests=seed_digests,
                hot_cache=self.hot_cache, arena=self.hot_arena)
        except Exception:  # noqa: BLE001 — strategy startup must never
            # fail the payload; the pipelined+incremental path covers it
            return None

    def _sparse_root_or_fallback(self, overlay: DatabaseProvider, out,
                                 task) -> bytes:
        """Close the sparse root job; on any SparseRootError rerun the
        block's root with the incremental committer (reference
        `state_root_fallback` in the engine-primitives config).
        All overlay writes happen only after the sparse path fully
        succeeded, so the fallback starts from a clean layer."""
        from .sparse_root import SparseRootError

        try:
            root, digest_map, storage_roots = task.finish(out)
            acct_updates, storage_updates = task.export_updates(out, digest_map)
        except SparseRootError as e:
            if getattr(task, "cancelled", False):
                # a forkchoice reorg cancelled the task mid-finish: do
                # NOT fall back — the incremental committer would just
                # finish the same dead head's root the slow way
                raise PayloadCancelled(str(e)) from e
            self.last_sparse = {"strategy": "fallback", "error": str(e)}
            return self._state_root_job(overlay, out, None)
        self.last_sparse = {
            "strategy": "sparse", "reused": task.reused,
            "proof_batches": task.proof_batches,
            **task.overlap_metrics(),
        }
        try:
            from ..metrics import REGISTRY

            m = self.last_sparse
            REGISTRY.counter("sparse_root_blocks_total").increment()
            REGISTRY.histogram("sparse_root_overlap_fraction").record(
                m["overlap_fraction"])
            REGISTRY.histogram("sparse_root_proof_seconds").record(m["proof"])
            REGISTRY.histogram("sparse_root_reveal_seconds").record(m["reveal"])
            REGISTRY.histogram("sparse_root_finish_seconds").record(m["finish"])
            from ..metrics import sparse_commit_metrics

            cs = m.get("commit")
            if cs:
                sparse_commit_metrics.record_block(
                    dispatches=cs.get("dispatches", 0),
                    finish_s=m["finish"])
        except Exception:  # noqa: BLE001 — metrics must never fail consensus
            pass
        self._write_sparse_output(overlay, out, digest_map, storage_roots,
                                  acct_updates, storage_updates)
        return root

    def _write_sparse_output(self, overlay: DatabaseProvider, out,
                             digest_map, storage_roots,
                             acct_updates, storage_updates) -> None:
        """Mirror the sparse job's results into the overlay layer: hashed
        tables (live-tip equivalent of the hashing stages) and stored
        branch nodes straight from the sparse trie — no DB re-walk
        (reference: sparse trie TrieUpdates application)."""
        changes = out.changes
        addrs = sorted(set(changes.accounts) | set(changes.storage)
                       | set(changes.wiped_storage))
        haddr = {a: digest_map[a] for a in addrs}
        self._write_hashed_tables(overlay, out, haddr, digest_map)
        # merkle-layer invariant: HashedAccounts carries the CURRENT root
        for a, sroot in storage_roots.items():
            acct = overlay.hashed_account(haddr[a])
            if acct is not None and acct.storage_root != sroot:
                overlay.put_hashed_account(
                    haddr[a], acct.with_(storage_root=sroot),
                    preserve_storage_root=False)
        # wiped storage tries: drop every stale stored branch first; the
        # recreated trie's updates (if any) follow below
        for a in changes.wiped_storage:
            overlay.delete_storage_branches_with_prefix(haddr[a], b"")
        for path, node in acct_updates.items():
            if node is None:
                overlay.delete_account_branch(path)
            else:
                overlay.put_account_branch(path, node)
        for ha, upd in storage_updates.items():
            for path, node in upd.items():
                if node is None:
                    overlay.delete_storage_branch(ha, path)
                else:
                    overlay.put_storage_branch(ha, path, node)

    # -- forkchoice ------------------------------------------------------------

    def on_forkchoice_updated(
        self, head: bytes, safe: bytes | None = None, finalized: bytes | None = None
    ) -> PayloadStatus:
        if self.fenced:
            return PayloadStatus(PayloadStatusKind.INVALID, None,
                                 f"fenced: {self.fence_reason}")
        reason = self.invalid.get(head)
        if reason is not None:
            return PayloadStatus(PayloadStatusKind.INVALID, None, reason)
        # a forkchoice that reorgs away from the insert currently in
        # flight aborts its speculative machinery (sparse root task,
        # proof-pool shards, optimistic waves) instead of racing it
        self._cancel_inflight_for(head)
        if self.pipeline is not None:
            # same ladder for the cross-block speculation: an fcU that
            # reorgs past the speculated block's parent aborts it
            self.pipeline.on_forkchoice(head)
        if head == self.persisted_hash:
            return self._set_head(head)
        if head in self.blocks and self._chain_layers(head) is not None:
            return self._set_head(head)
        # head may be an old persisted canonical block (CL rewind) or reach
        # the canonical chain below the persisted tip via buffered blocks —
        # both need the persisted chain unwound to the branch point.
        branch = self._find_persisted_branch_point(head)
        if branch is None:
            return PayloadStatus(PayloadStatusKind.SYNCING)
        branch_number, replay = branch
        if self.unwinder is None:
            return PayloadStatus(PayloadStatusKind.SYNCING)
        self._unwind_persisted_to(branch_number)
        for blk in replay:
            st = self.on_new_payload(blk)
            if st.status is not PayloadStatusKind.VALID:
                return st
        if head in self.blocks or head == self.persisted_hash:
            return self._set_head(head)
        return PayloadStatus(PayloadStatusKind.SYNCING)

    def _set_head(self, head: bytes) -> PayloadStatus:
        old_head = self.head_hash
        depth = self._reorg_depth(old_head, head)
        self.head_hash = head
        if depth > 0:
            self._record_reorg(depth)
        # persist first so listeners (pool maintenance, static-file
        # producer, pruner) observe the advanced persisted state
        self._advance_persistence()
        if old_head != head:
            self._notify_canon_change()
            if self.fcu_listeners:
                eb = self.blocks.get(head)
                number = (eb.number if eb is not None
                          else self.persisted_number)
                for listener in list(self.fcu_listeners):
                    try:
                        listener(number, head)
                    except Exception:  # noqa: BLE001 - sinks never gate
                        pass
        return PayloadStatus(PayloadStatusKind.VALID, head)

    # -- consensus robustness --------------------------------------------------

    def _cancel_inflight_for(self, head: bytes) -> None:
        """Cancel the in-flight insert when ``head`` reorgs away from it
        (i.e. the new head neither IS the in-flight block nor extends its
        parent chain). Non-blocking: sets the cooperative event and asks
        the sparse task to stop at its next batch boundary; the insert
        thread runs the journaled aborts and reports SYNCING."""
        with self._inflight_lock:
            inflight = self._inflight
        if inflight is None or head == inflight.block_hash:
            return
        if self._extends(head, inflight.parent_hash):
            return
        if inflight.cancel.is_set():
            return
        inflight.cancel.set()
        task = inflight.sparse_task
        if task is not None:
            task.cancel()
        tree_metrics.payload_cancelled()
        tracing.event("engine::tree", "inflight_cancelled",
                      block=inflight.block_hash.hex()[:16],
                      new_head=head.hex()[:16])

    def _extends(self, head: bytes, target: bytes) -> bool:
        """Is ``target`` on ``head``'s chain (head included)? Unknown
        heads answer True — an fcU that only returns SYNCING performed
        no reorg, so it must not cancel anything."""
        if target == head:
            return True
        h = head
        while h != self.persisted_hash:
            eb = self.blocks.get(h)
            if eb is None:
                break
            h = eb.parent_hash
            if h == target:
                return True
        if h == self.persisted_hash:
            # head roots in the persisted canonical chain: every
            # persisted canonical block at or below the tip is an ancestor
            if target == self.persisted_hash:
                return True
            with self.factory.provider() as p:
                n = p.block_number(target)
                return (n is not None and n <= self.persisted_number
                        and p.canonical_hash(n) == target)
        with self.factory.provider() as p:
            hn = p.block_number(head)
            if hn is None or p.canonical_hash(hn) != head:
                return True  # unknown head: no reorg happens
            tn = p.block_number(target)
            return (tn is not None and tn <= hn
                    and p.canonical_hash(tn) == target)

    def _reorg_depth(self, old_head: bytes, new_head: bytes) -> int:
        """Blocks abandoned off the old canonical chain by switching to
        ``new_head`` (0 when the new head extends the old one)."""
        if old_head == new_head:
            return 0
        on_new = {new_head, self.persisted_hash}
        h = new_head
        while h != self.persisted_hash:
            eb = self.blocks.get(h)
            if eb is None:
                break
            h = eb.parent_hash
            on_new.add(h)
        depth = 0
        h = old_head
        while h not in on_new:
            eb = self.blocks.get(h)
            if eb is None:
                break
            depth += 1
            h = eb.parent_hash
        return depth

    def _record_reorg(self, depth: int, deep: bool = False) -> None:
        tree_metrics.record_reorg(depth, deep=deep)
        if self.reorgs.record(depth):
            # pathological churn: dump the flight recorder once per
            # storm (rate-limited) and engage the speculation backoff
            tree_metrics.storm()
            tracing.fault_event("TREE_REORG_STORM", target="engine::tree",
                                depth=depth, reorgs=self.reorgs.reorgs,
                                max_depth=self.reorgs.max_depth)
            # the hot-state plane is speculative state too: churn is
            # exactly what thrashes it, so it stands down with the rest
            self._invalidate_hot_state("reorg_storm")
        self.reorgs.in_backoff()  # refresh the gauge

    def _invalidate_hot_state(self, reason: str) -> None:
        """Wholesale hot-state invalidation (deep reorg / reorg storm):
        validation-at-lookup already guarantees no stale node can serve,
        so this is about not paying churn-thrashed miss storms — and
        about the arena's leak invariant across unwinds."""
        if self.hot_cache is not None:
            self.hot_cache.clear(reason)
        if self.hot_arena is not None:
            self.hot_arena.invalidate(reason)

    def _find_persisted_branch_point(self, head: bytes):
        """If ``head`` connects to a persisted canonical block below the tip
        (directly or via buffered blocks), return (branch_number, replay
        chain oldest-first); else None."""
        replay: list[Block] = []
        h = head
        with self.factory.provider() as p:
            while True:
                n = p.block_number(h)
                if n is not None and p.canonical_hash(n) == h:
                    return (n, list(reversed(replay)))
                blk = self.buffered.get(h)
                if blk is None:
                    eb = self.blocks.get(h)
                    if eb is None:
                        return None
                    blk = eb.block
                replay.append(blk)
                h = blk.header.parent_hash

    def _unwind_persisted_to(self, number: int) -> None:
        """Unwind the persisted chain to ``number`` (reference: engine →
        backfill pipeline unwind on deep reorgs, stages pipeline)."""
        # durable unwind intent BEFORE the first stage commit: the
        # pipeline unwinds with one commit per stage, so a crash anywhere
        # inside leaves ragged checkpoints — the marker tells startup
        # recovery the exact target to finish the job at (cleared
        # atomically with the canonical surgery below)
        from ..storage.recovery import UNWIND_MARKER_KEY

        # reorg accounting BEFORE surgery: everything above the branch
        # point on the current canonical chain is being abandoned
        eb = self.blocks.get(self.head_hash)
        head_number = eb.number if eb is not None else self.persisted_number
        with self.factory.provider_rw() as p:
            p.tx.put(Tables.Metadata.name, UNWIND_MARKER_KEY,
                     number.to_bytes(8, "big"))
        self.unwinder(self.factory, number)
        # crash window drilled by chaos.py: the pipeline unwind committed
        # but the canonical-header surgery below did not — startup
        # recovery heals it by completing the unwind to the marker target
        # (storage/recovery.py)
        crash_point("unwind")
        # drop unwound canonical blocks' header index
        with self.factory.provider_rw() as p:
            old_tip = p.last_block_number()
            for n in range(number + 1, old_tip + 1):
                bh = p.canonical_hash(n)
                if bh:
                    p.tx.delete(Tables.CanonicalHeaders.name, (n).to_bytes(8, "big"))
                    p.tx.delete(Tables.Headers.name, (n).to_bytes(8, "big"))
                    p.tx.delete(Tables.HeaderNumbers.name, bh)
            p.tx.delete(Tables.Metadata.name, UNWIND_MARKER_KEY)
        with self.factory.provider() as p:
            self.persisted_number = number
            self.persisted_hash = p.canonical_hash(number)
        self.head_hash = self.persisted_hash
        # in-memory tree entries built on the old chain are now stale
        self.blocks.clear()
        self.preserved_trie.invalidate()
        self._invalidate_hot_state("deep_reorg")
        self._record_reorg(max(0, head_number - number), deep=True)
        # the unwound shape is a durability boundary too: a crash after a
        # reorg must never resurrect the unwound chain
        self._durability_boundary()

    def _notify_canon_change(self):
        chain = [self.blocks[h] for h in self.canonical_chain()]
        for listener in self.canon_listeners:
            try:
                listener(chain)
            except Exception:  # noqa: BLE001 — a telemetry/maintenance
                # listener must never fail consensus-critical
                # canonicalization (reference notifications are decoupled
                # channels for the same reason)
                continue

    # -- persistence -----------------------------------------------------------

    def _advance_persistence(self):
        """Persist canonical blocks deeper than the threshold, prune tree.

        Reference analogue: `advance_persistence` + the persistence thread
        (crates/engine/tree/src/persistence.rs): apply layers to the DB,
        move stage checkpoints, drop persisted/abandoned tree nodes.
        """
        chain = self.canonical_chain()
        if len(chain) <= self.persistence_threshold:
            return
        to_persist = chain[: len(chain) - self.persistence_threshold]
        with self.factory.provider_rw() as p:
            for h in to_persist:
                apply_layer(p.tx, self.blocks[h].layer)
            top = self.blocks[to_persist[-1]].number
            # history indices run at persistence time (the engine path skips
            # the pipeline, but changesets are in the layers)
            from ..stages import IndexAccountHistoryStage, IndexStorageHistoryStage
            from ..stages.api import ExecInput

            for stage_obj in (IndexStorageHistoryStage(), IndexAccountHistoryStage()):
                cp = p.stage_checkpoint(stage_obj.id)
                if cp < top:
                    stage_obj.execute(p, ExecInput(top, cp))
            for stage in ("SenderRecovery", "Execution", "MerkleUnwind",
                          "AccountHashing", "StorageHashing", "MerkleExecute",
                          "TransactionLookup", "IndexStorageHistory",
                          "IndexAccountHistory", "Finish"):
                p.save_stage_checkpoint(stage, top)
        # crash window drilled by chaos.py: the persistence transaction
        # committed (and, with a WAL, is fsync-durable) but none of the
        # in-memory bookkeeping below ran — restart must recover to the
        # just-persisted head
        crash_point("advance-persistence")
        last = self.blocks[to_persist[-1]]
        self.persisted_number = last.number
        self.persisted_hash = last.hash
        # prune: drop persisted blocks and stale forks below the new root
        for h in to_persist:
            self.blocks.pop(h, None)
        for h in [h for h, eb in self.blocks.items() if eb.number <= self.persisted_number]:
            self.blocks.pop(h, None)
        self._durability_boundary()

    def _durability_boundary(self):
        """Make everything persisted so far crash-durable.

        With a WAL attached (``self.durability``) commits are already
        fsync'd record-by-record; this notifies the manager so it can
        truncate the log via a checkpoint. Without one, a store exposing
        ``flush`` gets its image written here — durability then tracks
        the persistence threshold instead of process lifetime (the old
        behavior flushed only in ``Node.stop``).
        """
        if self.durability is not None:
            try:
                self.durability.on_persisted(self.persisted_number,
                                             self.persisted_hash)
                return
            except Exception:  # noqa: BLE001 - a failed checkpoint must not
                # fail consensus; per-commit WAL records still hold
                import traceback

                traceback.print_exc()
                return
        db = self.factory.db
        # native/paged engines: sync() is the cheap power-loss durability
        # point (fsync, no compaction); image-backed stores rewrite the
        # image — either way, prefer the light call when one exists
        op = getattr(db, "sync", None) or getattr(db, "flush", None)
        if op is not None:
            try:
                op()
            except Exception:  # noqa: BLE001 - durability best-effort here;
                # consensus state is already committed
                import traceback

                traceback.print_exc()
