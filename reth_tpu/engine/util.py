"""Engine-message middleware: fault injection + capture/replay.

Reference analogue: crates/engine/util — the stream combinators reth
wraps around the consensus-engine channel: `EngineReorg` (inject
artificial reorgs every N payloads), `EngineSkip` (drop every Nth
FCU/newPayload), and `EngineStoreExt` (persist every message to disk
for later replay). Here the same seams wrap the EngineTree's call
surface, so tests and debugging sessions can exercise reorg/skip
behavior without a misbehaving CL.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


class EngineFaultInjector:
    """Wraps an EngineTree-like target with skip/reorg fault policies.

    ``skip_fcu`` / ``skip_new_payload``: drop every Nth message (the
    reference's EngineSkip streams). ``reorg_frequency``: every Nth
    payload is first answered normally, then the PREVIOUS head is
    re-targeted, forcing the tree through its reorg path (EngineReorg).
    """

    def __init__(self, tree, skip_fcu: int = 0, skip_new_payload: int = 0,
                 reorg_frequency: int = 0):
        self.tree = tree
        self.skip_fcu = skip_fcu
        self.skip_new_payload = skip_new_payload
        self.reorg_frequency = reorg_frequency
        self.fcu_count = 0
        self.payload_count = 0
        self.skipped_fcu = 0
        self.skipped_payloads = 0
        self.injected_reorgs = 0
        self._prev_head: bytes | None = None

    def on_new_payload(self, block):
        self.payload_count += 1
        if self.skip_new_payload and self.payload_count % self.skip_new_payload == 0:
            self.skipped_payloads += 1
            from .tree import PayloadStatus, PayloadStatusKind

            return PayloadStatus(PayloadStatusKind.SYNCING)
        return self.tree.on_new_payload(block)

    def on_forkchoice_updated(self, head: bytes, *a, **kw):
        self.fcu_count += 1
        if self.skip_fcu and self.fcu_count % self.skip_fcu == 0:
            self.skipped_fcu += 1
            from .tree import PayloadStatus, PayloadStatusKind

            return PayloadStatus(PayloadStatusKind.SYNCING)
        prev = self._prev_head
        result = self.tree.on_forkchoice_updated(head, *a, **kw)
        if (self.reorg_frequency and prev is not None and prev != head
                and self.fcu_count % self.reorg_frequency == 0):
            # artificial reorg: walk back to the previous head, then forward
            self.injected_reorgs += 1
            self.tree.on_forkchoice_updated(prev)
            result = self.tree.on_forkchoice_updated(head, *a, **kw)
        self._prev_head = head
        return result

    def __getattr__(self, name):
        return getattr(self.tree, name)


class EngineMessageStore:
    """Persist every engine message as JSONL for later replay
    (reference `EngineStoreExt`/`engine-store`)."""

    def __init__(self, tree, path: str | Path):
        self.tree = tree
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def _record(self, kind: str, payload: dict):
        entry = {"ts": time.time(), "kind": kind, **payload}
        with self.path.open("a") as f:
            f.write(json.dumps(entry) + "\n")

    def on_new_payload(self, block):
        self._record("new_payload", {"block": block.encode().hex()})
        return self.tree.on_new_payload(block)

    def on_forkchoice_updated(self, head: bytes, *a, **kw):
        self._record("forkchoice_updated", {"head": head.hex()})
        return self.tree.on_forkchoice_updated(head, *a, **kw)

    def __getattr__(self, name):
        return getattr(self.tree, name)

    @classmethod
    def replay(cls, path: str | Path, tree) -> int:
        """Feed a recorded message stream into ``tree``; returns count."""
        from ..primitives.types import Block

        n = 0
        for line in Path(path).read_text().splitlines():
            msg = json.loads(line)
            if msg["kind"] == "new_payload":
                tree.on_new_payload(Block.decode(bytes.fromhex(msg["block"])))
            elif msg["kind"] == "forkchoice_updated":
                tree.on_forkchoice_updated(bytes.fromhex(msg["head"]))
            n += 1
        return n
