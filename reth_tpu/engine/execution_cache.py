"""Cross-block execution cache: warm account/storage/bytecode reads.

Reference analogue: crates/engine/execution-cache (CachedStateProvider/
SavedCache) — consecutive payloads read mostly the same hot state, so
the tree keeps one cache across blocks, serves reads through it, and
INVALIDATES exactly the keys the applied block changed (a stale entry
would be a consensus bug; wholesale clearing would lose the warmth).
"""

from __future__ import annotations

from collections import OrderedDict

_MISS = object()


class _Lru:
    """Thread-safe LRU: the prewarm workers (engine/prewarm.py) populate
    these caches from several threads while nothing else runs, and the
    sequential executor reads them after — a mutex keeps the OrderedDict
    reorders from interleaving."""

    __slots__ = ("cap", "data", "hits", "misses", "_mu")

    def __init__(self, cap: int):
        import threading

        self.cap = cap
        self.data: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self._mu = threading.Lock()

    def get(self, key):
        with self._mu:
            v = self.data.get(key, _MISS)
            if v is _MISS:
                self.misses += 1
                return _MISS
            self.data.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key, value) -> None:
        with self._mu:
            self.data[key] = value
            self.data.move_to_end(key)
            while len(self.data) > self.cap:
                self.data.popitem(last=False)

    def drop(self, key) -> None:
        with self._mu:
            self.data.pop(key, None)


class ExecutionCache:
    """Shared caches, safe across blocks via precise invalidation."""

    def __init__(self, accounts: int = 50_000, storage: int = 200_000,
                 code: int = 2_000):
        self.accounts = _Lru(accounts)
        self.storage = _Lru(storage)
        self.code = _Lru(code)
        # address -> cached slot keys, so storage wipes invalidate in
        # O(address's slots) instead of scanning the whole LRU
        self._slots_of: dict[bytes, set] = {}

    def on_block_applied(self, changes) -> None:
        """Invalidate everything the block touched (BlockChanges)."""
        for addr in changes.accounts:
            self.accounts.drop(addr)
        for addr, slots in changes.storage.items():
            index = self._slots_of.get(addr)
            for slot in slots:
                self.storage.drop((addr, slot))
                if index is not None:
                    index.discard(slot)
        for addr in changes.wiped_storage:
            for slot in self._slots_of.pop(addr, ()):
                self.storage.drop((addr, slot))
        # new code is append-only (keyed by hash): nothing to invalidate

    def stats(self) -> dict:
        return {
            "account_hits": self.accounts.hits, "account_misses": self.accounts.misses,
            "storage_hits": self.storage.hits, "storage_misses": self.storage.misses,
        }


class CachedStateSource:
    """StateSource wrapper serving reads through the shared cache."""

    def __init__(self, inner, cache: ExecutionCache):
        self.inner = inner
        self.cache = cache

    def account(self, address: bytes):
        v = self.cache.accounts.get(address)
        if v is _MISS:
            v = self.inner.account(address)
            self.cache.accounts.put(address, v)
        return v

    def storage(self, address: bytes, slot: bytes) -> int:
        v = self.cache.storage.get((address, slot))
        if v is _MISS:
            v = self.inner.storage(address, slot)
            self.cache.storage.put((address, slot), v)
            self.cache._slots_of.setdefault(address, set()).add(slot)
        return v

    def bytecode(self, code_hash: bytes) -> bytes:
        v = self.cache.code.get(code_hash)
        if v is _MISS:
            v = self.inner.bytecode(code_hash)
            self.cache.code.put(code_hash, v)
        return v
