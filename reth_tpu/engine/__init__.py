"""Engine (live tip): in-memory block tree, payload validation, forkchoice.

Reference analogue: crates/engine/tree — `EngineApiTreeHandler`
(src/tree/mod.rs), `TreeState` (src/tree/state.rs), the state-root
strategies (src/tree/state_root_strategy/), and the persistence service
(src/persistence.rs). Here each pending block's entire effect (plain +
hashed state, trie nodes, receipts, changesets) is one overlay layer;
the incremental-root committer runs unchanged against the overlay, and
persistence applies layers in canonical order.
"""

from .tree import EngineTree, ExecutedBlock, PayloadStatus

__all__ = ["EngineTree", "ExecutedBlock", "PayloadStatus"]
