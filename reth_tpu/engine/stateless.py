"""Stateless block validation over a witness-backed sparse trie.

Reference analogue: the stateless validation flow the reference exposes
through `debug_executionWitness` + invalid-block witness hooks
(crates/engine/invalid-block-hooks/src/witness.rs), and the sparse-trie
state-root strategy's reveal→update→rehash loop
(crates/engine/tree/src/tree/state_root_strategy/sparse_trie.rs:126-259)
— run here with NO state source at all: every read comes from the
witness's revealed nodes, every trie edit lands in the sparse trie, and
the post-state root is recomputed with level-batched keccak.

`StatelessChain` validates consecutive blocks reusing the preserved
sparse trie (chain-state `PreservedSparseTrie`): block n+1 anchors on the
trie left by block n and only reveals what it newly touches.
"""

from __future__ import annotations

from dataclasses import replace

from ..evm.executor import BlockExecutor, StateSource
from ..primitives.keccak import keccak256, keccak256_batch_np
from ..primitives.rlp import decode_int, encode_int, rlp_decode, rlp_encode
from ..primitives.types import Account, Block, Header, KECCAK_EMPTY
from ..trie.sparse import (
    BlindedNodeError,
    PreservedSparseTrie,
    SparseStateTrie,
    SparseTrie,
)


class StatelessValidationError(Exception):
    pass


def _decode_account_leaf(leaf: bytes) -> Account:
    nonce, balance, storage_root, code_hash = rlp_decode(leaf)
    return Account(nonce=decode_int(nonce), balance=decode_int(balance),
                   storage_root=storage_root, code_hash=code_hash)


class WitnessStateSource(StateSource):
    """EVM state source answering every read from a shared sparse trie
    revealed out of witness nodes (no database anywhere)."""

    def __init__(self, trie: SparseStateTrie, witness_nodes: list[bytes],
                 codes: list[bytes]):
        self.trie = trie
        self.nodes = witness_nodes
        self.codes = {keccak256(c): c for c in codes}
        self._storage_revealed: set[bytes] = set()

    def account(self, address: bytes) -> Account | None:
        leaf = self.trie.account_trie.get(keccak256(address))
        return _decode_account_leaf(leaf) if leaf is not None else None

    def storage(self, address: bytes, slot: bytes) -> int:
        acct = self.account(address)
        if acct is None:
            return 0
        ha = keccak256(address)
        if ha not in self._storage_revealed:
            self.trie.reveal_storage(ha, acct.storage_root, self.nodes)
            self._storage_revealed.add(ha)
        leaf = self.trie.storage_trie(ha).get(keccak256(slot))
        return decode_int(rlp_decode(leaf)) if leaf is not None else 0

    def bytecode(self, code_hash: bytes) -> bytes:
        if code_hash == KECCAK_EMPTY:
            return b""
        code = self.codes.get(code_hash)
        if code is None:
            raise StatelessValidationError(
                f"witness missing bytecode {code_hash.hex()}")
        return code


def apply_output_to_trie(st: SparseStateTrie, out,
                         hasher=keccak256_batch_np,
                         storage_roots_out: dict | None = None,
                         committer=None) -> bytes:
    """Apply a BlockExecutionOutput's state delta to the sparse state trie
    and return the recomputed root. Raises BlindedNodeError when an edit
    needs an unrevealed path (witness generation catches it to close the
    witness; stateless validation treats it as an incomplete witness).
    ``storage_roots_out`` (plain address -> recomputed storage root) is
    filled for callers that must mirror the roots into hashed tables (the
    engine's sparse live-tip strategy). ``committer`` (a
    ``trie/sparse.py`` :class:`~reth_tpu.trie.sparse
    .ParallelSparseCommitter`) switches hashing to the parallel packed
    path: all writes apply first (host pointer work), then every dirty
    storage trie hashes in ONE cross-trie per-depth schedule, then the
    account trie — bit-identical roots, far fewer dispatches."""
    # storage wipes reset the trie (SELFDESTRUCT / re-created accounts)
    for a in out.changes.wiped_storage:
        st.storage_tries[keccak256(a)] = SparseTrie()
    # phase 1: storage writes (structure-only; hashing is deferred so the
    # parallel path can pack every dirty trie into one schedule)
    touched_storage: list[tuple[bytes, SparseTrie]] = []
    for a, slots in out.post_storage.items():
        ha = keccak256(a)
        stg = st.storage_trie(ha)
        try:
            for slot, val in slots.items():
                hs = keccak256(slot)
                if val == 0:
                    stg.delete(hs)
                else:
                    stg.update(hs, rlp_encode(encode_int(val)))
        except BlindedNodeError as e:
            e.owner = ha  # which storage trie needs the reveal
            raise
        touched_storage.append((a, stg))
    for a in out.changes.wiped_storage:
        if a not in out.post_storage:
            touched_storage.append((a, st.storage_tries[keccak256(a)]))
    # phase 2: storage roots — packed across tries, or per-trie serial
    storage_roots: dict[bytes, bytes] = {}
    if committer is not None:
        roots = committer.commit([t for _, t in touched_storage], hasher)
        storage_roots.update(
            (a, r) for (a, _t), r in zip(touched_storage, roots))
    else:
        for a, stg in touched_storage:
            storage_roots[a] = stg.root_hash_compute(hasher)
    if storage_roots_out is not None:
        storage_roots_out.update(storage_roots)
    # account writes: compose leaves with the recomputed storage roots
    touched = set(out.post_accounts) | set(storage_roots)
    for a in sorted(touched):
        ha = keccak256(a)
        if a in out.post_accounts:
            acct = out.post_accounts[a]
            if acct is None:
                st.remove_account(ha)
                continue
        else:  # storage-only change: account fields come from the parent leaf
            leaf = st.account_trie.get(ha)
            if leaf is None:
                continue  # storage of a deleted account
            acct = _decode_account_leaf(leaf)
        sroot = storage_roots.get(a)
        if sroot is None:
            prior = st.account_trie.get(ha)
            sroot = (_decode_account_leaf(prior).storage_root
                     if prior is not None else Account().storage_root)
        st.update_account(ha, replace(acct, storage_root=sroot).trie_encode())
    if committer is not None:
        return committer.commit([st.account_trie], hasher)[0]
    return st.account_trie.root_hash_compute(hasher)


class StatelessChain:
    """Validate consecutive blocks statelessly, preserving the sparse trie
    across blocks (reference PreservedSparseTrie semantics)."""

    def __init__(self, config=None, hasher=keccak256_batch_np):
        self.config = config
        self.hasher = hasher
        self.preserved = PreservedSparseTrie()
        # the last validated block's BlockExecutionOutput: the replica
        # role serves receipts/logs from it (stateless re-execution
        # yields the receipts the full node committed — the root check
        # proves the whole output agrees)
        self.last_output = None

    def validate(self, block: Block, witness, parent_header: Header) -> bytes:
        """Re-execute ``block`` purely from ``witness``; returns the
        computed state root or raises StatelessValidationError."""
        if block.header.parent_hash != parent_header.hash:
            raise StatelessValidationError("witness parent mismatch")
        st = self.preserved.take(block.header.parent_hash)
        if st is None:
            st = SparseStateTrie.anchored(parent_header.state_root)
        st.reveal_account(witness.state)
        src = WitnessStateSource(st, witness.state, witness.codes)
        # BLOCKHASH map from witness.headers — but only headers provably in
        # the ancestor chain: walk parent_hash links down from parent_header
        # and reject anything unlinked (a malicious witness could otherwise
        # inject arbitrary (number, hash) pairs; reference stateless crate
        # verifies the same linkage)
        hashes = {parent_header.number: parent_header.hash}
        by_number: dict[int, Header] = {}
        for raw in witness.headers:
            h = Header.decode(raw)
            if h.number == parent_header.number:
                if h.hash != parent_header.hash:
                    raise StatelessValidationError(
                        "witness header forks from parent")
                continue
            if h.number in by_number and by_number[h.number].hash != h.hash:
                raise StatelessValidationError(
                    f"conflicting witness headers at {h.number}")
            by_number[h.number] = h
        expected = parent_header
        n = parent_header.number - 1
        while n in by_number:
            h = by_number.pop(n)
            if h.hash != expected.parent_hash:
                raise StatelessValidationError(
                    f"witness header {n} not hash-linked to parent chain")
            hashes[n] = h.hash
            expected = h
            n -= 1
        if by_number:
            raise StatelessValidationError(
                f"witness headers not in ancestor chain: {sorted(by_number)}")
        executor = BlockExecutor(src, self.config)
        try:
            senders = [tx.recover_sender() for tx in block.transactions]
            out = executor.execute(block, senders, hashes)
            root = apply_output_to_trie(st, out, self.hasher)
        except BlindedNodeError as e:
            raise StatelessValidationError(
                f"witness incomplete: blinded path {e.path.hex()}") from e
        if root != block.header.state_root:
            raise StatelessValidationError(
                f"stateless root mismatch: computed {root.hex()} header "
                f"{block.header.state_root.hex()}")
        if out.gas_used != block.header.gas_used:
            raise StatelessValidationError("gas used mismatch")
        self.preserved.preserve(block.header.hash, st)
        self.last_output = out
        return root
