"""Block-access-list (BAL) parallel execution.

Reference analogue: EIP-7928 block access lists and the reference's
BAL-driven parallel execution
(crates/engine/tree/src/tree/payload_processor/bal/execute.rs): when a
block's per-transaction access sets are known, non-conflicting
transactions execute concurrently against the pre-state and their
journals merge in order; transactions whose actual accesses collide with
an earlier in-flight write are re-executed serially against the merged
state. The result is bit-identical to serial execution — the access list
is an OPTIMIZATION HINT, never trusted for correctness:

* every wave worker re-records its actual reads/writes; the in-order
  commit validates them against the writes already merged this wave and
  demotes any collision to a serial re-run;
* the coinbase priority-fee credit — which would serialize every pair of
  transactions — is accumulated as a commutative delta through the
  executor's `_credit_coinbase` seam and applied once per commit; any
  OTHER coinbase access (BALANCE of the fee recipient, transfers to or
  from it) marks the transaction coinbase-sensitive and forces it serial.

Scheduling: waves are built greedily from the access list — a
transaction joins the current wave unless an earlier wave member's write
set intersects its read∪write set (read-after-write / write-after-write;
write-after-read is safe because wave members all read the pre-wave
state and journals merge in transaction order).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..evm.executor import BlockExecutor, blob_base_fee
from ..evm.spec import LATEST_SPEC
from ..evm.interpreter import BlockEnv
from ..evm.state import EvmState, StateSource
from ..primitives.types import Account, Block, Receipt


@dataclass
class TxAccess:
    """One transaction's access sets (EIP-7928 per-tx entry)."""

    index: int
    account_reads: set[bytes] = field(default_factory=set)
    account_writes: set[bytes] = field(default_factory=set)
    slot_reads: set[tuple[bytes, bytes]] = field(default_factory=set)
    slot_writes: set[tuple[bytes, bytes]] = field(default_factory=set)
    coinbase_sensitive: bool = False

    def conflicts_with_write_sets(self, accts: set, slots: set) -> bool:
        """Same predicate against an AGGREGATE of many txs' writes — one
        intersection instead of a pairwise scan (O(wave) total instead of
        O(wave^2); the hot cost in big conflict-free blocks).

        ``isdisjoint`` instead of ``&`` over materialized unions: CPython
        iterates the smaller operand and early-exits on the first hit, so
        a conflict-free check costs O(per-tx keys) with zero temporary
        sets no matter how large the accumulated wave writes grow
        (tests/test_parallel_exec.py carries the micro-benchmark)."""
        if not accts.isdisjoint(self.account_reads):
            return True
        if not accts.isdisjoint(self.account_writes):
            return True
        if not slots.isdisjoint(self.slot_reads):
            return True
        return not slots.isdisjoint(self.slot_writes)

    def to_json(self) -> dict:
        hx = lambda b: "0x" + b.hex()  # noqa: E731
        return {
            "index": self.index,
            "accountReads": sorted(hx(a) for a in self.account_reads),
            "accountWrites": sorted(hx(a) for a in self.account_writes),
            "slotReads": sorted([hx(a), hx(s)] for a, s in self.slot_reads),
            "slotWrites": sorted([hx(a), hx(s)] for a, s in self.slot_writes),
        }


@dataclass
class BlockAccessList:
    """Per-transaction access sets for one block."""

    entries: list[TxAccess] = field(default_factory=list)

    def to_json(self) -> list[dict]:
        return [e.to_json() for e in self.entries]


# -- sources ------------------------------------------------------------------


class _RecordingSource(StateSource):
    """Records the cold reads one transaction pulls through the source."""

    def __init__(self, base: StateSource, acc: TxAccess):
        self.base = base
        self.acc = acc

    def account(self, address: bytes):
        self.acc.account_reads.add(address)
        return self.base.account(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        self.acc.slot_reads.add((address, slot))
        return self.base.storage(address, slot)

    def bytecode(self, code_hash: bytes) -> bytes:
        return self.base.bytecode(code_hash)


class _MergedView(StateSource):
    """Parent source + committed post-state overlay."""

    def __init__(self, parent: StateSource):
        self.parent = parent
        self.accounts: dict[bytes, Account | None] = {}
        self.slots: dict[bytes, dict[bytes, int]] = {}
        self.wiped: set[bytes] = set()
        self.codes: dict[bytes, bytes] = {}

    def account(self, address: bytes):
        if address in self.accounts:
            return self.accounts[address]
        return self.parent.account(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        per = self.slots.get(address)
        if per is not None and slot in per:
            return per[slot]
        if address in self.wiped:
            return 0
        return self.parent.storage(address, slot)

    def bytecode(self, code_hash: bytes) -> bytes:
        code = self.codes.get(code_hash)
        if code is not None:
            return code
        return self.parent.bytecode(code_hash)


class _BalState(EvmState):
    """EvmState flagging genuine coinbase accesses (the fee credit itself
    bypasses state through the executor seam, so anything left is real)."""

    def __init__(self, source: StateSource, coinbase: bytes, acc: TxAccess):
        super().__init__(source)
        self._coinbase = coinbase
        self._acc = acc

    def account(self, address: bytes):
        if address == self._coinbase:
            self._acc.coinbase_sensitive = True
        return super().account(address)


class _WaveExecutor(BlockExecutor):
    """Worker executor: coinbase credit becomes a commutative delta."""

    def __init__(self, source: StateSource, config):
        super().__init__(source, config)
        self.fee_delta = 0

    def _credit_coinbase(self, state, env, amount):
        self.fee_delta += amount


def make_recording_state(source: StateSource, coinbase: bytes, index: int,
                         config):
    """The recording trio every speculative/recording execution needs:
    (TxAccess, fee-delta executor, coinbase-flagging state). The fee
    credit MUST go through the delta executor — a plain BlockExecutor
    would write coinbase state and poison every access set with a
    coinbase conflict."""
    acc = TxAccess(index=index)
    rec = _RecordingSource(source, acc)
    ex = _WaveExecutor(rec, config)
    state = _BalState(rec, coinbase, acc)
    return acc, ex, state


# -- recording (builds the exact BAL from a serial reference run) -------------


def record_access_list(source: StateSource, block: Block,
                       senders: list[bytes], config=None) -> BlockAccessList:
    """Serial execution that records each transaction's exact access sets
    (the payload builder's side of EIP-7928: the builder KNOWS the
    accesses because it executed the block)."""
    env = _block_env(block, config)
    bal = BlockAccessList()
    merged = _MergedView(source)
    cumulative = 0
    for i, (tx, sender) in enumerate(zip(block.transactions, senders)):
        acc, ex, state = make_recording_state(merged, env.coinbase, i, config)
        result = ex._execute_tx(state, env, tx, sender,
                                env.gas_limit - cumulative)
        cumulative += result.gas_used
        _extract_writes(state, acc)
        _commit_journal(merged, state, ex.fee_delta, env.coinbase)
        bal.entries.append(acc)
    return bal


def _block_env(block: Block, config, block_hashes=None) -> BlockEnv:
    h = block.header
    return BlockEnv(
        number=h.number, timestamp=h.timestamp, coinbase=h.beneficiary,
        gas_limit=h.gas_limit, base_fee=h.base_fee_per_gas or 0,
        prev_randao=h.mix_hash,
        chain_id=config.chain_id if config is not None else 1,
        difficulty=h.difficulty,
        block_hashes=block_hashes or {},
        blob_base_fee=blob_base_fee(
            h.excess_blob_gas or 0,
            config.blob_params_for(h.number, h.timestamp).update_fraction
            if config is not None else LATEST_SPEC.blob.update_fraction),
    )


def _extract_writes(state: EvmState, acc: TxAccess) -> None:
    for addr in state.changes.accounts:
        acc.account_writes.add(addr)
    for addr, slots in state.changes.storage.items():
        for s in slots:
            acc.slot_writes.add((addr, s))


def _apply_fee_delta(merged: "_MergedView", coinbase: bytes,
                     fee_delta: int) -> None:
    """Credit the accumulated priority fees to the coinbase in the merged
    view (the single home of this logic — python and native commits)."""
    prev = merged.account(coinbase)
    if prev is None:
        merged.accounts[coinbase] = Account(balance=fee_delta)
    else:
        merged.accounts[coinbase] = Account(
            nonce=prev.nonce, balance=prev.balance + fee_delta,
            storage_root=prev.storage_root, code_hash=prev.code_hash)


def _commit_journal(merged: _MergedView, state: EvmState, fee_delta: int,
                    coinbase: bytes) -> None:
    """Fold one transaction's journal into the merged post-state view."""
    accounts, storage = state.final_state()
    merged.accounts.update(accounts)
    for addr in state.changes.wiped_storage:
        merged.wiped.add(addr)
        merged.slots[addr] = {}
    for addr, slots in storage.items():
        merged.slots.setdefault(addr, {}).update(slots)
    merged.codes.update(state.changes.new_bytecodes)
    if fee_delta:
        _apply_fee_delta(merged, coinbase, fee_delta)


# -- the shared commit fold ---------------------------------------------------


class BlockCommitter:
    """In-order fold of executed transactions into one block's output:
    the merged post-state view, first-touch changesets (previous images
    relative to BLOCK start), receipts, per-tx outputs, and the
    ``state_hook`` key streaming that feeds the background state-root
    task. ONE home for this logic, shared by the BAL wave loop
    (:func:`execute_block_bal`) and the optimistic scheduler
    (engine/optimistic.py) — the two parallel execution paths cannot
    drift in how they merge state.

    ``written_accts`` / ``written_slots`` accumulate every committed
    write key since construction: the optimistic scheduler validates
    block-start speculation against them (Block-STM's read-set check)."""

    def __init__(self, source: StateSource, env: BlockEnv, transactions,
                 state_hook=None):
        self.source = source
        self.env = env
        self.transactions = transactions
        self.state_hook = state_hook
        self.merged = _MergedView(source)
        self.changes_accounts: dict[bytes, Account | None] = {}
        self.changes_storage: dict[bytes, dict[bytes, int]] = {}
        self.wiped: set[bytes] = set()
        self.new_codes: dict[bytes, bytes] = {}
        self.receipts: list[Receipt] = []
        self.tx_outputs: list[bytes] = []
        self.cumulative = 0
        self.committed_any = False
        self.written_accts: set[bytes] = set()
        self.written_slots: set[tuple[bytes, bytes]] = set()

    def capture_changesets(self, state) -> None:
        # first-touch-wins previous images, relative to BLOCK start
        for addr, prev in state.changes.accounts.items():
            if addr not in self.changes_accounts:
                self.changes_accounts[addr] = prev
        for addr, slots in state.changes.storage.items():
            per = self.changes_storage.setdefault(addr, {})
            for s, prev in slots.items():
                per.setdefault(s, prev)
        for addr in state.changes.wiped_storage:
            self.wiped.add(addr)
        self.new_codes.update(state.changes.new_bytecodes)

    def commit_tx(self, i: int, state, fee_delta: int, result) -> None:
        """Fold one interpreter-executed tx (journal in ``state``) into
        the block output."""
        self.committed_any = True
        self.capture_changesets(state)
        if self.state_hook is not None:
            keys = list(state.changes.accounts) + [
                (a, s) for a, per in state.changes.storage.items()
                for s in per]
            if fee_delta:
                keys.append(self.env.coinbase)
            self.state_hook(keys)
        self.written_accts.update(state.changes.accounts)
        for a, per in state.changes.storage.items():
            self.written_slots.update((a, s) for s in per)
        _commit_journal(self.merged, state, fee_delta, self.env.coinbase)
        if fee_delta and self.env.coinbase not in self.changes_accounts:
            self.changes_accounts[self.env.coinbase] = \
                self.source.account(self.env.coinbase)
        self.cumulative += result.gas_used
        self.receipts.append(Receipt(
            tx_type=self.transactions[i].tx_type,
            success=result.success,
            cumulative_gas_used=self.cumulative,
            logs=tuple(result.receipt.logs),
        ))
        self.tx_outputs.append(result.output)

    def commit_native(self, tx_type: int, success: bool, gas_used: int,
                      fee_delta: int, logs, acct_writes, slot_writes,
                      prev_accounts, prev_slots, output: bytes = b"") -> None:
        """Single-pass fold of a natively executed tx — same effects as
        :meth:`commit_tx`, skipping the intermediate BlockChanges/shim
        objects (this is on the per-tx hot path of big blocks)."""
        self.committed_any = True
        merged = self.merged
        keys = [] if self.state_hook is not None else None
        for wa, deleted, nonce, balance in acct_writes:
            prev = prev_accounts[wa]
            if wa not in self.changes_accounts:
                self.changes_accounts[wa] = prev
            if deleted:
                merged.accounts[wa] = None
            elif prev is not None:
                merged.accounts[wa] = Account(
                    nonce=nonce, balance=balance,
                    storage_root=prev.storage_root,
                    code_hash=prev.code_hash)
            else:
                merged.accounts[wa] = Account(nonce=nonce, balance=balance)
            self.written_accts.add(wa)
            if keys is not None:
                keys.append(wa)
        for ka, ks, v in slot_writes:
            per = self.changes_storage.get(ka)
            if per is None:
                per = self.changes_storage[ka] = {}
            if ks not in per:
                per[ks] = prev_slots[(ka, ks)]
            mper = merged.slots.get(ka)
            if mper is None:
                mper = merged.slots[ka] = {}
            mper[ks] = v
            self.written_slots.add((ka, ks))
            if keys is not None:
                keys.append((ka, ks))
        if fee_delta:
            _apply_fee_delta(merged, self.env.coinbase, fee_delta)
            if self.env.coinbase not in self.changes_accounts:
                self.changes_accounts[self.env.coinbase] = \
                    self.source.account(self.env.coinbase)
            if keys is not None:
                keys.append(self.env.coinbase)
        if keys:
            self.state_hook(keys)
        self.cumulative += gas_used
        self.receipts.append(Receipt(
            tx_type=tx_type, success=success,
            cumulative_gas_used=self.cumulative, logs=logs,
        ))
        self.tx_outputs.append(output)

    def commit_system_state(self, state) -> None:
        """Fold a system-call phase's journal (an EvmState OVER the merged
        view) into the block: changesets, merged view, key stream — no
        receipt, no gas (system calls are unmetered in the block)."""
        self.capture_changesets(state)
        if self.state_hook is not None:
            keys = list(state.changes.accounts) + [
                (a, s) for a, per in state.changes.storage.items()
                for s in per]
            if keys:
                self.state_hook(keys)
        self.written_accts.update(state.changes.accounts)
        for a, per in state.changes.storage.items():
            self.written_slots.update((a, s) for s in per)
        _commit_journal(self.merged, state, 0, self.env.coinbase)

    def apply_withdrawals(self, withdrawals) -> None:
        """Post-tx withdrawal credits (gwei → wei), as the serial path."""
        keys = []
        for w in withdrawals or ():
            if w.amount:
                if w.address not in self.changes_accounts:
                    self.changes_accounts[w.address] = \
                        self.source.account(w.address)
                prev = self.merged.account(w.address) or Account()
                self.merged.accounts[w.address] = prev.with_(
                    balance=prev.balance + w.amount * 10**9)
                self.written_accts.add(w.address)
                keys.append(w.address)
        if keys and self.state_hook is not None:
            self.state_hook(keys)

    def build_output(self, senders):
        """Assemble the BlockExecutionOutput (identical in shape to the
        serial executor's)."""
        from ..evm.executor import BlockExecutionOutput

        out = BlockExecutionOutput()
        out.senders = senders
        out.receipts = self.receipts
        out.tx_outputs = self.tx_outputs
        out.gas_used = self.cumulative
        from ..evm.state import BlockChanges

        out.changes = BlockChanges(accounts=self.changes_accounts,
                                   storage=self.changes_storage,
                                   wiped_storage=self.wiped,
                                   new_bytecodes=self.new_codes)
        out.post_accounts = {a: self.merged.accounts.get(a)
                             for a in self.changes_accounts}
        out.post_storage = {
            a: {s: self.merged.slots.get(a, {}).get(s, 0) for s in slots}
            for a, slots in self.changes_storage.items()
        }
        return out


# -- parallel execution -------------------------------------------------------


def _build_waves(bal: BlockAccessList, n_txs: int) -> list[list[int]]:
    """Greedy in-order wave partition from the (hint) access list."""
    waves: list[list[int]] = []
    entries = {e.index: e for e in bal.entries}
    current: list[int] = []
    cur_accts: set = set()
    cur_slots: set = set()
    for i in range(n_txs):
        acc = entries.get(i)
        joins = (acc is not None and not acc.coinbase_sensitive
                 and not acc.conflicts_with_write_sets(cur_accts, cur_slots))
        if joins or not current:
            current.append(i)
            if acc is not None:
                cur_accts |= acc.account_writes
                cur_slots |= acc.slot_writes
        else:
            waves.append(current)
            current = [i]
            cur_accts = set(acc.account_writes) if acc else set()
            cur_slots = set(acc.slot_writes) if acc else set()
    if current:
        waves.append(current)
    return waves


def execute_block_bal(source: StateSource, block: Block,
                      senders: list[bytes], bal: BlockAccessList,
                      config=None, max_workers: int = 4, state_hook=None,
                      block_hashes=None):
    """Execute a block wave-parallel per the access-list hint; output is
    identical to `BlockExecutor.execute` (validated, with serial fallback
    per conflicting transaction). Returns (output, stats)."""
    env = _block_env(block, config, block_hashes)
    com = BlockCommitter(source, env, block.transactions,
                         state_hook=state_hook)
    merged = com.merged
    stats = {"waves": 0, "parallel": 0, "serial": 0, "native": 0}
    waves = _build_waves(bal, len(block.transactions))
    entries_by_index = {e.index: e for e in bal.entries}
    # Wave execution prefers the NATIVE core (native/evmexec.cpp): the
    # whole wave runs on real OS threads in C++ against a snapshot built
    # from the access hint, entirely GIL-free; transactions it declines
    # (unsupported ops, missing keys) fall back to the Python
    # interpreter below. RETH_TPU_BAL_NATIVE=0 disables it.
    use_native = os.environ.get("RETH_TPU_BAL_NATIVE", "1") != "0"
    # Pure-Python wave members under threads are GIL-bound: contention
    # without concurrency (measured ~4x SLOWER than serial) — so the
    # Python fallback runs sequentially; RETH_TPU_BAL_THREADS=1 forces a
    # pool anyway for experiments.
    use_threads = os.environ.get("RETH_TPU_BAL_THREADS") == "1"
    pool = (ThreadPoolExecutor(max_workers=max_workers)
            if use_threads and any(len(w) > 1 for w in waves) else None)

    def _speculate(i: int):
        acc, ex, state = make_recording_state(merged, env.coinbase, i, config)
        try:
            result = ex._execute_tx(state, env, block.transactions[i],
                                    senders[i], env.gas_limit)
            _extract_writes(state, acc)
            return (i, acc, state, ex.fee_delta, result, None)
        except Exception as e:  # noqa: BLE001 — stale-state failures retry serial
            return (i, acc, None, 0, None, e)

    def _serial(i: int):
        acc, ex, state = make_recording_state(merged, env.coinbase, i, config)
        result = ex._execute_tx(state, env, block.transactions[i], senders[i],
                                env.gas_limit - com.cumulative)
        _extract_writes(state, acc)
        return acc, state, ex.fee_delta, result

    native_done = False
    if use_native:
        # native segment flow: maximal runs of native-eligible txs execute
        # entirely in C++ (waves, conflict validation, inter-wave merge);
        # anything else runs serially through the interpreter in order
        try:
            from .native_exec import native_flow

            native_done = native_flow(
                block, senders, waves, entries_by_index, config, env,
                merged, max_workers, stats,
                commit_tx=com.commit_tx, commit_native=com.commit_native,
                run_python=_serial,
                remaining_gas=lambda: env.gas_limit - com.cumulative)
        except Exception:  # noqa: BLE001 — native is an accelerator only;
            native_done = False  # any failure restarts on the Python path
            if com.committed_any:
                raise  # partial commit: restarting would double-apply
            # nothing committed: zero the failed attempt's counters so the
            # Python loop's accounting starts clean
            for k in stats:
                stats[k] = 0

    if not native_done:
        for wave in waves:
            stats["waves"] += 1
            if len(wave) == 1 or pool is None:
                results = {i: _speculate(i) for i in wave}
            else:
                results = {r[0]: r for r in pool.map(_speculate, wave)}
            committed_accts: set = set()
            committed_slots: set = set()
            for i in wave:
                _, acc, state, fee_delta, result, err = results[i]
                conflicted = (
                    err is not None
                    or acc.coinbase_sensitive
                    or acc.conflicts_with_write_sets(committed_accts,
                                                     committed_slots)
                    or block.transactions[i].gas_limit > env.gas_limit - com.cumulative
                )
                if conflicted:
                    stats["serial"] += 1
                    acc, state, fee_delta, result = _serial(i)  # may raise: invalid block
                elif len(wave) > 1:
                    stats["parallel"] += 1  # conflict-free wave commit (the
                    # schedule-level count; threads only under RETH_TPU_BAL_THREADS)
                else:
                    stats["serial"] += 1
                com.commit_tx(i, state, fee_delta, result)
                committed_accts |= acc.account_writes
                committed_slots |= acc.slot_writes

    if pool is not None:
        pool.shutdown(wait=True)
    com.apply_withdrawals(block.withdrawals)
    return com.build_output(senders), stats
