"""Consensus-robustness primitives for the engine tree.

Reference analogue: `BlockBuffer`
(crates/engine/tree/src/tree/block_buffer.rs — bounded LRU of blocks
whose parent is unknown, with a parent→children index so the buffered
subtree replays the moment the missing parent arrives) and
`InvalidHeaderCache` (crates/engine/tree/src/tree/invalid_headers.rs —
a bounded LRU, because a hostile CL can flood `newPayload` with
distinct invalid blocks forever and an unbounded dict is a memory
leak).

On top of the two reference caches this module adds a
:class:`ReorgTracker`: reorg-depth accounting with storm detection.
The speculative machinery this repo keeps growing (preserved sparse
tries, optimistic execution, proof prefetch) is exactly what a
reorg-storming CL invalidates over and over — when forkchoice churns
pathologically the tracker dumps the flight recorder once and engages
a backoff window during which the engine serves blocks through the
non-speculative paths (serial execution + pipelined/incremental root),
which have no cross-block state for the attacker to thrash.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict

from ..metrics import tree_metrics


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, "") or 0)
    except ValueError:
        v = 0
    return v if v > 0 else default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def resolve_invalid_cache_size(size: int | None = None) -> int:
    """``--invalid-cache-size`` > ``RETH_TPU_INVALID_CACHE`` > 512."""
    if size is not None and size > 0:
        return size
    return _env_int("RETH_TPU_INVALID_CACHE", 512)


class BlockBuffer:
    """Bounded, timeout-evicted store of blocks awaiting their parent.

    ``insert`` refreshes LRU position; a full buffer evicts the
    least-recently-touched entry (an attacker streaming orphans pushes
    out its own garbage, not the honest chain the node is about to
    connect). Entries older than ``ttl`` seconds are lazily evicted on
    the next insert — a parent that never arrives must not pin memory.
    ``take_children_of`` removes and returns the direct children of a
    hash so the tree can replay them once that parent validates.
    """

    def __init__(self, limit: int | None = None, ttl: float | None = None,
                 clock=time.monotonic):
        self.limit = (limit if limit is not None and limit > 0
                      else _env_int("RETH_TPU_BLOCK_BUFFER", 256))
        self.ttl = (ttl if ttl is not None
                    else _env_float("RETH_TPU_BLOCK_BUFFER_TTL", 60.0))
        self._clock = clock
        self._blocks: OrderedDict[bytes, tuple[object, float]] = OrderedDict()
        self._children: dict[bytes, set[bytes]] = {}
        self.evicted = 0

    def __len__(self) -> int:
        return len(self._blocks)

    def __contains__(self, block_hash: bytes) -> bool:
        return block_hash in self._blocks

    def insert(self, block) -> None:
        self.evict_expired()
        h = block.hash
        if h in self._blocks:
            self._blocks.move_to_end(h)
            return
        while len(self._blocks) >= self.limit:
            old_h, (old_b, _) = self._blocks.popitem(last=False)
            self._unlink(old_h, old_b)
            self.evicted += 1
            tree_metrics.orphan_evicted()
        self._blocks[h] = (block, self._clock())
        self._children.setdefault(block.header.parent_hash, set()).add(h)
        tree_metrics.set_orphans(len(self._blocks))

    def get(self, block_hash: bytes):
        entry = self._blocks.get(block_hash)
        return entry[0] if entry is not None else None

    def pop(self, block_hash: bytes, default=None):
        entry = self._blocks.pop(block_hash, None)
        if entry is None:
            return default
        block, _ = entry
        self._unlink(block_hash, block)
        tree_metrics.set_orphans(len(self._blocks))
        return block

    def take_children_of(self, parent_hash: bytes) -> list:
        """Remove and return the buffered DIRECT children of
        ``parent_hash`` (the caller recurses through replay — a child
        that turns out invalid must invalidate, not replay, its own
        descendants)."""
        out = []
        for h in sorted(self._children.get(parent_hash, ())):
            blk = self.pop(h)
            if blk is not None:
                out.append(blk)
        return out

    def evict_expired(self) -> None:
        if not self.ttl:
            return
        now = self._clock()
        stale = [h for h, (_, ts) in self._blocks.items()
                 if now - ts > self.ttl]
        for h in stale:
            block, _ = self._blocks.pop(h)
            self._unlink(h, block)
            self.evicted += 1
            tree_metrics.orphan_evicted()
        if stale:
            tree_metrics.set_orphans(len(self._blocks))

    def _unlink(self, block_hash: bytes, block) -> None:
        sibs = self._children.get(block.header.parent_hash)
        if sibs is not None:
            sibs.discard(block_hash)
            if not sibs:
                del self._children[block.header.parent_hash]


class InvalidHeaderCache:
    """Bounded LRU of invalid block hash → rejection reason.

    Drop-in for the engine tree's old unbounded dict (``h in cache``,
    ``cache[h]``, ``cache[h] = reason``). Lookups refresh LRU position
    so the invalid blocks a CL keeps re-sending stay cached while
    one-shot flood entries age out. Eviction is safe: a re-sent evicted
    block simply re-validates (or buffers as unknown-parent) — bounded
    memory traded for re-checking, the reference's exact trade.
    """

    def __init__(self, capacity: int | None = None):
        self.capacity = resolve_invalid_cache_size(capacity)
        self._entries: OrderedDict[bytes, str] = OrderedDict()
        self.evicted = 0
        self.hits = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, block_hash: bytes) -> bool:
        if block_hash in self._entries:
            self._entries.move_to_end(block_hash)
            self.hits += 1
            return True
        return False

    def __getitem__(self, block_hash: bytes) -> str:
        reason = self._entries[block_hash]
        self._entries.move_to_end(block_hash)
        return reason

    def get(self, block_hash: bytes, default=None):
        if block_hash in self._entries:
            return self[block_hash]
        return default

    def __setitem__(self, block_hash: bytes, reason: str) -> None:
        self.insert(block_hash, reason)

    def insert(self, block_hash: bytes, reason: str) -> None:
        self._entries[block_hash] = reason
        self._entries.move_to_end(block_hash)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self.evicted += 1
            tree_metrics.invalid_evicted()
        tree_metrics.set_invalid(len(self._entries), self.capacity)


class ReorgTracker:
    """Reorg-depth accounting with storm detection and backoff.

    ``record(depth)`` returns True when the records within ``window_s``
    cross either trip wire (``storm_count`` reorgs, or ``storm_depth``
    total abandoned blocks) and a storm newly engages. While a storm is
    live every further reorg extends the backoff (capped exponential);
    :meth:`in_backoff` is the engine's cue to stop feeding the
    speculative paths until forkchoice calms down.
    """

    def __init__(self, window_s: float | None = None,
                 storm_count: int | None = None,
                 storm_depth: int | None = None,
                 backoff_s: float | None = None,
                 clock=time.monotonic):
        self.window_s = (window_s if window_s is not None
                         else _env_float("RETH_TPU_REORG_STORM_WINDOW", 30.0))
        self.storm_count = (storm_count if storm_count is not None
                            else _env_int("RETH_TPU_REORG_STORM_COUNT", 6))
        self.storm_depth = (storm_depth if storm_depth is not None
                            else _env_int("RETH_TPU_REORG_STORM_DEPTH", 16))
        self.base_backoff_s = (backoff_s if backoff_s is not None
                               else _env_float("RETH_TPU_REORG_BACKOFF", 10.0))
        self._clock = clock
        self._events: list[tuple[float, int]] = []  # (ts, depth)
        self._backoff_until = 0.0
        self._backoff_s = self.base_backoff_s
        self.reorgs = 0
        self.max_depth = 0
        self.storms = 0

    def record(self, depth: int) -> bool:
        """Account one reorg of ``depth`` abandoned blocks; True when a
        storm newly engages (caller dumps the flight recorder once)."""
        if depth <= 0:
            return False
        now = self._clock()
        self.reorgs += 1
        self.max_depth = max(self.max_depth, depth)
        self._events.append((now, depth))
        cutoff = now - self.window_s
        self._events = [(t, d) for t, d in self._events if t >= cutoff]
        stormy = (len(self._events) >= self.storm_count
                  or sum(d for _, d in self._events) >= self.storm_depth)
        if not stormy:
            return False
        newly = now >= self._backoff_until
        if newly:
            self.storms += 1
            self._backoff_s = self.base_backoff_s
        else:
            self._backoff_s = min(self._backoff_s * 2, 120.0)
        self._backoff_until = now + self._backoff_s
        return newly

    def in_backoff(self) -> bool:
        active = self._clock() < self._backoff_until
        tree_metrics.set_backoff(active)
        return active
