"""Dev-mode local miner: drives the FCU/payload loop without a CL.

Reference analogue: `LocalMiner` (crates/engine/local/src/lib.rs) — in
dev mode the node mines its own blocks from the pool on an interval or
on demand.
"""

from __future__ import annotations

from ..consensus.validation import calc_next_base_fee
from ..payload import PayloadAttributes, build_payload
from .tree import EngineTree, PayloadStatusKind


class LocalMiner:
    def __init__(self, tree: EngineTree, pool, block_time: int = 12,
                 producer=None):
        self.tree = tree
        self.pool = pool
        self.block_time = block_time
        # continuous-build mode: seal the producer's hot candidate instead
        # of running a fresh greedy build per block
        self.producer = producer
        self.producer_seals = 0
        self.serial_builds = 0

    def mine_block(self, timestamp: int | None = None):
        """Build one block from the pool, submit it, make it canonical."""
        head = self.tree.head_hash
        overlay = self.tree.overlay_provider(head)
        parent = overlay.header_by_number(overlay.block_number(head))
        ts = timestamp if timestamp is not None else parent.timestamp + self.block_time
        # instant sealing can produce several blocks per wall-clock second;
        # consensus requires strictly increasing timestamps (geth dev mode
        # applies the same clamp)
        attrs = PayloadAttributes(timestamp=max(ts, parent.timestamp + 1))
        block = None
        if self.producer is not None:
            try:
                block, _fees = self.producer.take(head, attrs)
                self.producer_seals += 1
            except Exception:  # noqa: BLE001 — the serial build is always
                block = None   # the fallback; mining must not fail
        if block is None:
            block, _fees = build_payload(self.tree, self.pool, head, attrs)
            self.serial_builds += 1
        st = self.tree.on_new_payload(block)
        if st.status is not PayloadStatusKind.VALID:
            raise RuntimeError(f"self-mined block invalid: {st.validation_error}")
        self.tree.on_forkchoice_updated(block.hash)
        next_blob_fee = None
        if block.header.excess_blob_gas is not None:
            from ..evm.executor import blob_base_fee, next_excess_blob_gas

            params = self.tree.config.blob_params_for(
                block.header.number + 1, block.header.timestamp)
            next_blob_fee = blob_base_fee(next_excess_blob_gas(
                block.header.excess_blob_gas, block.header.blob_gas_used or 0,
                params.target_gas), params.update_fraction)
        self.pool.on_canonical_state_change(calc_next_base_fee(block.header),
                                            blob_base_fee=next_blob_fee)
        return block
