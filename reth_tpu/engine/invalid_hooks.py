"""Invalid-block hooks: dump a debug witness when a payload fails.

Reference analogue: crates/engine/invalid-block-hooks/src/witness.rs —
on a bad block (state-root mismatch, post-execution failure) the tree
invokes installed hooks with everything needed for offline diagnosis.
The witness file carries the block RLP, the divergence, and the
execution output's state delta in hex — enough to replay the block
elsewhere and bisect executor-vs-trie disagreements.
"""

from __future__ import annotations

import json
from pathlib import Path


class InvalidBlockWitnessHook:
    """Writes one JSON witness per invalid block into ``directory``."""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def __call__(self, block, reason: str, out=None,
                 computed_root: bytes | None = None) -> Path:
        witness = {
            "blockNumber": block.header.number,
            "blockHash": "0x" + block.hash.hex(),
            "reason": reason,
            "headerStateRoot": "0x" + block.header.state_root.hex(),
            "computedStateRoot": (
                "0x" + computed_root.hex() if computed_root else None
            ),
            "blockRlp": "0x" + block.encode().hex(),
        }
        if out is not None:
            witness["gasUsed"] = out.gas_used
            witness["postAccounts"] = {
                "0x" + a.hex(): (
                    None if acct is None else {
                        "nonce": acct.nonce,
                        "balance": str(acct.balance),
                        "codeHash": "0x" + acct.code_hash.hex(),
                    }
                )
                for a, acct in out.post_accounts.items()
            }
            witness["postStorage"] = {
                "0x" + a.hex(): {
                    "0x" + s.hex(): hex(v) for s, v in slots.items()
                }
                for a, slots in out.post_storage.items()
            }
        path = self.dir / f"{block.header.number}_{block.hash.hex()[:8]}.json"
        path.write_text(json.dumps(witness, indent=1))
        return path
