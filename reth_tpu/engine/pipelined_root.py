"""Pipelined live-tip state root: hash dirty keys WHILE the block executes.

Reference analogue: the background state-root task fed by per-tx
`OnStateHook` updates (crates/trie/parallel/src/state_root_task.rs:20-100
+ crates/engine/tree/src/tree/state_root_strategy/sparse_trie.rs:126-259).
There, execution streams `EvmState` per transaction into a concurrently
running sparse-trie job. Here the streamed unit is the block's dirty KEY
set: a worker thread batch-hashes newly touched addresses/slots on the
device as they arrive, so by the time execution finishes, the keccak
digests the incremental root needs are already resident — the root
commit only hashes stragglers (e.g. withdrawal targets) and walks the
trie. The device hashes while the CPU interprets: the two real resources
of this design overlap instead of serializing.
"""

from __future__ import annotations

import queue
import threading
import time


class PipelinedStateRoot:
    """Streaming key-hash worker for one block's execution."""

    def __init__(self, hasher):
        self.hasher = hasher
        self._queue: queue.Queue = queue.Queue()
        self._digests: dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        self._sent: set[bytes] = set()
        self.batches_hashed = 0
        self.batches_failed = 0
        self.hash_spans: list[tuple[float, float]] = []  # worker activity
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- execution-side hook (called after every transaction) ---------------

    def on_state_update(self, keys) -> None:
        """Queue newly touched plain keys — 20-byte addresses and
        ``(address, slot)`` pairs (slots are hashed standalone; the pair
        form exists for the sparse strategy, which needs the owner)."""
        flat = [k if isinstance(k, bytes) else k[1] for k in keys]
        fresh = [k for k in flat if k not in self._sent]
        if not fresh:
            return
        self._sent.update(fresh)
        self._queue.put(fresh)

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self._queue.get()
            if batch is None:
                return
            t0 = time.monotonic()
            try:
                digests = self.hasher(batch)
            except Exception:  # noqa: BLE001 — a dying worker would silently
                # serialize ALL hashing into finish(); with a supervised
                # hasher (ops/supervisor.py) failures route to the CPU and
                # never land here, but an unsupervised device hasher must
                # not take the stream down — the keys re-hash in finish()
                with self._lock:
                    self.batches_failed += 1
                continue
            with self._lock:
                for k, d in zip(batch, digests):
                    self._digests[k] = d
                self.batches_hashed += 1
                self.hash_spans.append((t0, time.monotonic()))

    # -- finalization --------------------------------------------------------

    def finish(self, all_keys) -> dict[bytes, bytes]:
        """Drain the worker and return digests for ``all_keys`` (stragglers
        the stream never saw are hashed here, in one batch)."""
        self._queue.put(None)
        self._thread.join()
        missing = [k for k in all_keys if k not in self._digests]
        if missing:
            for k, d in zip(missing, self.hasher(missing)):
                self._digests[k] = d
        return self._digests
