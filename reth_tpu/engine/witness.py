"""Execution witness generation: everything needed to re-execute a block
statelessly against its parent.

Reference analogue: `debug_executionWitness`
(crates/rpc/rpc/src/debug.rs), the invalid-block witness hook
(crates/engine/invalid-block-hooks/src/witness.rs), and revm's witness
recording (crates/revm/src/witness.rs). Format follows the reference's
ExecutionWitness: `state` (parent-state trie nodes), `codes` (touched
bytecodes), `keys` (touched preimages), `headers` (RLP ancestor headers
for BLOCKHASH + the parent).

The witness is CLOSED under trie edits: after collecting the touched-key
multiproof, the block's state delta is applied to a sparse trie revealed
from it; any `BlindedNodeError` (a delete collapsing into an unrevealed
sibling) reveals that path from the parent view and adds it to the
witness, so a stateless validator can replay the block without a state
source (reference sparse-trie reveal-on-demand, done ahead of time here
because the consumer has nobody to ask).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..evm.executor import BlockExecutor, StateSource
from ..primitives.keccak import keccak256
from ..primitives.types import Block, Header
from ..trie.proof import ProofCalculator
from ..trie.sparse import BlindedNodeError, SparseStateTrie
from .stateless import apply_output_to_trie


@dataclass
class ExecutionWitness:
    """Self-contained stateless re-execution input for one block."""

    state: list[bytes] = field(default_factory=list)    # trie node RLPs
    codes: list[bytes] = field(default_factory=list)    # bytecodes
    keys: list[bytes] = field(default_factory=list)     # address/slot preimages
    headers: list[bytes] = field(default_factory=list)  # RLP headers

    def to_json(self) -> dict:
        return {
            "state": ["0x" + n.hex() for n in self.state],
            "codes": ["0x" + c.hex() for c in self.codes],
            "keys": ["0x" + k.hex() for k in self.keys],
            "headers": ["0x" + h.hex() for h in self.headers],
        }

    @classmethod
    def from_json(cls, obj: dict) -> "ExecutionWitness":
        unhex = lambda x: bytes.fromhex(x[2:] if x.startswith("0x") else x)  # noqa: E731
        return cls(
            state=[unhex(n) for n in obj.get("state", [])],
            codes=[unhex(c) for c in obj.get("codes", [])],
            keys=[unhex(k) for k in obj.get("keys", [])],
            headers=[unhex(h) for h in obj.get("headers", [])],
        )


class RecordingStateSource(StateSource):
    """Wraps a provider view, recording every read the EVM makes."""

    def __init__(self, provider):
        self.provider = provider
        self.addresses: set[bytes] = set()
        self.slots: dict[bytes, set[bytes]] = {}
        self.code_hashes: set[bytes] = set()

    def account(self, address: bytes):
        self.addresses.add(address)
        return self.provider.account(address)

    def storage(self, address: bytes, slot: bytes) -> int:
        self.addresses.add(address)
        self.slots.setdefault(address, set()).add(slot)
        return self.provider.storage(address, slot)

    def bytecode(self, code_hash: bytes) -> bytes:
        self.code_hashes.add(code_hash)
        return self.provider.bytecode(code_hash) or b""


class _RecordingHashes(dict):
    """BLOCKHASH window that records which block numbers the EVM read, so
    the witness ships exactly the ancestor headers a stateless replay needs
    (reference ExecutionWitness `headers`)."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.read: set[int] = set()

    def get(self, key, default=None):
        self.read.add(key)
        return super().get(key, default)

    def __getitem__(self, key):
        self.read.add(key)
        return super().__getitem__(key)


def generate_witness(parent_provider, block: Block, committer,
                     senders: list[bytes] | None = None,
                     parent_header: Header | None = None,
                     config=None,
                     block_hashes: dict[int, bytes] | None = None,
                     provider_factory=None,
                     proof_workers: int | None = None) -> ExecutionWitness:
    """Execute ``block`` against the parent view, recording reads, and
    assemble a closed witness. ``parent_provider`` must present the state
    AS OF the parent block (trie tables + hashed/plain state);
    ``block_hashes`` supplies the BLOCKHASH window when the parent view
    (e.g. a historical provider) cannot. With ``provider_factory`` (a
    zero-arg callable yielding fresh parent views) the touched-key
    multiproof shards by storage trie across the proof-worker pool
    (``trie/proof.py`` ProofWorkerPool) instead of serializing per trie
    — big witnesses stop being a single-threaded walk."""
    src = RecordingStateSource(parent_provider)
    executor = BlockExecutor(src, config)
    if senders is None:
        senders = [tx.recover_sender() for tx in block.transactions]
    # BLOCKHASH window served (and recorded) from canonical headers
    hashes = _RecordingHashes(block_hashes or {})
    headers: list[bytes] = []
    if parent_header is None and hasattr(parent_provider, "header_by_number"):
        parent_header = parent_provider.header_by_number(block.header.number - 1)
    if parent_header is not None:
        headers.append(parent_header.encode())
    lo = max(0, block.header.number - 256)
    if not hashes and hasattr(parent_provider, "canonical_hash"):
        for n in range(lo, block.header.number):
            h = parent_provider.canonical_hash(n)
            if h is not None:
                hashes[n] = h
    out = executor.execute(block, senders, hashes)

    # ship the ancestor headers BLOCKHASH actually read — as a contiguous
    # hash-linked chain down from the parent, since a stateless validator
    # can only authenticate header N-k through its child at N-k+1
    read = {n for n in hashes.read if lo <= n < block.header.number - 1}
    if read and hasattr(parent_provider, "header_by_number"):
        for n in range(block.header.number - 2, min(read) - 1, -1):
            hdr = parent_provider.header_by_number(n)
            if hdr is None:
                break
            headers.append(hdr.encode())

    # the executor also writes: fee recipient, withdrawals, created/deleted
    touched = set(src.addresses) | set(out.post_accounts)
    slots = {a: set(s) for a, s in src.slots.items()}
    for a, ps in out.post_storage.items():
        slots.setdefault(a, set()).update(ps)
    targets = {a: sorted(slots.get(a, ())) for a in sorted(touched)}

    # witness generation is RPC work: ride the proof (lowest) hash-service
    # lane — its multiproofs coalesce with other clients' batches but
    # never delay the live tip (identity without a service)
    if hasattr(committer, "for_lane"):
        committer = committer.for_lane("proof")
    calc = ProofCalculator(parent_provider, committer)
    if provider_factory is not None:
        from ..trie.proof import ProofWorkerPool

        pool = ProofWorkerPool(
            lambda: ProofCalculator(provider_factory(), committer),
            workers=proof_workers)
        try:
            proofs = pool.multiproof(targets)
        finally:
            pool.shutdown()
    else:
        proofs = calc.multiproof(targets)
    nodes: dict[bytes, bytes] = {}
    for ap in proofs.values():
        for n in ap.proof:
            nodes[keccak256(n)] = n
        for sp in ap.storage_proofs:
            for n in sp.proof:
                nodes[keccak256(n)] = n

    # close the witness under the block's own trie edits: reveal, apply,
    # and feed back any sibling paths a collapse needs
    parent_root = (parent_header.state_root if parent_header is not None
                   else parent_provider.header_by_number(
                       block.header.number - 1).state_root)
    for _attempt in range(64):
        st = SparseStateTrie.anchored(parent_root)
        all_nodes = list(nodes.values())
        st.reveal_account(all_nodes)
        for a in targets:
            ap = proofs.get(a)
            if ap is not None and ap.account is not None:
                st.reveal_storage(keccak256(a), ap.storage_root, all_nodes)
        try:
            apply_output_to_trie(st, out, committer.hasher)
            break
        except BlindedNodeError as e:
            extra = (calc.storage_spine_for_path(e.owner, e.path)
                     if e.owner is not None else calc.spine_for_path(e.path))
            new = False
            for n in extra:
                if keccak256(n) not in nodes:
                    nodes[keccak256(n)] = n
                    new = True
            if not new:
                raise  # witness cannot be closed; bail loudly
    codes = []
    seen = set()
    for ch in src.code_hashes:
        code = parent_provider.bytecode(ch)
        if code and ch not in seen:
            seen.add(ch)
            codes.append(code)
    keys = [a for a in targets]
    for a in targets:
        keys.extend(targets[a])
    return ExecutionWitness(
        state=list(nodes.values()), codes=codes, keys=keys, headers=headers,
    )
