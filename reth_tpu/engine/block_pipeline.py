"""Cross-block import pipeline: execute block N+1 while block N commits.

The engine imports one block at a time; with the commit dispatches
collapsed into whole-subtrie kernels the remaining back-to-back wall is
the *serialization* of execution and commitment across the block
boundary.  This module overlaps them, two deep:

* When block N's insert reaches its state-root phase the tree publishes
  a **commit window** — N's identity plus a frozen snapshot of its
  uncommitted plain-state overlay layer (header/body/exec output; the
  commit phase itself only writes the *hashed*/trie tables, so the
  snapshot is complete for execution purposes the moment it is taken).
* If ``on_new_payload(N+1)`` arrives while that window is open, the
  transport thread does not buffer-and-SYNCING: it **speculates** —
  optimistic execution (engine/optimistic.py) of N+1 over a merged
  overlay of N's ancestors plus N's uncommitted write set, with the
  touched keys pre-hashed concurrently on a double-buffered sub-mesh
  (ops/hash_service.py ``pipeline_lease``) while N's commit dispatches
  keep the remaining devices.
* When N's window closes VALID, the speculative output is **adopted**:
  N+1 re-enters the normal insert path with its execution pre-done and
  its key digests pre-hashed, so only post-validation + its own commit
  remain.  Roots stay bit-identical to serial import by construction —
  nothing speculative is ever written; adoption feeds the standard
  root/consensus checks exactly as a fresh execution would.
* If N's root mismatches, N turns out INVALID, or an fcU reorgs past
  the speculation, the abort ladder (PR 12's cooperative-cancellation
  substrate: cancel events → ``ExecCancelled`` at wave boundaries)
  discards the speculation and N+1 falls back to the normal
  buffer/replay path — it is never wrongly marked INVALID.

Reference analogue: reth's in-flight payload processing overlapping the
persistence service across blocks (crates/engine/tree), lifted to full
execute-while-commit as in the Reddio async-storage design
(arxiv 2503.04595), one level up the stack.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .. import tracing
from ..metrics import block_pipeline_metrics
from ..storage.overlay import Layer, OverlayTx
from ..storage.provider import DatabaseProvider


@dataclass
class CommitWindow:
    """Block N's commit-in-progress handle: identity + the frozen
    overlay snapshot a speculative child executes over."""

    block: object                      # primitives Block
    block_hash: bytes
    parent_hash: bytes
    number: int
    parent_layers: list[Layer]         # N's ancestors (frozen)
    exec_layer: Layer                  # N's plain-state writes (frozen copy)
    opened: float = field(default_factory=time.monotonic)
    closed: float | None = None
    ok: bool | None = None
    done: threading.Event = field(default_factory=threading.Event)

    @property
    def wall(self) -> float:
        end = self.closed if self.closed is not None else time.monotonic()
        return max(0.0, end - self.opened)


@dataclass
class _Speculation:
    """The one in-flight speculative execution (N+1 over N's window)."""

    block_hash: bytes
    parent_hash: bytes
    cancel: threading.Event = field(default_factory=threading.Event)
    abort_reason: str | None = None


@dataclass
class SpeculationResult:
    """A finished speculative execution, ready for adoption by the
    normal insert path once the parent's window closes VALID."""

    out: object                        # ExecutionOutput
    stats: object                      # optimistic scheduler stats (or None)
    senders: list[bytes]
    keys: list                         # touched keys, first-seen order
    digests: dict[bytes, bytes]        # pre-hashed key digests
    cache: object                      # warmed ExecutionCache
    exec_start: float = 0.0
    exec_end: float = 0.0


class _SpecPrehash:
    """Background key pre-hash for the speculative block: drains batches
    of touched keys and keccaks them on the double-buffered sub-mesh
    (when leased) or the proof lane, so the adopted sparse task starts
    with its digest map already populated."""

    def __init__(self, hasher, min_batch: int = 64):
        self._hasher = hasher
        self._min_batch = min_batch
        self._pending: list = []
        self._seen: set = set()
        self.digests: dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._stop = False
        self._failed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, keys) -> None:
        if self._failed:
            return
        with self._cond:
            fresh = [k for k in keys if k not in self._seen]
            if not fresh:
                return
            self._seen.update(fresh)
            self._pending.extend(fresh)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait()
                batch, self._pending = self._pending, []
                if not batch and self._stop:
                    return
            # flatten (addr, slot) pairs: both legs hash independently
            msgs: list[bytes] = []
            for k in batch:
                if isinstance(k, tuple):
                    msgs.extend(k)
                else:
                    msgs.append(k)
            msgs = [m for m in dict.fromkeys(msgs) if m not in self.digests]
            if msgs:
                try:
                    for m, d in zip(msgs, self._hasher(msgs)):
                        self.digests[m] = bytes(d)
                except Exception:  # noqa: BLE001 — prehash is best-effort:
                    # a failed batch just means the sparse task hashes
                    # those keys itself at adoption
                    self._failed = True
                    return

    def finish(self, timeout: float = 10.0) -> dict[bytes, bytes]:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout)
        return {} if self._failed else dict(self.digests)


class BlockPipeline:
    """Two-deep cross-block import pipeline attached to an EngineTree.

    The tree calls :meth:`open_commit` / :meth:`close_commit` around its
    state-root phase and :meth:`try_speculate` from ``on_new_payload``
    when a payload's parent is the block currently committing.
    """

    def __init__(self, tree, depth: int = 2, wait_s: float = 300.0):
        self.tree = tree
        # depth 1 = serial (the tree does not construct a pipeline then);
        # anything >= 2 currently means one speculation deep — the window
        # snapshot chains are not stacked further yet
        self.depth = max(2, int(depth))
        self.wait_s = wait_s
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._window: CommitWindow | None = None
        self._spec: _Speculation | None = None
        self._recent_closed: dict[bytes, bool] = {}
        # bench/test accounting (monotonic totals; metrics mirror them)
        self.speculations = 0
        self.adopted = 0
        self.aborted = 0
        self.abort_reasons: dict[str, int] = {}
        self.exec_wall_s = 0.0       # execution wall seen by the tree
        self.commit_wall_s = 0.0     # commit-window wall (open→close)
        self.overlap_wall_s = 0.0    # speculative exec inside a window
        self.last_overlap_fraction = 0.0
        self.leases_active = 0
        # commit-window observers beyond the tree itself: the continuous
        # block producer subscribes so N+1's candidate starts building the
        # moment N's window opens (called OUTSIDE the pipeline lock)
        self.open_listeners: list = []
        block_pipeline_metrics.set_depth(self.depth)

    # -- commit window (called from the insert thread) ----------------------

    def open_commit(self, block, block_hash: bytes,
                    parent_layers: list[Layer], layer: Layer) -> CommitWindow:
        """Publish block N's commit-in-progress: freeze a shallow copy of
        its overlay layer (taken synchronously on the insert thread,
        BEFORE the commit phase starts writing hashed/trie tables, so no
        concurrent mutation can race the copy)."""
        exec_layer: Layer = {t: dict(kv) for t, kv in layer.items()}
        win = CommitWindow(block=block, block_hash=block_hash,
                           parent_hash=block.header.parent_hash,
                           number=block.header.number,
                           parent_layers=list(parent_layers or []),
                           exec_layer=exec_layer)
        with self._cond:
            self._window = win
            self._cond.notify_all()
        block_pipeline_metrics.window_opened()
        for fn in list(self.open_listeners):
            try:
                fn(win)
            except Exception:  # noqa: BLE001 — an observer must never
                pass           # stall the insert thread
        return win

    def current_window(self):
        """The commit window currently open, or None."""
        with self._lock:
            return self._window

    def close_commit(self, win: CommitWindow, ok: bool) -> None:
        """Close N's window (idempotent; called on EVERY insert exit
        path). ``ok`` means N is VALID *and* visible in ``tree.blocks``
        — only then may a speculation be adopted on top of it."""
        with self._cond:
            if win.done.is_set():
                return
            win.ok = ok
            win.closed = time.monotonic()
            win.done.set()
            if self._window is win:
                self._window = None
            self._recent_closed[win.block_hash] = ok
            while len(self._recent_closed) > 16:
                self._recent_closed.pop(next(iter(self._recent_closed)))
            spec = self._spec
            self.commit_wall_s += win.wall
            self._cond.notify_all()
        if not ok and spec is not None and spec.parent_hash == win.block_hash:
            # N failed: stop the speculative waves at their next boundary
            # instead of letting them finish for a dead parent
            self._abort_spec(spec, "parent_invalid")
        block_pipeline_metrics.window_closed(ok, win.wall)

    def note_exec_wall(self, seconds: float) -> None:
        """The tree reports each block's execution wall (serial or
        speculative) so the bench can compare overlap against legs."""
        self.exec_wall_s += seconds

    # -- abort ladder -------------------------------------------------------

    def _abort_spec(self, spec: _Speculation, reason: str) -> None:
        if spec.abort_reason is None:
            spec.abort_reason = reason
        spec.cancel.set()

    def on_forkchoice(self, head: bytes) -> None:
        """A forkchoiceUpdated landed: if it reorgs past the in-flight
        speculation (the new head neither IS the speculated block, nor
        its committing parent, nor extends that parent), abort it
        cooperatively — ExecCancelled at the next wave boundary."""
        with self._lock:
            spec = self._spec
        if spec is None:
            return
        if head in (spec.block_hash, spec.parent_hash):
            return
        if self.tree._extends(head, spec.parent_hash):
            return
        self._abort_spec(spec, "fcu_reorg")
        tracing.event("engine::pipeline", "speculation_cancelled",
                      block=spec.block_hash.hex()[:16],
                      new_head=head.hex()[:16])

    # -- speculation (called from the payload transport thread) -------------

    def try_speculate(self, block) -> object | None:
        """Payload N+1 arrived while its parent N commits: execute it
        speculatively over N's uncommitted overlay, wait for N's window
        to close, and adopt the result through the normal insert path.

        Returns a PayloadStatus when the pipeline fully handled the
        payload, or None to fall back to the normal buffer/SYNCING path
        (never an INVALID of its own — only the normal path judges)."""
        tree = self.tree
        if tree.reorgs.in_backoff():
            return None  # reorg storm: speculation is what the churn thrashes
        with self._cond:
            win = self._window
            if (win is None or win.done.is_set()
                    or win.block_hash != block.header.parent_hash
                    or self._spec is not None):
                return None
            spec = _Speculation(block_hash=block.hash,
                                parent_hash=win.block_hash)
            self._spec = spec
        self.speculations += 1
        block_pipeline_metrics.speculation_started()
        tracing.event("engine::pipeline", "speculation_started",
                      block=spec.block_hash.hex()[:16],
                      parent=spec.parent_hash.hex()[:16])
        lease = self._acquire_lease()
        result = None
        try:
            result = self._speculate(block, win, spec, lease)
        finally:
            if lease is not None:
                lease.release()
                with self._lock:
                    self.leases_active -= 1
        if result is None:
            return self._finish_abort(spec)
        # wait for N's verdict; the speculative work is done, so this
        # wait is the residue of commit-minus-exec, not added latency
        win.done.wait(self.wait_s)
        if spec.cancel.is_set() or not win.done.is_set() or not win.ok:
            if not win.done.is_set():
                self._abort_spec(spec, "parent_timeout")
            elif spec.abort_reason is None:
                self._abort_spec(spec, "parent_invalid")
            return self._finish_abort(spec)
        parent_layers = tree._chain_layers(block.header.parent_hash)
        if parent_layers is None:
            self._abort_spec(spec, "parent_missing")
            return self._finish_abort(spec)
        # adopt: re-enter the normal insert with execution pre-done; all
        # consensus/root checks run exactly as a fresh execution's would.
        # The speculation slot clears FIRST — the adoption insert opens
        # its own commit window, and the NEXT payload must be able to
        # speculate over it (that chaining is the whole pipeline); fcU
        # aborts from here on ride the normal in-flight insert machinery
        with self._lock:
            self._spec = None
        st = tree._validate_and_insert(block, parent_layers,
                                       pre_executed=result)
        self.adopted += 1
        overlap = max(0.0, min(result.exec_end, win.closed)
                      - result.exec_start)
        frac = overlap / win.wall if win.wall > 1e-9 else 0.0
        self.overlap_wall_s += overlap
        self.last_overlap_fraction = frac
        block_pipeline_metrics.speculation_adopted(frac)
        tracing.event("engine::pipeline", "speculation_adopted",
                      block=spec.block_hash.hex()[:16],
                      overlap_fraction=round(frac, 3))
        return st

    def _finish_abort(self, spec: _Speculation):
        with self._lock:
            self._spec = None
        reason = spec.abort_reason or "exec_error"
        self.aborted += 1
        self.abort_reasons[reason] = self.abort_reasons.get(reason, 0) + 1
        block_pipeline_metrics.speculation_aborted(reason)
        tracing.event("engine::pipeline", "speculation_aborted",
                      block=spec.block_hash.hex()[:16], reason=reason)
        return None

    def _acquire_lease(self):
        """Double-buffer: carve a sub-mesh for the speculative side's
        prehash dispatches; the committing block's lanes re-form over the
        remaining devices. No mesh (or exhausted) → run without."""
        svc = getattr(self.tree.committer, "hash_service", None)
        if svc is None or getattr(svc, "mesh", None) is None:
            return None
        try:
            lease = svc.pipeline_lease()
        except Exception:  # noqa: BLE001 — the lease is an optimization
            return None
        if lease is not None:
            with self._lock:
                self.leases_active += 1
            block_pipeline_metrics.lease_taken(lease.devices)
        return lease

    def _speculate(self, block, win: CommitWindow, spec: _Speculation,
                   lease) -> SpeculationResult | None:
        """Execute ``block`` over its parent's uncommitted overlay.
        Returns None (with spec.abort_reason set) on any failure — the
        normal path re-runs and judges the payload then."""
        tree = self.tree
        header = block.header
        # the speculative stage starts HERE: prevalidation, sender
        # recovery, and overlay setup are all work the serial import
        # would do after N's commit — count them in the overlap
        t0 = time.monotonic()
        wall_t0 = time.time()  # span timestamps are wall-clock
        try:
            tree.consensus.validate_header_against_parent(
                header, win.block.header)
            tree.consensus.validate_block_pre_execution(block)
        except Exception:  # noqa: BLE001 — let the normal path report it
            self._abort_spec(spec, "prevalidate")
            return None
        from ..primitives.types import recover_senders

        senders = recover_senders(block.transactions)
        if any(s is None for s in senders):
            self._abort_spec(spec, "prevalidate")
            return None
        # merged overlay: N's ancestors + N's uncommitted-but-known
        # write set (the frozen snapshot), newest layer last
        layers = win.parent_layers + [win.exec_layer]
        overlay = DatabaseProvider(
            OverlayTx(tree.factory.db.tx(), layers))
        hashes = {}
        for k in range(max(0, header.number - 256), header.number):
            bh = overlay.canonical_hash(k)
            if bh:
                hashes[k] = bh
        from ..evm.executor import ProviderStateSource
        from .execution_cache import CachedStateSource, ExecutionCache

        cache = ExecutionCache()
        source = CachedStateSource(ProviderStateSource(overlay), cache)
        hasher = lease.hash if lease is not None else self._lane_hasher()
        prehash = _SpecPrehash(hasher)
        keys: list = []
        seen: set = set()

        def state_hook(batch):
            fresh = [k for k in batch if k not in seen]
            if not fresh:
                return
            seen.update(fresh)
            keys.extend(fresh)
            prehash.submit(fresh)

        try:
            out, stats = self._execute(block, senders, source, hashes,
                                       state_hook, spec)
        except _SpecAborted as e:
            self._abort_spec(spec, e.reason)
            prehash.finish(timeout=1.0)
            return None
        t1 = time.monotonic()
        digests = prehash.finish()
        # the speculative window as a span on N+1's (future) timeline:
        # debug_blockTimeline then shows it overlapping N's state_root.
        # The timeline must be pre-registered — this span lands before
        # N+1's own trace_block opens — and the span carries a synthetic
        # parent id so it never shadows the lifecycle root in summaries.
        tracing.ensure_timeline(block.hash.hex())
        tracing.record_span(
            "engine::pipeline", "speculate.exec", wall_t0, t1 - t0,
            ctx=tracing.TraceContext(block.hash.hex(), "speculation"),
            fields={"txs": len(block.transactions),
                    "parent": win.block_hash.hex()[:16]})
        return SpeculationResult(out=out, stats=stats, senders=senders,
                                 keys=keys, digests=digests, cache=cache,
                                 exec_start=t0, exec_end=t1)

    def _lane_hasher(self):
        committer = self.tree.committer
        if getattr(committer, "hash_service", None) is not None \
                and hasattr(committer, "for_lane"):
            return committer.for_lane("proof").hasher
        return committer.hasher

    def _execute(self, block, senders, source, hashes, state_hook, spec):
        """Run the speculative execution: the PR 7 optimistic scheduler
        (its speculative first attempt doubles as the prewarm + key
        stream), serial executor for tiny blocks; the spec's cancel
        event aborts at wave boundaries."""
        tree = self.tree
        from .optimistic import ExecCancelled, execute_block_optimistic

        try:
            if len(block.transactions) >= 2:
                return execute_block_optimistic(
                    source, block, senders, tree.config,
                    max_workers=tree.exec_workers, state_hook=state_hook,
                    block_hashes=hashes, cancel_event=spec.cancel)
            from ..evm import BlockExecutor

            if spec.cancel.is_set():
                raise _SpecAborted(spec.abort_reason or "cancelled")
            out = BlockExecutor(source, tree.config).execute(
                block, senders, hashes, state_hook=state_hook)
            return out, None
        except ExecCancelled:
            raise _SpecAborted(spec.abort_reason or "cancelled") from None
        except _SpecAborted:
            raise
        except Exception as e:  # noqa: BLE001 — a speculative failure is
            # never a verdict: the normal path re-executes and judges
            tracing.event("engine::pipeline", "speculation_exec_error",
                          error=str(e)[:120])
            raise _SpecAborted("exec_error") from e

    # -- driver support -----------------------------------------------------

    def wait_commit_open(self, block_hash: bytes, timeout: float = 30.0) -> bool:
        """Block until ``block_hash``'s commit window opens (True) or its
        insert already finished / the wait times out (False). Import
        drivers use this to land the next payload mid-commit."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                win = self._window
                if (win is not None and win.block_hash == block_hash
                        and not win.done.is_set()):
                    return True
                if (block_hash in self._recent_closed
                        or block_hash in self.tree.blocks
                        or self.tree.invalid.get(block_hash) is not None):
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def stats_snapshot(self) -> dict:
        with self._lock:
            return {
                "depth": self.depth,
                "speculations": self.speculations,
                "adopted": self.adopted,
                "aborted": self.aborted,
                "abort_reasons": dict(self.abort_reasons),
                "exec_wall_s": self.exec_wall_s,
                "commit_wall_s": self.commit_wall_s,
                "overlap_wall_s": self.overlap_wall_s,
                "overlap_fraction": (
                    self.overlap_wall_s / self.commit_wall_s
                    if self.commit_wall_s > 1e-9 else 0.0),
                "leases_active": self.leases_active,
            }


class _SpecAborted(Exception):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def import_chain(tree, blocks, fcu: bool = True, overlap: bool = True,
                 wait_s: float = 30.0, payload_timeout: float = 120.0):
    """Back-to-back import driver: feed ``blocks`` into ``tree``.

    With ``overlap`` (and a pipeline attached), each block is submitted
    the moment its parent enters its commit window, so consecutive
    blocks overlap exec-with-commit; otherwise strictly serial.
    forkchoiceUpdated calls are issued in block order from the caller
    thread. Returns the list of PayloadStatus, one per block.
    """
    from concurrent.futures import ThreadPoolExecutor

    from .tree import PayloadStatusKind

    def _import_one(blk):
        deadline = time.monotonic() + payload_timeout
        st = tree.on_new_payload(blk)
        while st.status is PayloadStatusKind.SYNCING \
                and time.monotonic() <= deadline:
            # parent insert still in flight (or the speculation aborted
            # benignly): either the parent's thread replays the buffered
            # block itself, or — once it sits in the buffer with its
            # parent known — we resubmit; never both at once
            if blk.hash in tree.blocks:
                return tree.on_new_payload(blk)  # replay imported it
            if tree.invalid.get(blk.hash) is not None:
                return tree.on_new_payload(blk)
            if (tree.buffered.get(blk.hash) is not None
                    and blk.header.parent_hash in tree.blocks):
                st = tree.on_new_payload(blk)
                continue
            time.sleep(0.002)
        return st

    pipelined = overlap and getattr(tree, "pipeline", None) is not None
    statuses: list = []
    if not pipelined:
        for blk in blocks:
            st = _import_one(blk)
            statuses.append(st)
            if fcu and st.status is PayloadStatusKind.VALID:
                tree.on_forkchoice_updated(blk.hash)
        return statuses
    pool = ThreadPoolExecutor(max_workers=2,
                              thread_name_prefix="import-pipeline")
    futs: list = []
    fcu_idx = 0
    try:
        for i, blk in enumerate(blocks):
            if i > 0:
                # land this payload mid-commit of its parent (or, if the
                # parent never opened a window, after its insert)
                if not tree.pipeline.wait_commit_open(blocks[i - 1].hash,
                                                      wait_s):
                    futs[i - 1].result()
            futs.append(pool.submit(_import_one, blk))
            while fcu_idx < i and futs[fcu_idx].done():
                st = futs[fcu_idx].result()
                if fcu and st.status is PayloadStatusKind.VALID:
                    tree.on_forkchoice_updated(blocks[fcu_idx].hash)
                fcu_idx += 1
        for j, fut in enumerate(futs):
            st = fut.result()
            statuses.append(st)
            if fcu and j >= fcu_idx and st.status is PayloadStatusKind.VALID:
                tree.on_forkchoice_updated(blocks[j].hash)
        return statuses
    finally:
        pool.shutdown(wait=True)
