"""Prewarm: parallel optimistic execution to warm the state caches.

Reference analogue: crates/engine/tree/src/tree/payload_processor/
prewarm.rs — before the sequential (canonical) execution of a new
payload, worker tasks execute every transaction INDEPENDENTLY against
the parent state. The results are discarded; the point is the side
effect: every account/storage/bytecode read lands in the shared
execution cache, so the sequential pass hits warm caches instead of
cold storage. Transactions that depend on earlier in-block writes
simply read parent-state values — still the right keys to warm (the
reference accepts the same approximation; its BAL-driven variant warms
the exact access list).

Workers execute against thread-local EvmStates over the SHARED
CachedStateSource; reads flow through the (mutex-guarded) cache,
speculative writes stay in the worker's journal and die with it.

With ``--parallel-exec`` this task does not run at all: the optimistic
scheduler (engine/optimistic.py) FOLDS the prewarm pass into its
speculative first attempts — the same recording execution warms the
cache and streams keys, but a validation-clean result commits directly
instead of being discarded and re-executed.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor

from .. import tracing
from ..evm.state import EvmState


class PrewarmTask:
    """One prewarm pass for one payload."""

    def __init__(self, executor, env, max_workers: int = 4,
                 record_accesses: bool = False, key_sink=None):
        """``executor``: the BlockExecutor whose (cached) source the
        sequential pass will use; ``env``: the block's BlockEnv. With
        ``record_accesses`` each worker also records its tx's access sets
        — the BAL scheduling hint (reference: prewarm and BAL execution
        share the speculative pass).

        ``key_sink(keys)``: optional OnStateHook-shaped callable fed each
        worker's touched plain keys (20-byte addresses and
        ``(address, slot)`` pairs) AS WORKERS FINISH — a cheap key-only
        recording independent of the BAL machinery. Wired to the sparse
        state-root task's ``on_state_update`` so multiproof fetch
        overlaps prewarm instead of waiting for canonical execution
        (reference: the sparse strategy's prefetch off the prewarm pass).
        Keys are speculative: extra keys only pre-reveal trie paths the
        block may not touch, which never changes the computed root."""
        self.executor = executor
        self.env = env
        self.max_workers = max_workers
        self.record_accesses = record_accesses
        self.key_sink = key_sink
        self.accesses: dict[int, object] = {}  # tx index -> TxAccess
        self.warmed = 0
        self.failed = 0
        self.streamed_keys = 0  # keys handed to key_sink (tests/metrics)

    def _one(self, item) -> bool:
        # adopt the block's trace context in this pool worker (explicit
        # handoff: captured once in start(), reused by every worker)
        with tracing.use_context(self._ctx):
            with tracing.span("engine::prewarm", "prewarm.tx", idx=item[0]):
                return self._one_inner(item)

    def _one_inner(self, item) -> bool:
        i, tx, sender = item
        try:
            if self.record_accesses:
                from .bal import _extract_writes, make_recording_state

                # the recording executor routes the coinbase fee credit
                # through the delta seam — a plain executor would poison
                # every access set with a coinbase write/flag
                acc, ex, state = make_recording_state(
                    self.executor.source, self.env.coinbase, i,
                    self.executor.config)
                self.accesses[i] = acc  # dict: per-key writes race-free
            else:
                ex = self.executor
                state = EvmState(self.executor.source)  # thread-local journal
            # independent execution: later in-block txs see the PARENT
            # nonce, so align the journal's copy (the reference's prewarm
            # relaxes the same sequential-only checks); reads still flow
            # through (and warm) the shared cache
            if state.nonce(sender) != tx.nonce:
                state.set_nonce(sender, tx.nonce)
            ex._execute_tx(state, self.env, tx, sender, self.env.gas_limit)
            if self.record_accesses:
                _extract_writes(state, acc)
            self._stream_keys(state)
            return True
        except Exception:  # noqa: BLE001 — speculative: any failure is fine
            return False

    def _stream_keys(self, state) -> None:
        """Hand this worker's touched keys to the sink (key-only mode):
        every account and storage slot the journal read or wrote, in the
        executor's OnStateHook format. Failures never fail the worker —
        prefetch is an optimization, not a correctness seam."""
        if self.key_sink is None:
            return
        try:
            keys: list = list(getattr(state, "_accounts", {}))
            for addr, slots in getattr(state, "_storage", {}).items():
                keys.extend((addr, s) for s in slots)
            if keys:
                self.streamed_keys += len(keys)
                self.key_sink(keys)
        except Exception:  # noqa: BLE001 — speculative prefetch only
            pass

    def run(self, transactions, senders) -> int:
        """Execute all txs concurrently; returns how many completed.
        Counters come from the map results — workers share no mutable
        state, so nothing needs a lock."""
        self.start(transactions, senders)
        return self.join()

    def start(self, transactions, senders) -> None:
        """Kick the workers off WITHOUT waiting: the canonical sequential
        pass runs concurrently and benefits from whatever has already been
        warmed when it reaches each transaction (the reference's prewarm
        overlaps execution the same way — blocking first would serialize
        two full passes)."""
        self._pool = None
        self._futures = []
        self._ctx = tracing.current_context()
        self._t0 = time.time()
        if not transactions:
            return
        self._pool = ThreadPoolExecutor(max_workers=self.max_workers)
        self._futures = [self._pool.submit(self._one, (i, tx, s))
                         for i, (tx, s) in enumerate(zip(transactions, senders))]

    def join(self) -> int:
        """Collect results and release the workers."""
        if self._pool is None:
            return 0
        results = [f.result() for f in self._futures]
        self._pool.shutdown(wait=True)
        self._pool = None
        self.warmed = sum(results)
        self.failed = len(results) - self.warmed
        # the whole pass as one span under the block trace (start() ran on
        # the block thread; workers overlapped canonical execution)
        tracing.record_span("engine::prewarm", "prewarm", self._t0,
                            time.time() - self._t0, ctx=self._ctx,
                            fields={"warmed": self.warmed,
                                    "failed": self.failed,
                                    "streamed_keys": self.streamed_keys})
        return self.warmed
