"""Prewarm: parallel optimistic execution to warm the state caches.

Reference analogue: crates/engine/tree/src/tree/payload_processor/
prewarm.rs — before the sequential (canonical) execution of a new
payload, worker tasks execute every transaction INDEPENDENTLY against
the parent state. The results are discarded; the point is the side
effect: every account/storage/bytecode read lands in the shared
execution cache, so the sequential pass hits warm caches instead of
cold storage. Transactions that depend on earlier in-block writes
simply read parent-state values — still the right keys to warm (the
reference accepts the same approximation; its BAL-driven variant warms
the exact access list).

Workers execute against thread-local EvmStates over the SHARED
CachedStateSource; reads flow through the (mutex-guarded) cache,
speculative writes stay in the worker's journal and die with it.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

from ..evm.state import EvmState


class PrewarmTask:
    """One prewarm pass for one payload."""

    def __init__(self, executor, env, max_workers: int = 4):
        """``executor``: the BlockExecutor whose (cached) source the
        sequential pass will use; ``env``: the block's BlockEnv."""
        self.executor = executor
        self.env = env
        self.max_workers = max_workers
        self.warmed = 0
        self.failed = 0

    def _one(self, tx, sender) -> bool:
        state = EvmState(self.executor.source)  # thread-local journal
        try:
            # independent execution: later in-block txs see the PARENT
            # nonce, so align the journal's copy (the reference's prewarm
            # relaxes the same sequential-only checks); reads still flow
            # through (and warm) the shared cache
            if state.nonce(sender) != tx.nonce:
                state.set_nonce(sender, tx.nonce)
            self.executor._execute_tx(state, self.env, tx, sender,
                                      self.env.gas_limit)
            return True
        except Exception:  # noqa: BLE001 — speculative: any failure is fine
            return False

    def run(self, transactions, senders) -> int:
        """Execute all txs concurrently; returns how many completed.
        Counters come from the map results — workers share no mutable
        state, so nothing needs a lock."""
        self.start(transactions, senders)
        return self.join()

    def start(self, transactions, senders) -> None:
        """Kick the workers off WITHOUT waiting: the canonical sequential
        pass runs concurrently and benefits from whatever has already been
        warmed when it reaches each transaction (the reference's prewarm
        overlaps execution the same way — blocking first would serialize
        two full passes)."""
        self._pool = None
        self._futures = []
        if not transactions:
            return
        self._pool = ThreadPoolExecutor(max_workers=self.max_workers)
        self._futures = [self._pool.submit(self._one, tx, s)
                         for tx, s in zip(transactions, senders)]

    def join(self) -> int:
        """Collect results and release the workers."""
        if self._pool is None:
            return 0
        results = [f.result() for f in self._futures]
        self._pool.shutdown(wait=True)
        self._pool = None
        self.warmed = sum(results)
        self.failed = len(results) - self.warmed
        return self.warmed
