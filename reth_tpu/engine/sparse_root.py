"""Sparse-trie live-tip state-root strategy: the WHOLE trie job overlaps
execution, not just key prehashing.

Reference analogue: `SparseTrieCacheTask` + the proof-worker pools
(crates/engine/tree/src/tree/state_root_strategy/sparse_trie.rs:126-259,
crates/trie/parallel/src/state_root_task.rs:20-100,
crates/trie/parallel/src/proof_task.rs:136) and chain-state's
`PreservedSparseTrie` (crates/chain-state/src/preserved_sparse_trie.rs:15).
There, execution streams per-tx state into a background task that fetches
multiproofs with dedicated workers and reveals them into an in-memory
sparse trie; when execution finishes only the final leaf updates + dirty
subtree rehash remain.

TPU-first shape here: one worker thread per block consumes the streamed
key batches and, while the EVM interprets on the main thread,
(a) batch-hashes the plain keys (device dispatchable — the digests later
feed the hashed-table writes), and (b) computes multiproofs from the
PARENT view and reveals them into the (possibly cross-block preserved)
sparse trie. ``finish`` then applies the block's final state delta and
level-batch-rehashes only dirty subtrees — the commit that remains on the
latency path is proportional to the block's touch set, not the trie.

Any failure mode (unresolvable blind, proof mismatch) raises; the engine
falls back to the incremental committer (`state_root_fallback`,
reference crates/engine/primitives/src/config.rs:140).
"""

from __future__ import annotations

import queue
import threading
import time

from .. import tracing
from ..primitives.keccak import keccak256
from ..trie.proof import ProofCalculator, ProofWorkerPool
from ..trie.sparse import (
    BlindedNodeError,
    ParallelSparseCommitter,
    SparseStateTrie,
    SparseTrie,
    export_branch_updates,
)
from .stateless import apply_output_to_trie


class SparseRootError(Exception):
    """The sparse path could not produce a root; use the fallback."""


class SparseRootTask:
    """One block's background sparse-trie state-root job."""

    MAX_REVEAL_RETRIES = 64

    def __init__(self, parent_provider, parent_root: bytes, preserved,
                 committer, parent_hash: bytes | None = None,
                 provider_factory=None, workers: int | None = None,
                 trace_ctx=None, seed_digests=None, hot_cache=None,
                 arena=None):
        # live tip is the highest-priority hash-service lane: with
        # --hash-service the task's batches coalesce with every other
        # client's but dispatch first; without one this is committer.hasher
        self.hasher = committer.for_lane("live").hasher \
            if hasattr(committer, "for_lane") else committer.hasher
        # committer wired through --hasher auto carries the device
        # supervisor: its hasher already watchdogs + CPU-fails-over every
        # device batch, so a wedged tunnel degrades this task instead of
        # hanging the worker thread mid-block; kept for observability
        self.supervisor = getattr(committer, "supervisor", None)
        self.calc = ProofCalculator(parent_provider, committer)
        # hot-state plane (ISSUE 19): the shared cross-block node cache
        # serves blinded paths before they become proof targets, and the
        # shared digest arena turns the fused finish into a delta upload
        self.hot_cache = hot_cache
        self.cache_unblinds = 0   # proof targets the cache absorbed
        self.proof_targets = 0    # targets that DID go to proof fetch
        self._touched_accounts: set[bytes] = set()
        self._touched_storage: dict[bytes, set[bytes]] = {}
        # parallel finish: cross-trie packed hashing + encode pool
        # (--sparse-workers; trie/sparse.py ParallelSparseCommitter)
        self.sparse_committer = ParallelSparseCommitter(workers=workers,
                                                        arena=arena)
        # proof-worker pool (reth proof_task.rs analogue): shards
        # multiproof targets by storage trie across N workers, each on a
        # FRESH parent view from ``provider_factory`` (cursor state is
        # per-tx). Without a factory, fetches stay on the single worker.
        self.proof_pool = None
        if provider_factory is not None \
                and self.sparse_committer.workers > 1:
            self.proof_pool = ProofWorkerPool(
                lambda: ProofCalculator(provider_factory(), committer),
                workers=self.sparse_committer.workers,
                injector=self.sparse_committer.injector)
        self._outstanding: list = []   # [(future, shard_targets)]
        self._fetching: set = set()    # in-flight reveal targets (dedupe)
        self.preserved = preserved
        self.reused = False
        st = preserved.take(parent_hash) if parent_hash is not None else None
        if st is not None and st.account_trie.root_hash == parent_root:
            self.trie = st
            self.reused = True
        else:
            self.trie = SparseStateTrie.anchored(parent_root)
        if hot_cache is not None:
            # reveal-ref stamping: revealed-but-unmutated nodes keep a
            # clean ref, so the delta finish never re-stages them (and
            # trie.stamped is the delta-fraction denominator)
            self.trie.set_stamping(True)
        self._queue: queue.Queue = queue.Queue()
        self._digests: dict[bytes, bytes] = {}
        if seed_digests:
            # cross-block pipeline adoption: the speculative stage
            # pre-hashed the touched keys on the double-buffered sub-mesh
            # while the parent committed — seed them so _process skips
            # re-hashing (proof fetch + reveal still run normally)
            self._digests.update(seed_digests)
        self._sent: set = set()
        self._failed: Exception | None = None
        # cooperative cancellation (engine/tree.py _cancel_inflight_for):
        # a forkchoiceUpdated reorging away from this block sets it from
        # ANOTHER thread; the worker stops at its next batch boundary and
        # finish() refuses to produce a root for the dead head
        self.cancelled = False
        self.proof_batches = 0
        self.commit_stats: dict | None = None
        # per-block wall breakdown (round-5 directive: measure the overlap
        # honestly — reference sparse_trie.rs:259 logs the same splits)
        self.walls = {"hash": 0.0, "proof": 0.0, "reveal": 0.0,
                      "finish": 0.0, "worker_busy": 0.0}
        self.started_at = time.monotonic()
        self.finish_called_at: float | None = None
        # explicit trace handoff: the task is created on the block thread
        # (under the block's root span); the worker adopts the context so
        # its hash/proof/reveal spans land in the block's timeline.
        # ``trace_ctx`` lets the engine hand the BLOCK root down (the
        # constructor itself runs inside a short startup span).
        self._ctx = (trace_ctx if trace_ctx is not None
                     else tracing.current_context())
        self._thread = threading.Thread(target=self._run_traced, daemon=True)
        self._thread.start()

    # -- execution-side hook (OnStateHook seam) -----------------------------

    def on_state_update(self, keys) -> None:
        """Queue newly touched keys: 20-byte addresses and
        ``(address, slot)`` pairs."""
        fresh = [k for k in keys if k not in self._sent]
        if not fresh:
            return
        self._sent.update(fresh)
        self._queue.put(fresh)

    # -- worker -------------------------------------------------------------

    def _run_traced(self) -> None:
        with tracing.use_context(self._ctx):
            self._run()

    def _run(self) -> None:
        while True:
            batch = self._queue.get()
            if self.cancelled:
                return  # no drain: in-flight proof shards die with pools
            if batch is None:
                if self._failed is None:
                    try:
                        self._reap(block=True)  # drain in-flight proof shards
                    except Exception as e:  # noqa: BLE001 — see finish()
                        self._failed = e
                return
            # coalesce everything already queued: each proof fetch
            # re-commits the upper trie spine, so ONE multiproof per
            # burst of transactions beats one per transaction by the
            # number of batches drained (measured ~10x on storage-heavy
            # blocks); the stream still overlaps execution
            done = False
            batch = list(batch)
            while True:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    done = True
                    break
                batch.extend(nxt)
            if self._failed is None:
                t0 = time.monotonic()
                try:
                    self._reap(block=done)
                    self._process(batch)
                    if done:
                        self._reap(block=True)
                except Exception as e:  # noqa: BLE001 — reported at finish()
                    self._failed = e
                self.walls["worker_busy"] += time.monotonic() - t0
            if done:
                return

    def _process(self, batch) -> None:
        addrs = [k for k in batch if isinstance(k, bytes)]
        pairs = [k for k in batch if not isinstance(k, bytes)]
        # ONE coalesced hash call for everything this burst needs: the
        # addresses, the pair-owner addresses (previously hashed one at a
        # time inside the reveal loop), and the slots
        plain = [k for k in addrs + [a for a, _ in pairs]
                 + [s for _, s in pairs] if k not in self._digests]
        if plain:
            t0 = time.monotonic()
            plain = list(dict.fromkeys(plain))
            with tracing.span("engine::sparse_root", "key_hash",
                              keys=len(plain)):
                for k, d in zip(plain, self.hasher(plain)):
                    self._digests[k] = bytes(d)
            self.walls["hash"] += time.monotonic() - t0
        # reveal only what the trie can't already read (a preserved trie
        # usually has last block's hot paths — the cross-block reuse),
        # deduped against targets already in flight on the proof pool
        targets: dict[bytes, list[bytes]] = {}
        for a in addrs:
            ha = self._digests[a]
            self._touched_accounts.add(ha)
            if ha in self._fetching:
                continue
            if self._needs_account_reveal(ha):
                if self._cache_reveal_account(ha):
                    self.cache_unblinds += 1
                    continue
                targets.setdefault(a, [])
                self._fetching.add(ha)
        for a, s in pairs:
            ha = self._digests[a]
            hs = self._digests[s]
            self._touched_accounts.add(ha)
            self._touched_storage.setdefault(ha, set()).add(hs)
            key = (ha, hs)
            if key in self._fetching:
                continue
            if self._needs_storage_reveal(*key):
                if self._cache_reveal_storage(ha, hs):
                    self.cache_unblinds += 1
                    continue
                targets.setdefault(a, []).append(s)
                self._fetching.add(key)
        if not targets:
            return
        self.proof_batches += 1
        self.proof_targets += len(targets) + sum(
            len(v) for v in targets.values())
        if self.proof_pool is not None:
            # sharded async fetch: workers walk independent storage tries
            # on their own parent views; reveals land when shards complete
            # (next loop turn or the pre-finish drain), so proof fetch
            # overlaps execution AND other fetches
            self._outstanding.extend(self.proof_pool.submit(targets))
            return
        t0 = time.monotonic()
        with tracing.span("engine::sparse_root", "proof.fetch",
                          targets=len(targets)):
            proofs = self.calc.multiproof(targets)
        self.walls["proof"] += time.monotonic() - t0
        self._reveal(proofs, targets)

    def _reap(self, block: bool) -> None:
        """Reveal completed proof shards; with ``block`` wait for all."""
        still = []
        for fut, shard in self._outstanding:
            if not block and not fut.done():
                still.append((fut, shard))
                continue
            proofs, wall = fut.result()  # raises a worker's failure here
            self.walls["proof"] += wall
            # attribute the shard's (concurrent, pool-side) proof wall to
            # the block trace; start is reconstructed from the wall
            tracing.record_span("engine::sparse_root", "proof.shard",
                                time.time() - wall, wall, ctx=self._ctx,
                                fields={"targets": len(shard)})
            self._reveal(proofs, shard)
        self._outstanding = still

    def _reveal(self, proofs, targets) -> None:
        t1 = time.monotonic()
        with tracing.span("engine::sparse_root", "reveal",
                          accounts=len(proofs)):
            nodes = []
            for ap in proofs.values():
                nodes.extend(ap.proof)
            self.trie.reveal_account(nodes)
            for a, ap in proofs.items():
                snodes = [n for sp in ap.storage_proofs for n in sp.proof]
                if snodes or targets.get(a):
                    self.trie.reveal_storage(self._digests[a], ap.storage_root,
                                             nodes + snodes)
        self.walls["reveal"] += time.monotonic() - t1

    def _needs_account_reveal(self, hashed_addr: bytes) -> bool:
        try:
            self.trie.account_trie.get(hashed_addr)
            return False
        except BlindedNodeError:
            return True

    def _needs_storage_reveal(self, hashed_addr: bytes,
                              hashed_slot: bytes) -> bool:
        st = self.trie.storage_tries.get(hashed_addr)
        if st is None:
            return True  # storage root unknown until the account is read
        try:
            st.get(hashed_slot)
            return False
        except BlindedNodeError:
            return True

    # -- hot-state cache reveals (proof fetches the cache absorbs) -----------

    def _cache_reveal_account(self, hashed_addr: bytes) -> bool:
        """Unblind the account path purely from the cross-block node
        cache; True = no proof target needed for this key."""
        if self.hot_cache is None:
            return False
        from ..trie.hot_cache import ACCOUNT_OWNER

        return self.hot_cache.reveal_through(self.trie.account_trie,
                                             ACCOUNT_OWNER, hashed_addr)

    def _cache_reveal_storage(self, hashed_addr: bytes,
                              hashed_slot: bytes) -> bool:
        """Storage analogue — when the storage trie itself is unknown but
        the account leaf is readable (possibly just cache-revealed), its
        storage root anchors a fresh trie that the cache then unblinds."""
        if self.hot_cache is None:
            return False
        st = self.trie.storage_tries.get(hashed_addr)
        if st is None:
            try:
                acct_rlp = self.trie.account_trie.get(hashed_addr)
            except BlindedNodeError:
                return False
            if acct_rlp is None:
                return False  # absent account: the proof path handles it
            from ..primitives.types import Account

            try:
                root = Account.decode(acct_rlp).storage_root
            except Exception:  # noqa: BLE001 — malformed: proof path
                return False
            st = self.trie.storage_trie(hashed_addr, root)
        return self.hot_cache.reveal_through(st, hashed_addr, hashed_slot)

    # -- finalization --------------------------------------------------------

    def finish(self, out):
        """Apply the block's state delta and rehash dirty levels.
        Returns ``(root, digest_map, storage_roots)`` where ``digest_map``
        maps plain keys (addresses, slots) to keccak digests and
        ``storage_roots`` maps plain addresses to recomputed storage
        roots. Raises SparseRootError when the sparse path cannot close.
        Call :meth:`preserve` only after the root matched the header —
        preserving a trie mutated by an invalid block would poison the
        next block's anchor."""
        self.finish_called_at = time.monotonic()
        # overlap snapshot: only busy time BEFORE this point ran while the
        # EVM executed; drain batches inside finish() are latency, not overlap
        self._busy_at_finish = self.walls["worker_busy"]
        self._queue.put(None)
        self._thread.join()
        try:
            return self._finish_inner(out)
        finally:
            self._shutdown_pools()

    def _finish_inner(self, out):
        if self.cancelled:
            raise SparseRootError("cancelled by forkchoice reorg")
        if self._failed is not None:
            raise SparseRootError(f"worker failed: {self._failed}") \
                from self._failed
        # straggler digests (withdrawal targets, wiped accounts, ...)
        want = sorted(set(out.changes.accounts) | set(out.changes.storage)
                      | set(out.changes.wiped_storage))
        slot_keys = [s for _, slots in out.post_storage.items()
                     for s in slots]
        missing = [k for k in want + slot_keys if k not in self._digests]
        if missing:
            missing = list(dict.fromkeys(missing))
            for k, d in zip(missing, self.hasher(missing)):
                self._digests[k] = bytes(d)
        storage_roots: dict[bytes, bytes] = {}
        for _attempt in range(self.MAX_REVEAL_RETRIES):
            if self.cancelled:
                raise SparseRootError("cancelled by forkchoice reorg")
            try:
                # parallel commit: cross-trie packed dispatches + encode
                # pool; any failure inside it (including the injected
                # RETH_TPU_FAULT_SPARSE_ABORT drill) surfaces as
                # SparseRootError below -> incremental fallback
                with tracing.span("engine::sparse_root", "sparse.finish",
                                  attempt=_attempt):
                    root = apply_output_to_trie(
                        self.trie, out, self.hasher,
                        storage_roots_out=storage_roots,
                        committer=self.sparse_committer)
                break
            except BlindedNodeError as e:
                if self._cache_unblind(e):
                    self.cache_unblinds += 1
                    continue  # retry the commit without a spine fetch
                extra = (self.calc.storage_spine_for_path(e.owner, e.path)
                         if e.owner is not None
                         else self.calc.spine_for_path(e.path))
                if e.owner is not None:
                    st = self.trie.storage_tries.get(e.owner)
                    if st is None:
                        raise SparseRootError("blind in unknown storage trie")
                    st.reveal(extra)
                else:
                    self.trie.reveal_account(extra)
            except Exception as e:  # noqa: BLE001 — commit failure -> fallback
                raise SparseRootError(f"parallel commit failed: {e}") from e
        else:
            raise SparseRootError("blinded-node reveal did not converge")
        self.commit_stats = self.sparse_committer.last
        self.walls["finish"] = time.monotonic() - self.finish_called_at
        return root, self._digests, storage_roots

    def _cache_unblind(self, e: BlindedNodeError) -> bool:
        """Serve a finish-side blind from the node cache (one validated
        node at the reported path); False = pay the spine fetch."""
        if self.hot_cache is None:
            return False
        if e.owner is not None:
            trie = self.trie.storage_tries.get(e.owner)
            owner = e.owner
        else:
            from ..trie.hot_cache import ACCOUNT_OWNER

            trie = self.trie.account_trie
            owner = ACCOUNT_OWNER
        if trie is None:
            return False
        path = bytes(e.path)
        h = trie.blind_hash_at(path)
        if h is None:
            return False
        rlp = self.hot_cache.lookup(owner, path, h)
        return rlp is not None and trie.reveal_at(path, rlp)

    def absorb_into_cache(self, out, digest_map=None) -> None:
        """Post-root-match population pass: push this block's freshly
        committed spines (changed keys) and revealed read paths (touched
        keys) into the shared node cache. Call next to :meth:`preserve`
        — absorbing a trie mutated by an INVALID block would poison
        sibling forks' reveals."""
        if self.hot_cache is None:
            return
        if digest_map is None:
            digest_map = self._digests
        changed = sorted(set(out.changes.accounts) | set(out.changes.storage)
                         | set(out.changes.wiped_storage))
        account_keys = [digest_map[a] for a in changed]
        storage_keys = {digest_map[a]: [digest_map[s] for s in slots]
                        for a, slots in out.post_storage.items()}
        wiped = [digest_map[a] for a in out.changes.wiped_storage]
        self.hot_cache.absorb_block(
            self.trie, account_keys, storage_keys, wiped_owners=wiped,
            touched_accounts=self._touched_accounts,
            touched_storage=self._touched_storage)

    def _shutdown_pools(self) -> None:
        self.sparse_committer.shutdown()
        if self.proof_pool is not None:
            self.proof_pool.shutdown()

    def overlap_metrics(self) -> dict:
        """Per-block breakdown for TrieMetrics: how much of the trie work
        overlapped execution. ``overlap_fraction`` = worker busy time that
        ran BEFORE finish() was called (i.e. while the EVM executed) over
        the execution window."""
        exec_wall = ((self.finish_called_at or time.monotonic())
                     - self.started_at)
        busy_during_exec = getattr(self, "_busy_at_finish",
                                   self.walls["worker_busy"])
        overlapped = min(busy_during_exec, exec_wall)
        out = {
            **{k: round(v, 6) for k, v in self.walls.items()},
            "exec_wall": round(exec_wall, 6),
            "overlap_fraction": round(overlapped / exec_wall, 4)
            if exec_wall > 0 else 0.0,
            # note: with the proof pool, "proof" sums per-shard busy time
            # across concurrent workers (can exceed wall clock)
            "proof_shards": (self.proof_pool.shards_total
                             if self.proof_pool is not None else 0),
            "sparse_workers": self.sparse_committer.workers,
            "proof_targets": self.proof_targets,
            "cache_unblinds": self.cache_unblinds,
        }
        if self.commit_stats is not None:
            out["commit"] = dict(self.commit_stats)
        if self.supervisor is not None:
            out["hasher_breaker"] = self.supervisor.breaker.state
        return out

    def preserve(self, block_hash: bytes) -> None:
        """Anchor the updated trie for the next payload (call after the
        computed root matched the block header)."""
        self.preserved.preserve(block_hash, self.trie)

    def export_updates(self, out, digest_map):
        """Stored-format branch updates for the overlay, straight from the
        sparse trie (reference: sparse trie TrieUpdates — no DB re-walk).
        Returns (account_updates, storage_updates) where each maps
        path -> BranchNode | None (None = delete)."""
        changed = sorted(set(out.changes.accounts) | set(out.changes.storage)
                         | set(out.changes.wiped_storage))
        acct_keys = [digest_map[a] for a in changed]
        account_updates = export_branch_updates(
            self.trie.account_trie, acct_keys, self.calc.provider.account_branch)
        storage_updates: dict[bytes, dict] = {}
        for a, slots in out.post_storage.items():
            ha = digest_map[a]
            st = self.trie.storage_tries.get(ha)
            if st is None:
                continue
            skeys = [digest_map[s] for s in slots]
            storage_updates[ha] = export_branch_updates(
                st, skeys,
                lambda p, _ha=ha: self.calc.provider.storage_branch(_ha, p))
        for a in out.changes.wiped_storage:
            ha = digest_map[a]
            if ha in storage_updates:
                continue  # wiped + recreated: already exported above
            st = self.trie.storage_tries.get(ha, SparseTrie())
            post = out.post_storage.get(a, {})
            skeys = [digest_map[s] for s in post]
            storage_updates[ha] = export_branch_updates(
                st, skeys, lambda p: None)
        return account_updates, storage_updates

    def abort(self) -> None:
        """Stop the worker without producing a root (execution failed)."""
        self._queue.put(None)
        self._thread.join()
        self._shutdown_pools()

    def cancel(self) -> None:
        """Non-blocking abort from ANOTHER thread (a forkchoiceUpdated
        reorging away from this block): flag the task, wake the worker.
        The insert thread still owns the blocking cleanup — its abort /
        finish path joins the worker and shuts the pools down."""
        self.cancelled = True
        self._queue.put(None)
