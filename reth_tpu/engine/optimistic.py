"""Optimistic parallel EVM execution with asynchronous storage prefetch.

Reference analogue: Block-STM-style optimistic scheduling (the shape
reth's experimental parallel executors and Reddio's "Parallel EVM
Execution with Asynchronous Storage" — arxiv 2503.04595 — both describe):
every transaction of a block executes SPECULATIVELY in parallel against
the block-start state with per-rank read/write-set capture; each rank's
read set is validated in order against the writes committed by earlier
ranks; only invalidated ranks re-execute against the merged view. No
access-list hint is needed — this is the engine tree's no-BAL path, the
one every real ``newPayload`` takes.

Execution engine layering (the fallback ladder):

1. **Native rounds** — maximal runs of native-eligible transactions go
   to the C++ wave core (native/evmexec.cpp) as ONE single-wave segment:
   all ranks speculate on OS threads (GIL released for the whole ctypes
   call), in-order validation demotes conflicting ranks to a serial
   native re-run, and the committed prefix folds into the block output
   rank by rank. The snapshot the core executes against starts from the
   statically known keys (senders, targets, tx access lists) and GROWS
   round over round from the read sets every result reports back — a
   miss keeps its partial reads precisely so the next round can carry
   the missing state.
2. **Async storage layer** — :class:`AsyncStateReader` prefetches the
   discovered keys (accounts, slots, bytecode) on background threads
   while the native core crunches, so cold provider reads overlap
   execution instead of serializing in front of the next round.
3. **Python ranks** — transactions the native core cannot take
   (creations, blob/set-code types, coinbase-sensitive, unsupported
   opcodes) speculate on a thread pool against a frozen block-start view
   — this IS the prewarm pass (reads warm the shared execution cache and
   stream to the sparse root task) — and commit their speculative
   journal directly when validation passes; only invalidated ranks
   re-execute serially against the merged view.
4. **Serial fallback** — any scheduler error (not a consensus-invalid
   transaction) abandons the attempt and re-runs the whole block through
   ``BlockExecutor.execute``; nothing was written outside the
   scheduler's local views, so the fallback is always safe.

Receipts, logs, gas, requests, and post-state are bit-identical to the
serial executor by construction: commits happen strictly in rank order,
validation is the same read/write-intersection rule the BAL machinery
uses (engine/bal.py), and the native core reproduces the interpreter
bit-for-bit or declines.

Fault drills: ``RETH_TPU_FAULT_EXEC_CONFLICT_STORM`` forces every rank
through speculation-invalidated serial re-execution (the all-conflict
worst case); ``RETH_TPU_FAULT_EXEC_RANK_WEDGE=<rank>`` wedges that
rank's speculative worker so the rank timeout trips the serial-fallback
ladder end to end.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass

from .. import tracing
from ..evm.executor import (
    BEACON_ROOTS_ADDRESS,
    BlockExecutor,
    HISTORY_STORAGE_ADDRESS,
    InvalidTransaction,
)
from ..evm.spec import LATEST_SPEC
from ..evm.state import EvmState, StateSource
from ..primitives.types import KECCAK_EMPTY
from .bal import (
    BlockCommitter,
    _block_env,
    _extract_writes,
    _MergedView,
    make_recording_state,
)

_FAULT_STORM = "RETH_TPU_FAULT_EXEC_CONFLICT_STORM"
_FAULT_WEDGE = "RETH_TPU_FAULT_EXEC_RANK_WEDGE"


class ExecSchedulerError(Exception):
    """The optimistic scheduler could not finish; use the serial path."""


class ExecCancelled(Exception):
    """Cooperative cancellation (a forkchoiceUpdated reorged away from
    the block mid-execution): NOT a scheduler failure — it must
    propagate to the engine, never fall back to a serial re-run of a
    dead head's block."""


def default_exec_workers() -> int:
    """Speculation width: RETH_TPU_EXEC_WORKERS, else core-derived."""
    env = os.environ.get("RETH_TPU_EXEC_WORKERS")
    if env:
        return max(1, int(env))
    return max(2, min(8, os.cpu_count() or 4))


# -- async storage layer ------------------------------------------------------


class AsyncStateReader:
    """Batched background prefetch of accounts, storage slots, and
    bytecode into a shared read cache (the paper's asynchronous storage
    layer). Requests come from three places: the block's statically
    known keys, the read sets missed native ranks report back, and the
    read sets completed speculative ranks captured — each feeding the
    still-running ones. All reads stay SYNCHRONOUS fallbacks: the reader
    only moves cold provider reads off the critical path, overlapping
    them with the GIL-free native rounds, so a wedged or slow prefetch
    can never change a result."""

    def __init__(self, base: StateSource, workers: int = 2):
        self.base = base
        self.accounts: dict[bytes, object] = {}
        self.slots: dict[tuple[bytes, bytes], int] = {}
        self.codes: dict[bytes, bytes] = {}
        self.prefetched = 0
        self._queue: queue.Queue = queue.Queue()
        self._seen: set = set()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"exec-prefetch-{i}")
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    def request(self, keys) -> None:
        """Enqueue plain keys (20-byte addresses / (address, slot) pairs)
        for background fetch; duplicates are dropped."""
        fresh = [k for k in keys if k not in self._seen]
        if not fresh:
            return
        self._seen.update(fresh)
        self._queue.put(fresh)

    def _run(self) -> None:
        while True:
            batch = self._queue.get()
            if batch is None:
                return
            for k in batch:
                try:
                    if isinstance(k, bytes):
                        if k not in self.accounts:
                            acc = self.base.account(k)
                            self.accounts[k] = acc
                            if acc is not None \
                                    and acc.code_hash != KECCAK_EMPTY \
                                    and acc.code_hash not in self.codes:
                                self.codes[acc.code_hash] = \
                                    self.base.bytecode(acc.code_hash)
                    elif k not in self.slots:
                        self.slots[k] = self.base.storage(*k)
                    self.prefetched += 1
                except Exception:  # noqa: BLE001 — prefetch is advisory;
                    pass  # the synchronous read will surface real errors

    def stop(self) -> None:
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=2)


class _PrefetchSource(StateSource):
    """StateSource over ``base`` consulting the reader's cache first and
    filling it on synchronous misses (the block's parent state is frozen,
    so caching is always sound)."""

    def __init__(self, base: StateSource, reader: AsyncStateReader):
        self.base = base
        self.reader = reader

    def account(self, address: bytes):
        cache = self.reader.accounts
        if address in cache:
            return cache[address]
        acc = self.base.account(address)
        cache[address] = acc
        return acc

    def storage(self, address: bytes, slot: bytes) -> int:
        cache = self.reader.slots
        key = (address, slot)
        if key in cache:
            return cache[key]
        v = self.base.storage(address, slot)
        cache[key] = v
        return v

    def bytecode(self, code_hash: bytes) -> bytes:
        cache = self.reader.codes
        code = cache.get(code_hash)
        if code is None:
            code = self.base.bytecode(code_hash)
            cache[code_hash] = code
        return code


# -- the scheduler ------------------------------------------------------------


@dataclass
class _Speculation:
    """One rank's speculative first attempt (= its prewarm run)."""

    acc: object          # TxAccess (read/write sets + coinbase flag)
    state: object        # EvmState journal over the frozen view
    fee_delta: int
    result: object       # TxResult
    err: Exception | None


class OptimisticScheduler:
    """One block's (or one payload candidate list's) optimistic run."""

    MAX_RETRIES = 6  # native retry rounds per stuck head rank

    def __init__(self, source: StateSource, transactions, senders,
                 config=None, max_workers: int | None = None,
                 state_hook=None, env=None, block=None, block_hashes=None,
                 mode: str = "block", withdrawals=None,
                 blob_cap: int | None = None, cancel_event=None):
        self.txs = list(transactions)
        self.senders = senders
        self.config = config
        self.block = block
        self.mode = mode
        self.withdrawals = withdrawals
        self.blob_cap = blob_cap
        self.blob_gas_used = 0
        self.state_hook = state_hook
        # cooperative cancellation (engine tree in-flight insert event):
        # checked at wave boundaries so a reorging fcU stops the rounds
        self.cancel_event = cancel_event
        self.workers = max_workers or default_exec_workers()
        self.env = env if env is not None else _block_env(
            block, config, block_hashes)
        self.spec = (config.spec_for(self.env.number, self.env.timestamp)
                     if config is not None else LATEST_SPEC)
        self.storm = bool(os.environ.get(_FAULT_STORM))
        wedge = os.environ.get(_FAULT_WEDGE)
        self.wedge_rank = int(wedge) if wedge not in (None, "") else None
        self.rank_timeout = float(
            os.environ.get("RETH_TPU_EXEC_RANK_TIMEOUT", "60"))
        self.reader = AsyncStateReader(source,
                                       workers=max(1, self.workers // 4))
        self.psource = _PrefetchSource(source, self.reader)
        self.lib = None
        if not self.storm and \
                os.environ.get("RETH_TPU_EXEC_NATIVE", "1") != "0":
            try:
                from .native_exec import load_library

                self.lib = load_library()
            except Exception:  # noqa: BLE001 — native is an accelerator;
                self.lib = None  # python ranks still produce the block
        self.native_ok = (self.lib is not None
                          and self.spec.at_least(LATEST_SPEC.name))
        self.stats = {
            "mode": "optimistic", "workers": self.workers, "rounds": 0,
            "native": 0, "python": 0, "speculative": 0, "serial_rerun": 0,
            "conflicts": 0, "misses": 0, "demoted": 0, "prefetched": 0,
            "snapshot_keys": 0, "fallback": None,
            "native_available": self.native_ok,
        }
        self.committed: list[int] = []
        self.evicted: list[int] = []
        self.snap_accts: set[bytes] = set()
        self.snap_slots: set[tuple[bytes, bytes]] = set()
        self._pending_keys: queue.Queue = queue.Queue()
        self._attempts: dict[int, int] = {}
        self.spec_pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="exec-spec")
        self.spec_futures: dict[int, object] = {}
        self.failed_senders: set[bytes] = set()
        self.frozen = None
        self.com = None
        self._ctx = tracing.current_context()
        if self.storm:
            tracing.fault_event("EXEC_CONFLICT_STORM",
                                target="engine::optimistic",
                                txs=len(self.txs))

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self.reader.stop()
        try:
            self.spec_pool.shutdown(wait=False, cancel_futures=True)
        except TypeError:  # pragma: no cover - pre-3.9 signature
            self.spec_pool.shutdown(wait=False)

    # -- eligibility ---------------------------------------------------------

    def _static_eligible(self, i: int) -> bool:
        """Can rank ``i`` even be OFFERED to the native core? (The core
        itself still declines dynamically — nonce/balance/opcode misses
        come back as status 2.)"""
        if not self.native_ok:
            return False
        if self.wedge_rank is not None and i == self.wedge_rank:
            return False  # drill: force the wedged rank onto the pool
        tx = self.txs[i]
        env = self.env
        return (tx.tx_type <= 2 and tx.to is not None
                and not tx.authorization_list
                and (tx.chain_id is None or tx.chain_id == env.chain_id)
                and not (tx.tx_type >= 2 and tx.max_fee_per_gas < env.base_fee)
                and not (tx.tx_type < 2 and tx.gas_price < env.base_fee)
                and env.coinbase != tx.to
                and env.coinbase != self.senders[i])

    def _demote(self, i: int) -> None:
        """Hand rank ``i`` to the Python path permanently (and start its
        speculative prewarm run right away)."""
        if self.eligible[i]:
            self.eligible[i] = False
            self.stats["demoted"] += 1
            self._submit_speculation(i)

    # -- speculation (the folded-in prewarm pass) ----------------------------

    def _submit_speculation(self, i: int) -> None:
        if i not in self.spec_futures:
            self.spec_futures[i] = self.spec_pool.submit(self._speculate, i)

    def _speculate(self, i: int) -> _Speculation:
        """Speculative first attempt of rank ``i`` against the frozen
        block-start view. This IS the prewarm run: reads flow through
        (and warm) the shared cached source, the captured read set feeds
        the async storage layer and the state-root task's prefetch, and
        — unlike the old PrewarmTask — a validation-clean result commits
        directly instead of being thrown away."""
        with tracing.use_context(self._ctx):
            with tracing.span("engine::optimistic", "exec.speculate", idx=i):
                return self._speculate_inner(i)

    def _speculate_inner(self, i: int) -> _Speculation:
        if self.wedge_rank is not None and i == self.wedge_rank:
            tracing.fault_event("EXEC_RANK_WEDGE",
                                target="engine::optimistic", rank=i)
            time.sleep(float(os.environ.get(
                "RETH_TPU_FAULT_EXEC_WEDGE_S", "5")))
        acc, ex, state = make_recording_state(
            self.frozen, self.env.coinbase, i, self.config)
        try:
            result = ex._execute_tx(state, self.env, self.txs[i],
                                    self.senders[i], self.env.gas_limit)
            _extract_writes(state, acc)
            sp = _Speculation(acc, state, ex.fee_delta, result, None)
        except Exception as e:  # noqa: BLE001 — stale-state failures
            sp = _Speculation(acc, None, 0, None, e)  # retry serially
        # feed the async storage layer + the state-root prefetch with the
        # captured read set (complete for finished runs, partial for
        # failed ones — still the right keys to warm)
        try:
            keys = list(acc.account_reads) + list(acc.slot_reads)
            if keys:
                self.reader.request(keys)
                self._pending_keys.put(keys)
                if self.state_hook is not None and self.mode == "block":
                    self.state_hook(keys)
        except Exception:  # noqa: BLE001 — prefetch is advisory only
            pass
        return sp

    def _drain_pending_keys(self) -> None:
        """Fold worker-discovered keys into the native snapshot key sets
        (main-thread only: the sets are iterated during marshaling)."""
        while True:
            try:
                keys = self._pending_keys.get_nowait()
            except queue.Empty:
                return
            for k in keys:
                (self.snap_accts if isinstance(k, bytes)
                 else self.snap_slots).add(k)

    # -- native rounds -------------------------------------------------------

    def _native_round(self, lo: int, hi: int):
        """One optimistic native round over ranks [lo, hi): single-wave
        speculation + in-order validation + serial conflict re-runs, all
        in C++. Returns ``(next_pos, stopper, stopper_grew)`` where
        ``stopper`` is the first uncommitted rank's result (None when the
        whole run committed) and ``stopper_grew`` says whether its
        reported reads added new keys to the snapshot (i.e. a retry can
        succeed)."""
        from .native_exec import (
            call_segment,
            env_buffer,
            parse_results,
            snapshot_buffer,
            txs_buffer,
        )

        com = self.com
        self.stats["rounds"] += 1
        snap_buf, prev_accounts, prev_slots = snapshot_buffer(
            com.merged, self.snap_accts, self.snap_slots)
        txs_buf = txs_buffer(self.txs, self.senders, range(lo, hi),
                             self.spec, self.env)
        raw = call_segment(self.lib, snap_buf, env_buffer(self.env), txs_buf,
                           [hi - lo], self.env.gas_limit - com.cumulative,
                           self.workers)
        results = parse_results(raw)
        next_pos = lo
        stopper = None
        stopper_grew = False
        for res in results:
            i = res["index"]
            if res["status"] <= 1 and next_pos == i:
                com.commit_native(
                    self.txs[i].tx_type, res["status"] == 1,
                    res["gas_used"], res["fee_delta"], res["logs"],
                    res["acct_writes"], res["slot_writes"],
                    prev_accounts, prev_slots, output=res["output"])
                self.committed.append(i)
                self.stats["native"] += 1
                if res["mode"] == 1:
                    self.stats["conflicts"] += 1
                next_pos = i + 1
                continue
            # missed / not-run rank: harvest its reads for the prefetcher
            fresh_a = res["acct_reads"] - self.snap_accts
            fresh_s = res["slot_reads"] - self.snap_slots
            if fresh_a or fresh_s:
                self.snap_accts |= fresh_a
                self.snap_slots |= fresh_s
                self.reader.request(list(fresh_a) + list(fresh_s))
                if i == next_pos:
                    stopper_grew = True
            if i == next_pos and stopper is None:
                stopper = res
                self.stats["misses"] += 1
        return next_pos, stopper, stopper_grew

    # -- python ranks --------------------------------------------------------

    def _payload_gate(self, i: int):
        """Payload-build admission for rank ``i``; returns a skip reason
        (builder semantics: skip, never block-invalid) or None."""
        tx = self.txs[i]
        if self.senders[i] in self.failed_senders:
            return "nonce-gapped descendant"
        if tx.gas_limit > self.env.gas_limit - self.com.cumulative:
            return "over block gas limit"
        if tx.blob_gas():
            if self.blob_cap is None or \
                    self.blob_gas_used + tx.blob_gas() > self.blob_cap:
                return "over blob gas cap"
        return None

    def _commit_python_rank(self, i: int) -> None:
        """Commit rank ``i`` on the Python path: take its speculative
        result when validation passes, else re-execute serially against
        the merged view (only invalidated ranks pay the re-run)."""
        com = self.com
        env = self.env
        tx = self.txs[i]
        if self.mode == "payload":
            reason = self._payload_gate(i)
            if reason is not None:
                return  # skipped, stays pooled (builder semantics)
        t0 = time.time()
        self._submit_speculation(i)
        fut = self.spec_futures[i]
        try:
            sp = fut.result(timeout=self.rank_timeout)
        except _FutureTimeout:
            raise ExecSchedulerError(
                f"rank {i} speculation wedged past "
                f"{self.rank_timeout}s") from None
        mode = "speculative"
        if (sp.err is None and not self.storm
                and not sp.acc.coinbase_sensitive
                and tx.gas_limit <= env.gas_limit - com.cumulative
                and not sp.acc.conflicts_with_write_sets(com.written_accts,
                                                         com.written_slots)):
            # Block-STM commit: the speculative journal IS the result.
            # (Writes committed before the freeze — the system-call phase
            # — can flag a spurious conflict; that only costs a re-run.)
            com.commit_tx(i, sp.state, sp.fee_delta, sp.result)
            self.stats["speculative"] += 1
        else:
            mode = "serial"
            try:
                acc, ex, state = make_recording_state(
                    com.merged, env.coinbase, i, self.config)
                result = ex._execute_tx(state, env, tx, self.senders[i],
                                        env.gas_limit - com.cumulative)
                _extract_writes(state, acc)
            except (InvalidTransaction, ValueError) as e:
                if self.mode == "payload":
                    # provably unexecutable candidate: evict, skip its
                    # descendants (they are nonce-gapped now)
                    self.evicted.append(i)
                    self.failed_senders.add(self.senders[i])
                    return
                raise  # newPayload: the block is invalid, same as serial
            com.commit_tx(i, state, ex.fee_delta, result)
            self.stats["serial_rerun"] += 1
        self.committed.append(i)
        self.stats["python"] += 1
        self.blob_gas_used += tx.blob_gas()
        tracing.record_span("engine::optimistic", "exec.rank", t0,
                            time.time() - t0, ctx=self._ctx,
                            fields={"idx": i, "mode": mode})

    # -- system phases (newPayload mode only) --------------------------------

    def _pre_block_phase(self) -> None:
        """EIP-4788 beacon root + EIP-2935 history system calls, folded
        into the merged view before rank 0 (exactly the serial order)."""
        header = self.block.header
        spec = self.spec
        ex = BlockExecutor(self.com.merged, self.config)
        state = EvmState(self.com.merged)
        ran = False
        if spec.beacon_root_call and \
                header.parent_beacon_block_root is not None:
            ex._system_call(state, self.env, spec, BEACON_ROOTS_ADDRESS,
                            header.parent_beacon_block_root)
            ran = True
        if spec.history_contract_call and header.number > 0:
            ex._system_call(state, self.env, spec, HISTORY_STORAGE_ADDRESS,
                            header.parent_hash)
            ran = True
        if ran:
            self.com.commit_system_state(state)

    def _requests_phase(self) -> list[bytes]:
        """EIP-7685 requests over the merged post-tx view (deposit logs
        from the committed receipts + the two system calls)."""
        if not self.spec.has_requests:
            return []
        ex = BlockExecutor(self.com.merged, self.config)
        state = EvmState(self.com.merged)
        requests = ex._collect_requests(state, self.env, self.spec,
                                        self.com.receipts)
        self.com.commit_system_state(state)
        return requests

    # -- the run -------------------------------------------------------------

    def run(self):
        t_start = time.time()
        spec = self.spec
        if self.mode == "block" and (
                spec.block_reward or not spec.receipt_status
                or (self.block is not None and self.block.ommers)):
            raise ExecSchedulerError(
                f"pre-merge rules ({spec.name}): serial path")
        self.com = BlockCommitter(self.psource, self.env, self.txs,
                                  state_hook=self.state_hook)
        if self.mode == "block":
            self._pre_block_phase()
        # freeze the post-system-call view: speculation workers read this
        # while the commit loop mutates the live merged view
        frozen = _MergedView(self.psource)
        frozen.accounts = dict(self.com.merged.accounts)
        frozen.slots = {a: dict(p) for a, p in self.com.merged.slots.items()}
        frozen.wiped = set(self.com.merged.wiped)
        frozen.codes = dict(self.com.merged.codes)
        self.frozen = frozen
        n = len(self.txs)
        self.eligible = [self._static_eligible(i) for i in range(n)]
        # statically known keys seed the snapshot + the async prefetch
        static_keys: list = []
        for i in range(n):
            static_keys.append(self.senders[i])
            if self.txs[i].to is not None:
                static_keys.append(self.txs[i].to)
            for addr, slots in self.txs[i].access_list:
                static_keys.append(addr)
                static_keys.extend((addr, s) for s in slots)
        for k in static_keys:
            (self.snap_accts if isinstance(k, bytes)
             else self.snap_slots).add(k)
        self.reader.request(static_keys)
        # ineligible ranks start their speculative (prewarm) run now
        for i in range(n):
            if not self.eligible[i]:
                self._submit_speculation(i)

        pos = 0
        while pos < n:
            if self.cancel_event is not None and self.cancel_event.is_set():
                raise ExecCancelled("forkchoice reorged away mid-wave")
            if not self.eligible[pos]:
                self._commit_python_rank(pos)
                pos += 1
                continue
            end = pos
            while end < n and self.eligible[end]:
                end += 1
            self._drain_pending_keys()
            t0 = time.time()
            with tracing.span("engine::optimistic", "exec.round",
                              lo=pos, hi=end):
                next_pos, stopper, stopper_grew = self._native_round(pos, end)
            tracing.record_span(
                "engine::optimistic", "exec.commit", t0, time.time() - t0,
                ctx=self._ctx,
                fields={"committed": next_pos - pos, "lo": pos})
            if next_pos < end:
                head = next_pos
                attempts = self._attempts.get(head, 0) + 1
                self._attempts[head] = attempts
                if (stopper is None or stopper["coinbase_sensitive"]
                        or not stopper_grew
                        or attempts > self.MAX_RETRIES):
                    self._demote(head)
            pos = next_pos

        requests = []
        if self.mode == "block":
            requests = self._requests_phase()
        self.com.apply_withdrawals(
            self.withdrawals if self.mode == "payload"
            else (self.block.withdrawals if self.block is not None else None))
        out = self.com.build_output(self.senders)
        out.requests = requests
        self.stats["prefetched"] = self.reader.prefetched
        self.stats["snapshot_keys"] = (len(self.snap_accts)
                                       + len(self.snap_slots))
        self.stats["wall_s"] = round(time.time() - t_start, 4)
        return out


# -- entry points -------------------------------------------------------------


def execute_block_optimistic(source: StateSource, block, senders,
                             config=None, max_workers: int | None = None,
                             state_hook=None, block_hashes=None,
                             cancel_event=None):
    """Execute ``block`` with the optimistic scheduler; output is
    bit-identical to ``BlockExecutor.execute`` (including system calls,
    EIP-7685 requests, and withdrawals). Returns ``(output, stats)``.
    Consensus-invalid transactions raise :class:`InvalidTransaction`
    exactly like the serial path; ANY other scheduler failure falls back
    to a full serial re-run (``stats["fallback"]`` records why).
    ``cancel_event`` set mid-run raises :class:`ExecCancelled` instead —
    a reorged-away block must not be re-run at all."""
    sched = None
    try:
        sched = OptimisticScheduler(
            source, block.transactions, senders, config=config,
            max_workers=max_workers, state_hook=state_hook, block=block,
            block_hashes=block_hashes, mode="block",
            cancel_event=cancel_event)
        out = sched.run()
        return out, sched.stats
    except InvalidTransaction:
        raise  # genuinely invalid block — identical to serial behavior
    except ExecCancelled:
        raise  # cooperative abort — never serial-re-run a dead head
    except Exception as e:  # noqa: BLE001 — fallback ladder's last rung
        stats = dict(sched.stats) if sched is not None else {}
        stats["fallback"] = f"{type(e).__name__}: {e}"
        stats["mode"] = "serial-fallback"
        out = BlockExecutor(source, config).execute(
            block, senders, block_hashes, state_hook=state_hook)
        return out, stats
    finally:
        if sched is not None:
            sched.close()


def execute_candidates_optimistic(source: StateSource, env, transactions,
                                  senders, config=None,
                                  max_workers: int | None = None,
                                  withdrawals=None,
                                  blob_cap: int | None = None):
    """Payload-builder mode: execute a candidate list optimistically with
    the builder's greedy semantics — unexecutable candidates are SKIPPED
    (and reported for pool eviction), never block-invalidating; gas and
    blob caps gate at commit time in rank order. Returns
    ``(output, committed_indices, evicted_indices, blob_gas_used,
    stats)`` where output's receipts align with ``committed_indices``.
    Raises on scheduler failure — the builder keeps its serial loop as
    the fallback."""
    sched = OptimisticScheduler(
        source, transactions, senders, config=config,
        max_workers=max_workers, env=env, mode="payload",
        withdrawals=withdrawals, blob_cap=blob_cap)
    try:
        out = sched.run()
        return (out, sched.committed, sched.evicted, sched.blob_gas_used,
                sched.stats)
    finally:
        sched.close()
