"""Blob sidecar store: memory + disk backends.

Reference analogue: crates/transaction-pool/src/blobstore/ (mod.rs,
mem.rs, disk.rs) — blob sidecars live OUTSIDE the pool's tx index (they
are large), keyed by tx hash, inserted on pool admission, pruned when
the tx leaves the pool, and served to engine_getBlobsV1/V2 and the
pooled-tx network responses.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path

from ..primitives import kzg
from ..primitives.rlp import rlp_decode, rlp_encode


class BlobStoreError(ValueError):
    pass


@dataclass(frozen=True)
class BlobSidecar:
    """blobs + KZG commitments + proofs of one type-3 transaction."""

    blobs: tuple[bytes, ...]
    commitments: tuple[bytes, ...]
    proofs: tuple[bytes, ...]

    def versioned_hashes(self) -> tuple[bytes, ...]:
        return tuple(kzg.kzg_to_versioned_hash(c) for c in self.commitments)

    def validate(self, expected_hashes: tuple[bytes, ...]) -> None:
        """Full admission validation: shape, hash binding, KZG proofs."""
        if not (len(self.blobs) == len(self.commitments) == len(self.proofs)):
            raise BlobStoreError("sidecar length mismatch")
        if not self.blobs:
            raise BlobStoreError("empty sidecar")
        if self.versioned_hashes() != tuple(expected_hashes):
            raise BlobStoreError("versioned hashes do not match commitments")
        for blob, commitment, proof in zip(self.blobs, self.commitments, self.proofs):
            if not kzg.verify_blob_kzg_proof(blob, commitment, proof):
                raise BlobStoreError("KZG blob proof verification failed")

    def encode(self) -> bytes:
        return rlp_encode([list(self.blobs), list(self.commitments),
                           list(self.proofs)])

    @classmethod
    def decode(cls, data: bytes) -> "BlobSidecar":
        f = rlp_decode(data)
        return cls(tuple(f[0]), tuple(f[1]), tuple(f[2]))


class InMemoryBlobStore:
    """Reference blobstore/mem.rs analogue."""

    def __init__(self):
        self._store: dict[bytes, BlobSidecar] = {}

    def insert(self, tx_hash: bytes, sidecar: BlobSidecar) -> None:
        self._store[tx_hash] = sidecar

    def get(self, tx_hash: bytes) -> BlobSidecar | None:
        return self._store.get(tx_hash)

    def delete(self, tx_hash: bytes) -> None:
        self._store.pop(tx_hash, None)

    def __len__(self) -> int:
        return len(self._store)

    def by_versioned_hashes(self, hashes) -> list[tuple[bytes, bytes] | None]:
        """(blob, proof) per requested versioned hash, None when unknown —
        the engine_getBlobsV1 lookup shape."""
        index: dict[bytes, tuple[bytes, bytes]] = {}
        for sc in self._store.values():
            for vh, blob, proof in zip(sc.versioned_hashes(), sc.blobs, sc.proofs):
                index.setdefault(vh, (blob, proof))
        return [index.get(h) for h in hashes]


class DiskBlobStore(InMemoryBlobStore):
    """Reference blobstore/disk.rs analogue: one RLP file per tx hash with
    a small hot cache (the in-memory parent acts as the cache)."""

    def __init__(self, directory):
        super().__init__()
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _path(self, tx_hash: bytes) -> Path:
        return self.dir / (tx_hash.hex() + ".blob")

    def insert(self, tx_hash: bytes, sidecar: BlobSidecar) -> None:
        super().insert(tx_hash, sidecar)
        tmp = self._path(tx_hash).with_suffix(".tmp")
        tmp.write_bytes(sidecar.encode())
        tmp.replace(self._path(tx_hash))

    def get(self, tx_hash: bytes) -> BlobSidecar | None:
        sc = super().get(tx_hash)
        if sc is not None:
            return sc
        p = self._path(tx_hash)
        if not p.exists():
            return None
        sc = BlobSidecar.decode(p.read_bytes())
        super().insert(tx_hash, sc)  # warm the cache
        return sc

    def delete(self, tx_hash: bytes) -> None:
        super().delete(tx_hash)
        try:
            os.unlink(self._path(tx_hash))
        except FileNotFoundError:
            pass

    def by_versioned_hashes(self, hashes) -> list[tuple[bytes, bytes] | None]:
        # warm every persisted sidecar first: after a restart the cache is
        # empty and a hash lookup must still see the on-disk files
        for p in self.dir.glob("*.blob"):
            tx_hash = bytes.fromhex(p.stem)
            if super().get(tx_hash) is None:
                self.get(tx_hash)
        return super().by_versioned_hashes(hashes)
