"""Batched pool insertion + validation-task offload.

Reference analogue: `BatchTxProcessor` (crates/transaction-pool/src/
batcher.rs) — callers enqueue (tx, response channel) requests; a processor
drains the queue in batches to cut per-insert lock contention — and the
validation task pool (src/validate/task.rs) that moves validation work off
the caller's thread.

TPU-first collapse of the two: the expensive validation step is SENDER
RECOVERY, and this repo has a batched native secp256k1 backend
(primitives.types.recover_senders → one threaded C++ dispatch for the
whole batch). The batcher worker therefore drains up to ``max_batch``
requests, recovers every sender in ONE batched call, then inserts each tx
under a single lock acquisition per batch — callers just await futures.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future

from ..primitives.types import Transaction, recover_senders
from .pool import PoolError


class PoolOverloaded(PoolError):
    """Admission queue is full — the firehose outran the insert worker.

    Carries ``retry_after_s`` so the RPC layer can map this onto the
    gateway's shed convention (``-32005`` + retry_after) instead of the
    generic ``-32000`` pool error. Bounding the queue here is what keeps a
    tx flood from growing memory without limit and from starving the
    gateway's engine lanes: the submit call fails fast instead of
    parking work forever.
    """

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"transaction pool overloaded ({depth} admissions queued)")
        self.retry_after_s = retry_after_s


class TxBatcher:
    """Worker-thread insertion batcher over a :class:`TransactionPool`."""

    def __init__(self, pool, max_batch: int = 128, max_queue: int = 8192,
                 retry_after_s: float = 0.5):
        self.pool = pool
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s
        self._q: queue.Queue = queue.Queue()
        self._closed = False
        self.batches = 0
        self.processed = 0
        self.sheds = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tx-batcher")
        self._thread.start()

    # -- submission ----------------------------------------------------------

    def submit(self, tx: Transaction) -> Future:
        """Enqueue a tx; the Future resolves to its hash or raises
        PoolError (PoolOverloaded when the admission queue is saturated)."""
        fut: Future = Future()
        if self._closed:
            fut.set_exception(PoolError("batcher closed"))
            return fut
        depth = self._q.qsize()
        if self.max_queue and depth >= self.max_queue:
            self.sheds += 1
            try:
                from ..metrics import pool_metrics

                pool_metrics.record_shed()
            except Exception:  # noqa: BLE001
                pass
            fut.set_exception(PoolOverloaded(depth, self.retry_after_s))
            return fut
        self._q.put((tx, fut))
        return fut

    def add_sync(self, tx: Transaction, timeout: float = 30.0) -> bytes:
        """Submit and wait — the drop-in replacement for
        ``pool.add_transaction`` on RPC threads."""
        return self.submit(tx).result(timeout)

    # -- worker --------------------------------------------------------------

    def _drain(self) -> list:
        batch = [self._q.get()]
        while len(batch) < self.max_batch:
            try:
                batch.append(self._q.get_nowait())
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        while True:
            batch = self._drain()
            stop = any(tx is None for tx, _ in batch)  # close() sentinel
            try:
                self._process([(tx, fut) for tx, fut in batch
                               if tx is not None])
            except Exception as e:  # noqa: BLE001 — the worker must
                # survive ANY poison batch: fail these futures, keep
                # serving (a dead worker silently kills tx submission)
                for tx, fut in batch:
                    if tx is not None and not fut.done():
                        fut.set_exception(PoolError(f"internal: {e}"))
            if stop:
                return

    def _process(self, batch: list) -> None:
        if not batch:
            return
        self.batches += 1
        try:
            senders = recover_senders([tx for tx, _ in batch])
        except Exception:  # noqa: BLE001 — one malformed tx must not
            # poison the whole batch; fall back to per-tx recovery
            senders = [None] * len(batch)
        with self.pool._lock:
            for (tx, fut), sender in zip(batch, senders):
                if fut.set_running_or_notify_cancel() is False:
                    continue
                try:
                    if sender is None:
                        raise PoolError("invalid signature: recovery failed")
                    fut.set_result(
                        self.pool.add_transaction(tx, sender=sender))
                except PoolError as e:
                    fut.set_exception(e)
                except Exception as e:  # noqa: BLE001 — a poison tx must
                    # fail ITS future, not kill the worker for everyone
                    fut.set_exception(PoolError(f"internal: {e}"))
                finally:
                    self.processed += 1

    def close(self) -> None:
        """Stop the worker after the queue drains."""
        if not self._closed:
            self._closed = True
            self._q.put((None, None))
            self._thread.join(timeout=10)
