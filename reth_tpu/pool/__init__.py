"""Transaction pool: validation, subpools, best-transaction ordering.

Reference analogue: crates/transaction-pool — the `TransactionPool` trait
(src/traits.rs:114), the pending/queued/basefee subpool state machine
(src/pool/), `BestTransactions` (src/pool/best.rs), validation
(src/validate/), and the canonical-state maintenance loop
(src/maintain.rs).
"""

from .pool import PoolConfig, PoolError, TransactionPool
from .batcher import PoolOverloaded, TxBatcher

__all__ = ["PoolConfig", "PoolError", "PoolOverloaded", "TransactionPool",
           "TxBatcher"]
