"""The transaction pool.

Subpool model (reference src/pool/mod.rs state machine):

- **pending**: executable now — contiguous nonces from the account's
  on-chain nonce, fee cap >= current base fee.
- **basefee**: nonce-contiguous but priced below the current base fee;
  promoted when the base fee falls.
- **queued**: nonce gap; promoted when the gap fills.

``best_transactions`` yields pending txs ordered by effective tip (then
insertion order), never yielding a later nonce before an earlier one per
sender. ``on_canonical_state_change`` is the maintenance loop: drops
mined/stale txs and re-buckets everything against the new state.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from ..primitives.types import Transaction

MIN_PRICE_BUMP_PERCENT = 10  # replacement bump (reference: 10%)


class PoolError(Exception):
    pass


@dataclass
class PoolConfig:
    max_account_slots: int = 16      # txs per sender
    max_pool_size: int = 10_000
    minimal_protocol_fee: int = 0
    chain_id: int | None = None      # reject foreign-chain txs at admission


@dataclass
class PooledTx:
    tx: Transaction
    sender: bytes
    submission_id: int
    cost: int  # max gas cost + value

    @property
    def nonce(self) -> int:
        return self.tx.nonce

    def effective_tip(self, base_fee: int) -> int:
        if self.tx.tx_type >= 2:
            if self.tx.max_fee_per_gas < base_fee:
                return -1
            return min(self.tx.max_priority_fee_per_gas,
                       self.tx.max_fee_per_gas - base_fee)
        return self.tx.gas_price - base_fee

    def max_fee(self) -> int:
        return self.tx.max_fee_per_gas if self.tx.tx_type >= 2 else self.tx.gas_price


class TransactionPool:
    """State-aware pool over a read-provider factory."""

    def __init__(self, state_reader, config: PoolConfig | None = None,
                 blob_store=None):
        """``state_reader()`` → object with .account(addr) and the current
        base fee via ``state_reader.base_fee`` callable/attribute."""
        from .blobstore import InMemoryBlobStore

        self.state_reader = state_reader
        self.config = config or PoolConfig()
        self.by_sender: dict[bytes, dict[int, PooledTx]] = {}
        self.by_hash: dict[bytes, PooledTx] = {}
        self._submission_counter = itertools.count()
        self.base_fee: int = 0
        self.blob_base_fee: int = 1
        self.blob_store = blob_store if blob_store is not None else InMemoryBlobStore()
        # mined blob sidecars are RETAINED for a while (reorg re-broadcast +
        # engine_getBlobs after canonicalization; reference keeps them until
        # finalization) — bounded FIFO
        self._mined_sidecars: list[bytes] = []
        self.mined_sidecar_retention = 128
        # set on every successful insert / canonical update; consumers
        # (instant-seal dev miner, payload jobs) wait on this instead of
        # polling executability (which costs a state read per sender)
        import threading

        self.updated = threading.Event()
        # one lock serializes mutation: RPC threads, the insertion batcher
        # worker, and canonical-update maintenance all touch the indexes
        # (reference: the pool lives behind a RwLock)
        self._lock = threading.RLock()
        # pool-event plane (reference: TransactionPool's event listeners,
        # src/pool/events.rs): every admission/replacement/drop/canon
        # update is published to registered sinks under the pool lock with
        # a monotonic sequence number. The continuous block producer keys
        # its incremental refreshes off ``event_seq``; the fleet feed
        # ships the same events as ``pt_*`` records so replicas hold a
        # pending view. Listeners must be fast and non-blocking.
        self.listeners: list = []
        self.event_seq: int = 0

    # -- events ----------------------------------------------------------------

    def add_listener(self, fn) -> None:
        """Register a pool-event sink: ``fn(event_dict)`` called under the
        pool lock for add/replace/drop/canon events."""
        with self._lock:
            self.listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self.listeners:
                self.listeners.remove(fn)

    def _emit(self, kind: str, **fields) -> None:
        """Publish one pool event (lock held by every caller)."""
        self.event_seq += 1
        try:
            from ..metrics import pool_metrics

            pool_metrics.on_event(kind, fields.get("reason"))
        except Exception:  # noqa: BLE001 — metrics never block admission
            pass
        if not self.listeners:
            return
        ev = {"seq": self.event_seq, "kind": kind}
        ev.update(fields)
        for fn in list(self.listeners):
            try:
                fn(ev)
            except Exception:  # noqa: BLE001 — a broken sink must not
                pass           # poison admission for everyone

    # -- submission -----------------------------------------------------------

    def add_blob_transaction(self, tx: Transaction, sidecar) -> bytes:
        """Admit a type-3 tx WITH its sidecar: versioned hashes must bind
        the commitments and every KZG blob proof must verify (reference
        EthTransactionValidator + blobstore insert)."""
        from .blobstore import BlobStoreError

        if tx.tx_type != 3:
            raise PoolError("not a blob transaction")
        try:
            sidecar.validate(tx.blob_versioned_hashes)
        except BlobStoreError as e:
            raise PoolError(f"invalid blob sidecar: {e}")
        h = self.add_transaction(tx, _with_sidecar=True)
        self.blob_store.insert(h, sidecar)
        return h

    def add_transaction(self, tx: Transaction, _with_sidecar: bool = False,
                        sender: bytes | None = None) -> bytes:
        """Validate + insert; returns the tx hash. Raises PoolError.
        ``sender`` skips in-line recovery when the caller already recovered
        it (the insertion batcher's native batched secp dispatch)."""
        with self._lock:
            return self._add_locked(tx, _with_sidecar, sender)

    def _add_locked(self, tx: Transaction, _with_sidecar: bool,
                    sender: bytes | None) -> bytes:
        h = tx.hash
        if h in self.by_hash:
            raise PoolError("already known")
        if tx.tx_type == 3:
            if not _with_sidecar:
                raise PoolError("blob tx requires a sidecar (add_blob_transaction)")
            if not tx.blob_versioned_hashes:
                raise PoolError("blob tx without blobs")
            if tx.max_fee_per_blob_gas < self.blob_base_fee:
                raise PoolError("max blob fee below current blob base fee")
        # wrong-chain txs can never execute here — reject at admission
        # (reference EthTransactionValidator chain-id check); legacy
        # pre-EIP-155 txs carry no chain id and pass
        if (self.config.chain_id is not None and tx.chain_id is not None
                and tx.chain_id != self.config.chain_id):
            raise PoolError(
                f"wrong chain id {tx.chain_id} (expected {self.config.chain_id})")
        if sender is None:
            try:
                sender = tx.recover_sender()
            except ValueError as e:
                raise PoolError(f"invalid signature: {e}")
        if tx.tx_type >= 2 and tx.max_priority_fee_per_gas > tx.max_fee_per_gas:
            raise PoolError("priority fee exceeds max fee")
        # operator price floor (miner_setGasPrice): tip for 1559 txs,
        # gas price for legacy
        floor = self.config.minimal_protocol_fee
        if floor:
            offered = (tx.max_priority_fee_per_gas if tx.tx_type >= 2
                       else tx.gas_price)
            if offered < floor:
                raise PoolError("transaction underpriced (below pool floor)")
        if tx.gas_limit > 30_000_000:
            raise PoolError("gas limit too high")
        state = self.state_reader()
        acct = state.account(sender)
        nonce_on_chain = acct.nonce if acct else 0
        balance = acct.balance if acct else 0
        if tx.nonce < nonce_on_chain:
            raise PoolError("nonce too low")
        cost = tx.gas_limit * (tx.max_fee_per_gas if tx.tx_type >= 2 else tx.gas_price) + tx.value
        cost += tx.blob_gas() * tx.max_fee_per_blob_gas  # type-3 blob budget
        if cost > balance:
            raise PoolError("insufficient funds")
        sender_txs = self.by_sender.setdefault(sender, {})
        existing = sender_txs.get(tx.nonce)
        replaced_hash: bytes | None = None
        if existing is not None:
            bump = existing.max_fee() * (100 + MIN_PRICE_BUMP_PERCENT) // 100
            if self._fee_of(tx) < bump:
                raise PoolError("replacement underpriced")
            replaced_hash = existing.tx.hash
            self._drop(existing.tx.hash)
        if len(sender_txs) >= self.config.max_account_slots and existing is None:
            raise PoolError("sender slot limit")
        if len(self.by_hash) >= self.config.max_pool_size:
            # saturated: evict the worst-paying tx (and its descendants)
            # for a better one, else reject as underpriced (reference
            # discard_worst, pool/txpool.rs:1232)
            if tx.tx_type >= 2:
                tip = min(tx.max_priority_fee_per_gas,
                          max(0, tx.max_fee_per_gas - self.base_fee))
            else:
                tip = tx.gas_price - self.base_fee
            self._discard_worst(tip)
            # the discard may have evicted THIS sender's worst tx and
            # dropped its by_sender entry — re-anchor, or the insert below
            # would write into an orphaned dict invisible to the pool
            sender_txs = self.by_sender.setdefault(sender, {})
        ptx = PooledTx(tx, sender, next(self._submission_counter), cost)
        sender_txs[tx.nonce] = ptx
        self.by_hash[h] = ptx
        if replaced_hash is not None:
            self._emit("replace", tx=tx, sender=sender, old_hash=replaced_hash)
        else:
            self._emit("add", tx=tx, sender=sender)
        self.updated.set()
        return h

    def _discard_worst(self, incoming_tip: int) -> None:
        """Make room in a full pool: drop the lowest-priority tx plus its
        same-sender descendants (their nonces gap without it); raise when
        the incoming tx does not pay more than the current worst."""
        worst = min(self.by_hash.values(),
                    key=lambda p: (p.effective_tip(self.base_fee),
                                   -p.submission_id))
        if worst.effective_tip(self.base_fee) >= incoming_tip:
            raise PoolError("pool full: transaction underpriced")
        txs = self.by_sender.get(worst.sender, {})
        for n in sorted(n for n in txs if n >= worst.nonce):
            dropped = txs[n].tx.hash
            self._drop(dropped)
            del txs[n]
            self._emit("drop", hash=dropped, sender=worst.sender,
                       reason="evicted")
        if not txs:
            self.by_sender.pop(worst.sender, None)

    def _fee_of(self, tx: Transaction) -> int:
        return tx.max_fee_per_gas if tx.tx_type >= 2 else tx.gas_price

    def _drop(self, tx_hash: bytes, mined: bool = False) -> None:
        self.by_hash.pop(tx_hash, None)
        if mined and self.blob_store.get(tx_hash) is not None:
            # keep the sidecar until the retention window evicts it
            self._mined_sidecars.append(tx_hash)
            while len(self._mined_sidecars) > self.mined_sidecar_retention:
                self.blob_store.delete(self._mined_sidecars.pop(0))
            return
        self.blob_store.delete(tx_hash)

    def remove_invalid(self, tx_hash: bytes) -> None:
        """Evict a tx the payload builder proved unexecutable (reference
        BestTransactions::mark_invalid feeding pool removal) — without this
        an instant-seal dev miner spins forever on a 'best' tx that every
        build skips."""
        with self._lock:
            self._remove_invalid_locked(tx_hash)

    def _remove_invalid_locked(self, tx_hash: bytes) -> None:
        ptx = self.by_hash.get(tx_hash)
        if ptx is None:
            return
        self._drop(tx_hash)
        txs = self.by_sender.get(ptx.sender)
        if txs is not None:
            txs.pop(ptx.nonce, None)
            if not txs:
                del self.by_sender[ptx.sender]
        self._emit("drop", hash=tx_hash, sender=ptx.sender, reason="invalid")

    def get_blob_sidecar(self, tx_hash: bytes):
        return self.blob_store.get(tx_hash)

    # -- queries ---------------------------------------------------------------

    def get(self, tx_hash: bytes) -> Transaction | None:
        ptx = self.by_hash.get(tx_hash)
        return ptx.tx if ptx else None

    def contains(self, tx_hash: bytes) -> bool:
        return tx_hash in self.by_hash

    def __len__(self) -> int:
        return len(self.by_hash)

    def pooled_nonce(self, sender: bytes) -> int | None:
        """Highest contiguous pooled nonce + 1 for a sender (for RPC
        'pending' transaction count)."""
        state = self.state_reader()
        acct = state.account(sender)
        nonce = acct.nonce if acct else 0
        txs = self.by_sender.get(sender, {})
        while nonce in txs:
            nonce += 1
        return nonce

    def _bucket(self, ptx: PooledTx, nonce_on_chain: int, pending_gap: bool) -> str:
        if pending_gap:
            return "queued"
        if ptx.effective_tip(self.base_fee) < 0:
            return "basefee"
        return "pending"

    def content(self) -> dict[str, dict[bytes, dict[int, Transaction]]]:
        """txpool_content-shaped view: {pending|queued: {sender: {nonce: tx}}}."""
        out = {"pending": {}, "queued": {}}
        state = self.state_reader()
        for sender, txs in self.by_sender.items():
            acct = state.account(sender)
            next_nonce = acct.nonce if acct else 0
            for nonce in sorted(txs):
                ptx = txs[nonce]
                gap = nonce > next_nonce
                bucket = self._bucket(ptx, next_nonce, gap)
                key = "pending" if bucket == "pending" else "queued"
                out[key].setdefault(sender, {})[nonce] = ptx.tx
                if not gap:
                    next_nonce = nonce + 1
        return out

    # -- best transactions ------------------------------------------------------

    def best_transactions(self, base_fee: int | None = None):
        """Yield executable txs, highest effective tip first, nonce-ordered
        per sender (reference BestTransactions)."""
        base_fee = self.base_fee if base_fee is None else base_fee
        state = self.state_reader()
        heads: dict[bytes, int] = {}  # sender -> next yieldable nonce
        for sender in self.by_sender:
            acct = state.account(sender)
            heads[sender] = acct.nonce if acct else 0
        # heap keyed (-tip, submission_id): O(log n) per yield instead of a
        # full re-sort per transaction (reference BestTransactions keeps the
        # same priority order over its own BTree)
        import heapq

        heap: list[tuple[int, int, PooledTx]] = []
        for sender, txs in self.by_sender.items():
            ptx = txs.get(heads[sender])
            if ptx is not None and self._executable(ptx, base_fee):
                heapq.heappush(
                    heap, (-ptx.effective_tip(base_fee), ptx.submission_id, ptx))
        while heap:
            _, _, best = heapq.heappop(heap)
            yield best.tx
            heads[best.sender] += 1
            # .get twice: a consumer may remove_invalid() mid-iteration
            nxt = self.by_sender.get(best.sender, {}).get(heads[best.sender])
            if nxt is not None and self._executable(nxt, base_fee):
                heapq.heappush(
                    heap, (-nxt.effective_tip(base_fee), nxt.submission_id, nxt))

    def _executable(self, ptx: PooledTx, base_fee: int) -> bool:
        if ptx.effective_tip(base_fee) < 0:
            return False
        # blob subpool gate: blob txs wait until the blob fee market allows
        if ptx.tx.tx_type == 3 and ptx.tx.max_fee_per_blob_gas < self.blob_base_fee:
            return False
        return True

    # -- maintenance -------------------------------------------------------------

    def on_canonical_state_change(self, base_fee: int,
                                  blob_base_fee: int | None = None) -> None:
        """New head: drop mined/underfunded txs, update the fee markets.

        Reference: the maintenance task (src/maintain.rs) driven by
        CanonStateNotifications.
        """
        with self._lock:
            self._on_canon_locked(base_fee, blob_base_fee)

    def _on_canon_locked(self, base_fee: int,
                         blob_base_fee: int | None) -> None:
        self.base_fee = base_fee
        if blob_base_fee is not None:
            self.blob_base_fee = blob_base_fee
        state = self.state_reader()
        for sender in list(self.by_sender):
            acct = state.account(sender)
            nonce = acct.nonce if acct else 0
            balance = acct.balance if acct else 0
            txs = self.by_sender[sender]
            for n in [n for n in txs if n < nonce]:
                mined_hash = txs[n].tx.hash
                self._drop(mined_hash, mined=True)
                del txs[n]
                self._emit("drop", hash=mined_hash, sender=sender,
                           reason="mined")
            for n in [n for n in txs if txs[n].cost > balance]:
                poor_hash = txs[n].tx.hash
                self._drop(poor_hash)
                del txs[n]
                self._emit("drop", hash=poor_hash, sender=sender,
                           reason="underfunded")
            if not txs:
                del self.by_sender[sender]
        # one canon marker even when nothing dropped: the fee market moved,
        # so the producer's candidate ordering may be stale
        self._emit("canon", base_fee=base_fee,
                   blob_base_fee=self.blob_base_fee)
        if self.by_hash:
            self.updated.set()  # remaining txs may have become executable
