"""Broadcast event channels (EventSender/EventStream analogue).

Reference analogue: crates/tokio-util's `EventSender`/`EventStream` — a
bounded broadcast channel node components use to publish lifecycle events
(pipeline progress, canon changes, network events) to any number of late
subscribers without blocking the producer.

Semantics matched to the reference: sends never block (slow subscribers
drop their OLDEST queued events — lagging consumers skip ahead, they do
not stall consensus), subscribing is cheap, and a closed sender wakes all
streams with end-of-stream.
"""

from __future__ import annotations

import threading
from collections import deque


class EventStream:
    """One subscriber's view: iterate, or poll with ``next(timeout)``."""

    def __init__(self, sender: "EventSender", maxlen: int):
        self._buf: deque = deque(maxlen=maxlen)
        self._cond = threading.Condition()
        self._closed = False
        self._sender = sender
        self.dropped = 0  # events lost to lag (oldest-first)

    def _push(self, event) -> None:
        with self._cond:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(event)
            self._cond.notify()

    def _close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def next(self, timeout: float | None = None):
        """The next event, or None on close/timeout."""
        with self._cond:
            if not self._buf and not self._closed:
                self._cond.wait(timeout)
            if self._buf:
                return self._buf.popleft()
            return None

    def __iter__(self):
        while True:
            ev = self.next()
            if ev is None and self._closed:
                return
            if ev is not None:
                yield ev

    def unsubscribe(self) -> None:
        self._sender._remove(self)


class EventSender:
    """Fan-out sender; ``new_listener()`` returns an independent stream."""

    def __init__(self, buffer: int = 256):
        self._buffer = buffer
        self._streams: list[EventStream] = []
        self._lock = threading.Lock()
        self._closed = False

    def new_listener(self) -> EventStream:
        s = EventStream(self, self._buffer)
        with self._lock:
            if self._closed:
                s._close()
            else:
                self._streams.append(s)
        return s

    def notify(self, event) -> None:
        with self._lock:
            streams = list(self._streams)
        for s in streams:
            s._push(event)

    def _remove(self, stream: EventStream) -> None:
        with self._lock:
            if stream in self._streams:
                self._streams.remove(stream)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            streams, self._streams = self._streams, []
        for s in streams:
            s._close()
