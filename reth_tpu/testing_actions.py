"""Declarative e2e test actions: drive a live node as a scripted scenario.

Reference analogue: crates/e2e-test-utils' `Action` trait + testsuite
(setup → ordered actions, each acting on the node and asserting on the
result): ProduceBlocks, ReorgTo, SubmitTransaction, expect-status
combinators. Actions here run against a live in-process `Node` (RPC +
engine + dev miner), so a scenario reads as the user/CL behavior it
encodes.

    TestSuite(node).run(
        SubmitTransaction(wallet, to=bob, value=100),
        ProduceBlocks(1),
        AssertChainTip(1),
        AssertBalance(bob, 100),
        ReorgTo(0),
        AssertChainTip(0),
    )
"""

from __future__ import annotations

import time


class ActionError(AssertionError):
    pass


class TestSuite:
    """Ordered action runner over a live Node."""

    def __init__(self, node):
        self.node = node

    def run(self, *actions) -> "TestSuite":
        for i, action in enumerate(actions):
            try:
                action(self.node)
            except ActionError as e:
                raise ActionError(
                    f"action #{i} {type(action).__name__}: {e}") from None
        return self


class SubmitTransaction:
    def __init__(self, wallet, to: bytes, value: int, chain_id: int = 1):
        self.tx = wallet.transfer(to, value, chain_id=chain_id)

    def __call__(self, node):
        node.pool.add_transaction(self.tx)


class SubmitRawTransaction:
    def __init__(self, tx):
        self.tx = tx

    def __call__(self, node):
        node.pool.add_transaction(self.tx)


class ProduceBlocks:
    """Mine n blocks through the dev miner (the CL-loop stand-in)."""

    def __init__(self, n: int):
        self.n = n

    def __call__(self, node):
        for _ in range(self.n):
            node.miner.mine_block()


class ProduceInvalidPayload:
    """Submit a tampered payload; expects the engine to reject it."""

    def __init__(self, tamper):
        self.tamper = tamper  # fn(Block) -> Block

    def __call__(self, node):
        from reth_tpu.engine.tree import PayloadStatusKind
        from reth_tpu.payload.builder import PayloadAttributes, build_payload

        with node.factory.provider() as p:
            ts = p.header_by_number(p.last_block_number()).timestamp
        block, _ = build_payload(node.tree, None, node.tree.head_hash,
                                 PayloadAttributes(timestamp=ts + 1))
        st = node.tree.on_new_payload(self.tamper(block))
        if st.status is not PayloadStatusKind.INVALID:
            raise ActionError(f"expected INVALID, got {st.status.name}")


class ReorgTo:
    """Forkchoice back to an earlier canonical block."""

    def __init__(self, number: int):
        self.number = number

    def __call__(self, node):
        target = None
        with node.factory.provider() as p:
            target = p.canonical_hash(self.number)
        if target is None:
            # unpersisted tip blocks live in the tree; walk the CANONICAL
            # chain (a fork sibling at the same height must not win)
            head = node.tree.head_hash
            while head is not None:
                eb = node.tree.blocks.get(head)
                if eb is None:
                    break
                if eb.block.header.number == self.number:
                    target = head
                    break
                head = eb.block.header.parent_hash
        if target is None:
            raise ActionError(f"no canonical block {self.number}")
        node.tree.on_forkchoice_updated(target)


class WaitFor:
    """Poll a predicate(node) -> bool until true or timeout."""

    def __init__(self, predicate, timeout: float = 5.0):
        self.predicate = predicate
        self.timeout = timeout

    def __call__(self, node):
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if self.predicate(node):
                return
            time.sleep(0.02)
        raise ActionError("predicate never became true")


class AssertChainTip:
    def __init__(self, number: int):
        self.number = number

    def __call__(self, node):
        eb = node.tree.blocks.get(node.tree.head_hash)
        if eb is not None:
            tip = eb.block.header.number
        else:
            with node.factory.provider() as p:
                tip = p.block_number(node.tree.head_hash)
        if tip != self.number:
            raise ActionError(f"tip is {tip}, expected {self.number}")


class AssertBalance:
    def __init__(self, address: bytes, value: int):
        self.address = address
        self.value = value

    def __call__(self, node):
        got = node.tree.overlay_provider().account(self.address)
        bal = got.balance if got else 0
        if bal != self.value:
            raise ActionError(
                f"balance of 0x{self.address.hex()} is {bal}, "
                f"expected {self.value}")


class AssertPoolSize:
    def __init__(self, n: int):
        self.n = n

    def __call__(self, node):
        if len(node.pool) != self.n:
            raise ActionError(f"pool has {len(node.pool)}, expected {self.n}")


# -- fork builders (Engine-API adversarial scenarios) -------------------------
#
# Reference analogue: e2e-test-utils' testsuite fork helpers (CreateFork /
# ReorgTo over produced payload chains). ForkBuilder plays the hostile CL:
# a shadow, fault-free engine tree that can seal a consensus-valid block
# on ANY parent it knows — side-chain forks at arbitrary depths, longer
# competing branches, orphan subtrees. Because the shadow tree executes
# and root-checks every block itself with a plain CPU committer and no
# fault injectors, it doubles as the fault-free twin the chaos consensus
# domain (reth_tpu/chaos.py) compares the drilled node against: any block
# both trees accepted carries, by construction, bit-identical roots.


class _TxFeed:
    """Minimal pool view for ``build_payload``: a fixed candidate list."""

    def __init__(self, txs):
        self._txs = list(txs)

    def best_transactions(self, base_fee):
        return iter(self._txs)

    def remove_invalid(self, tx_hash):
        pass


class ForkBuilder:
    """CL-side block factory over a shadow fault-free engine tree."""

    def __init__(self, genesis_header, genesis_alloc, wallet=None,
                 committer=None, genesis_storage=None, genesis_codes=None,
                 chain_id: int = 1):
        from reth_tpu.engine import EngineTree
        from reth_tpu.evm import EvmConfig
        from reth_tpu.primitives.keccak import keccak256_batch_np
        from reth_tpu.primitives.types import Block
        from reth_tpu.storage import MemDb, ProviderFactory
        from reth_tpu.storage.genesis import init_genesis
        from reth_tpu.trie.committer import TrieCommitter

        if committer is None:
            committer = TrieCommitter(hasher=keccak256_batch_np)
        self.chain_id = chain_id
        self.wallet = wallet
        self.factory = ProviderFactory(MemDb())
        init_genesis(self.factory, genesis_header, genesis_alloc,
                     genesis_storage, genesis_codes, committer=committer)
        # a huge persistence threshold keeps every fork in memory, so any
        # known block can parent a new one via the overlay provider
        self.tree = EngineTree(self.factory, committer=committer,
                               config=EvmConfig(chain_id=chain_id),
                               persistence_threshold=1_000_000_000)
        self.genesis_hash = genesis_header.hash
        self.blocks: dict[bytes, Block] = {
            self.genesis_hash: Block(genesis_header, (), (), ())}

    def number_of(self, block_hash: bytes) -> int:
        return self.blocks[block_hash].header.number

    def ancestor(self, block_hash: bytes, depth: int) -> bytes:
        """The hash ``depth`` parents above ``block_hash`` (clamped at
        genesis)."""
        h = block_hash
        for _ in range(depth):
            if h == self.genesis_hash:
                break
            h = self.blocks[h].header.parent_hash
        return h

    def branch_point(self, a: bytes, b: bytes):
        """(number, hash) of the deepest common ancestor of two known
        blocks, or None when either is unknown to the builder."""
        if a not in self.blocks or b not in self.blocks:
            return None
        on_a = set()
        h = a
        while True:
            on_a.add(h)
            if h == self.genesis_hash:
                break
            h = self.blocks[h].header.parent_hash
        h = b
        while h not in on_a:
            h = self.blocks[h].header.parent_hash
        return (self.blocks[h].header.number, h)

    def block_on(self, parent_hash: bytes, txs: int = 1, salt: int = 0):
        """Seal (and shadow-import) a valid block on ``parent_hash``.
        ``salt`` diversifies siblings (timestamp + transfer target), so
        repeated calls on one parent mint distinct competing blocks."""
        from reth_tpu.payload.builder import PayloadAttributes, build_payload
        from reth_tpu.primitives.types import Transaction

        overlay = self.tree.overlay_provider(parent_hash)
        parent = overlay.header_by_number(
            overlay.block_number(parent_hash))
        feed = None
        if txs and self.wallet is not None:
            acct = overlay.account(self.wallet.address)
            nonce = acct.nonce if acct is not None else 0
            sink = bytes([0xD0 + (salt % 16)]) * 20
            signed = []
            for i in range(txs):
                signed.append(self.wallet.sign_tx(Transaction(
                    tx_type=2, chain_id=self.chain_id, nonce=nonce + i,
                    max_fee_per_gas=100 * 10**9,
                    max_priority_fee_per_gas=10**9, gas_limit=21_000,
                    to=sink, value=1_000 + salt), bump_nonce=False))
            feed = _TxFeed(signed)
        block, _ = build_payload(
            self.tree, feed, parent_hash,
            PayloadAttributes(timestamp=parent.timestamp + 1 + salt))
        st = self.tree.on_new_payload(block)
        if st.status.value != "VALID":
            raise ActionError(
                f"fork builder sealed an invalid block: {st.validation_error}")
        self.blocks[block.hash] = block
        return block

    def chain_on(self, parent_hash: bytes, length: int, txs: int = 1,
                 salt: int = 0) -> list:
        """A fork of ``length`` blocks rooted at ``parent_hash``."""
        out = []
        tip = parent_hash
        for i in range(length):
            blk = self.block_on(tip, txs=txs, salt=salt if i == 0 else 0)
            out.append(blk)
            tip = blk.hash
        return out


def tampered_block(block, kind: str, salt: bytes = b""):
    """A consensus-invalid (or orphaned) variant of a valid block.

    Kinds: ``state_root`` / ``receipts_root`` / ``gas_used`` (rejected
    after execution), ``gas_limit`` (rejected by header validation),
    ``unknown_parent`` (a fabricated parent — the orphan/SYNCING shape),
    ``reparent`` (parent := ``salt`` — build invalid-ancestor chains on
    a known-invalid block). ``salt`` also perturbs the timestamp so
    repeated tampers of one block mint distinct hashes."""
    from reth_tpu.primitives.types import Block, Header

    h = dict(block.header.__dict__)
    # uniqueness bump from the salt TAIL: ``reparent`` consumes the salt
    # HEAD as the new parent hash, so flood callers append a counter
    bump = int.from_bytes(salt[-4:], "big") % 1021 if salt else 0
    if kind == "state_root":
        h["state_root"] = bytes([0x13 + bump % 7]) * 32
    elif kind == "receipts_root":
        h["receipts_root"] = bytes([0x17 + bump % 7]) * 32
    elif kind == "gas_used":
        h["gas_used"] = block.header.gas_used + 1 + bump
    elif kind == "gas_limit":
        h["gas_limit"] = block.header.gas_limit * 2  # > 1/1024 step
    elif kind == "unknown_parent":
        h["parent_hash"] = (salt * 32)[:32] if salt else b"\x99" * 32
        h["timestamp"] = block.header.timestamp + 1 + bump
    elif kind == "reparent":
        h["parent_hash"] = salt[:32]
        h["timestamp"] = block.header.timestamp + 1 + bump
    else:
        raise ValueError(f"unknown tamper kind {kind!r}")
    return Block(Header(**h), block.transactions, block.ommers,
                 block.withdrawals)


class ProduceSideChain:
    """Build a ``length``-block fork off the canonical chain ``depth``
    blocks below the tip (via a ForkBuilder) and feed it to the node;
    with ``switch`` the forkchoice flips to the fork tip (a reorg)."""

    def __init__(self, fork: ForkBuilder, depth: int, length: int,
                 switch: bool = True, salt: int = 5):
        self.fork = fork
        self.depth = depth
        self.length = length
        self.switch = switch
        self.salt = salt

    def __call__(self, node):
        from reth_tpu.engine.tree import PayloadStatusKind

        head = node.tree.head_hash
        if head not in self.fork.blocks:
            raise ActionError("node head unknown to the fork builder — "
                              "drive the node through the same builder")
        anc = self.fork.ancestor(head, self.depth)
        chain = self.fork.chain_on(anc, self.length, salt=self.salt)
        for blk in chain:
            st = node.tree.on_new_payload(blk)
            if st.status is PayloadStatusKind.INVALID:
                raise ActionError(
                    f"fork block rejected: {st.validation_error}")
        if self.switch:
            st = node.tree.on_forkchoice_updated(chain[-1].hash)
            if st.status is not PayloadStatusKind.VALID:
                raise ActionError(f"fork fcU: {st.status.name}")
