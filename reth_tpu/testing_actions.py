"""Declarative e2e test actions: drive a live node as a scripted scenario.

Reference analogue: crates/e2e-test-utils' `Action` trait + testsuite
(setup → ordered actions, each acting on the node and asserting on the
result): ProduceBlocks, ReorgTo, SubmitTransaction, expect-status
combinators. Actions here run against a live in-process `Node` (RPC +
engine + dev miner), so a scenario reads as the user/CL behavior it
encodes.

    TestSuite(node).run(
        SubmitTransaction(wallet, to=bob, value=100),
        ProduceBlocks(1),
        AssertChainTip(1),
        AssertBalance(bob, 100),
        ReorgTo(0),
        AssertChainTip(0),
    )
"""

from __future__ import annotations

import time


class ActionError(AssertionError):
    pass


class TestSuite:
    """Ordered action runner over a live Node."""

    def __init__(self, node):
        self.node = node

    def run(self, *actions) -> "TestSuite":
        for i, action in enumerate(actions):
            try:
                action(self.node)
            except ActionError as e:
                raise ActionError(
                    f"action #{i} {type(action).__name__}: {e}") from None
        return self


class SubmitTransaction:
    def __init__(self, wallet, to: bytes, value: int, chain_id: int = 1):
        self.tx = wallet.transfer(to, value, chain_id=chain_id)

    def __call__(self, node):
        node.pool.add_transaction(self.tx)


class SubmitRawTransaction:
    def __init__(self, tx):
        self.tx = tx

    def __call__(self, node):
        node.pool.add_transaction(self.tx)


class ProduceBlocks:
    """Mine n blocks through the dev miner (the CL-loop stand-in)."""

    def __init__(self, n: int):
        self.n = n

    def __call__(self, node):
        for _ in range(self.n):
            node.miner.mine_block()


class ProduceInvalidPayload:
    """Submit a tampered payload; expects the engine to reject it."""

    def __init__(self, tamper):
        self.tamper = tamper  # fn(Block) -> Block

    def __call__(self, node):
        from reth_tpu.engine.tree import PayloadStatusKind
        from reth_tpu.payload.builder import PayloadAttributes, build_payload

        with node.factory.provider() as p:
            ts = p.header_by_number(p.last_block_number()).timestamp
        block, _ = build_payload(node.tree, None, node.tree.head_hash,
                                 PayloadAttributes(timestamp=ts + 1))
        st = node.tree.on_new_payload(self.tamper(block))
        if st.status is not PayloadStatusKind.INVALID:
            raise ActionError(f"expected INVALID, got {st.status.name}")


class ReorgTo:
    """Forkchoice back to an earlier canonical block."""

    def __init__(self, number: int):
        self.number = number

    def __call__(self, node):
        target = None
        with node.factory.provider() as p:
            target = p.canonical_hash(self.number)
        if target is None:
            # unpersisted tip blocks live in the tree; walk the CANONICAL
            # chain (a fork sibling at the same height must not win)
            head = node.tree.head_hash
            while head is not None:
                eb = node.tree.blocks.get(head)
                if eb is None:
                    break
                if eb.block.header.number == self.number:
                    target = head
                    break
                head = eb.block.header.parent_hash
        if target is None:
            raise ActionError(f"no canonical block {self.number}")
        node.tree.on_forkchoice_updated(target)


class WaitFor:
    """Poll a predicate(node) -> bool until true or timeout."""

    def __init__(self, predicate, timeout: float = 5.0):
        self.predicate = predicate
        self.timeout = timeout

    def __call__(self, node):
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if self.predicate(node):
                return
            time.sleep(0.02)
        raise ActionError("predicate never became true")


class AssertChainTip:
    def __init__(self, number: int):
        self.number = number

    def __call__(self, node):
        eb = node.tree.blocks.get(node.tree.head_hash)
        if eb is not None:
            tip = eb.block.header.number
        else:
            with node.factory.provider() as p:
                tip = p.block_number(node.tree.head_hash)
        if tip != self.number:
            raise ActionError(f"tip is {tip}, expected {self.number}")


class AssertBalance:
    def __init__(self, address: bytes, value: int):
        self.address = address
        self.value = value

    def __call__(self, node):
        got = node.tree.overlay_provider().account(self.address)
        bal = got.balance if got else 0
        if bal != self.value:
            raise ActionError(
                f"balance of 0x{self.address.hex()} is {bal}, "
                f"expected {self.value}")


class AssertPoolSize:
    def __init__(self, n: int):
        self.n = n

    def __call__(self, node):
        if len(node.pool) != self.n:
            raise ActionError(f"pool has {len(node.pool)}, expected {self.n}")
