"""reth-tpu: a TPU-native Ethereum execution-layer framework.

A brand-new framework with the capabilities of the reference client
(paradigmxyz/reth): staged historical sync, block execution, MDBX-class
storage, Merkle-Patricia-Trie state commitment, Engine API, and JSON-RPC —
with the state-commitment data plane (batched Keccak-256 node hashing)
expressed as shape-stable JAX/XLA/Pallas kernels that run on TPU.

Layer map (mirrors the reference's layering, see SURVEY.md §1):

- ``reth_tpu.primitives``  — B256/Address/RLP/nibbles/keccak CPU reference
  (reference layer 0: alloy-primitives, alloy-rlp, alloy-trie).
- ``reth_tpu.ops``         — device kernels: batched keccak-f[1600] in JAX
  and Pallas (replaces the reference's `asm-keccak` sha3-asm fast path).
- ``reth_tpu.storage``     — typed tables, Database/Tx/Cursor traits, memdb
  (reference: crates/storage/db-api, crates/storage/db).
- ``reth_tpu.trie``        — StateRoot/StorageRoot walkers, HashBuilder,
  prefix sets, sparse trie, proofs (reference: crates/trie/*).
- ``reth_tpu.evm``         — block execution on CPU (reference: revm glue).
- ``reth_tpu.consensus``   — header/body/post-execution validation.
- ``reth_tpu.stages``      — staged-sync pipeline (reference: crates/stages).
- ``reth_tpu.engine``      — live-tip tree, state-root strategies.
- ``reth_tpu.parallel``    — device meshes, sharded hashing, host↔device
  batching (the reference's rayon/crossbeam analogue).
- ``reth_tpu.utils``       — ETL collector, misc.
"""

__version__ = "0.1.0"
