"""ETL external-sort collector: buffer -> sorted spill files -> k-way merge.

Reference analogue: `Collector<K, V>` (crates/etl/src/lib.rs:31-40) —
bulk loads into sorted tables (hashed state, tx hashes) buffer here
first, spill sorted runs to disk when the memory budget is hit, and
stream back in globally sorted order via a heap merge. Keeps bulk-load
memory bounded regardless of input size, and makes the final table
inserts append-ordered (cheap for any B+tree-ish store).
"""

from __future__ import annotations

import heapq
import os
import struct
import tempfile
from typing import Iterator


class Collector:
    """Collects (key, value) byte pairs; iterates them in sorted order.

    Duplicate keys are preserved in insertion order (stable merge) — the
    caller decides last-wins or error semantics. Use as a context manager
    or call ``close()`` to drop spill files."""

    def __init__(self, buffer_bytes: int = 64 * 1024 * 1024, tmp_dir: str | None = None):
        self.buffer_bytes = buffer_bytes
        self.tmp_dir = tmp_dir
        self._buf: list[tuple[bytes, int, bytes]] = []  # (key, seq, value)
        self._buf_size = 0
        self._files: list = []
        self._seq = 0
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def insert(self, key: bytes, value: bytes) -> None:
        self._buf.append((key, self._seq, value))
        self._seq += 1
        self._len += 1
        self._buf_size += len(key) + len(value) + 16
        if self._buf_size >= self.buffer_bytes:
            self._spill()

    def _spill(self) -> None:
        if not self._buf:
            return
        self._buf.sort()
        f = tempfile.TemporaryFile(dir=self.tmp_dir, prefix="reth-tpu-etl-")
        w = f.write
        for key, seq, value in self._buf:
            w(struct.pack("<IQI", len(key), seq, len(value)))
            w(key)
            w(value)
        f.flush()
        f.seek(0)
        self._files.append(f)
        self._buf = []
        self._buf_size = 0

    @staticmethod
    def _read_run(f) -> Iterator[tuple[bytes, int, bytes]]:
        header = struct.Struct("<IQI")
        while True:
            raw = f.read(header.size)
            if not raw:
                return
            klen, seq, vlen = header.unpack(raw)
            key = f.read(klen)
            value = f.read(vlen)
            yield (key, seq, value)

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        """Globally sorted (key, value) stream across buffer + spills."""
        self._buf.sort()
        runs: list = [iter(self._buf)]
        for f in self._files:
            f.seek(0)
            runs.append(self._read_run(f))
        for key, _seq, value in heapq.merge(*runs):
            yield key, value

    def close(self) -> None:
        for f in self._files:
            f.close()
        self._files = []
        self._buf = []
        self._buf_size = 0
        self._len = 0

    def __enter__(self) -> "Collector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
