"""Device hasher supervisor: health probes, circuit breaker, watchdog-bounded
dispatch, and mid-commit CPU failover for the state-commitment path.

The repo's own bench history records the device tunnel wedged at whole
measurement windows (BENCH_r04/r05, VERDICT round 2) — and until now only
``bench.py`` knew how to probe it. The runtime path (``trie/committer.py``,
``ops/fused_commit.py``, ``trie/turbo.py``, ``engine/sparse_root.py``)
would simply hang the node on a stalled dispatch. This module makes device
flakiness a first-class failure mode, the way production accelerator
stacks do (cf. the bounded-queue backend isolation of arxiv 2503.04595):

- **Health probe** (:func:`probe_device`): a tiny jit in a SUBPROCESS under
  a hard wall-clock budget — promoted from ``bench.py:probe_tunnel`` so the
  node, the bench, and tests share one implementation. A wedged tunnel
  kills the child, never the caller.
- **Circuit breaker** (:class:`CircuitBreaker`): closed → open → half-open
  with exponential backoff. After ``failure_threshold`` watchdog trips all
  hashing routes to the numpy twin (``trie/turbo._NumpyBackend`` /
  ``keccak256_batch_np``) until a half-open probe succeeds.
- **Watchdog-bounded dispatch** (:meth:`DeviceSupervisor.run_guarded`):
  every device call gets a wall-clock budget in a worker thread; a trip
  abandons the wedged thread and fails over. Because the committer is
  level-batched and every dispatch's inputs are host numpy arrays, the
  :class:`SupervisedBackend` journals them and REPLAYS the same commit on
  the CPU twin from the current level boundary — no block is lost, the
  state root is still produced.
- **Fault injection** (:class:`FaultInjector`): env/CLI-configurable
  wedge-every-Nth-dispatch / fixed-delay / probe-failure policies in the
  style of ``engine/util.py``'s EngineSkip, so every failover path is
  testable without real hardware.
- **Observability**: breaker state, trips, failovers, and probe latency on
  ``/metrics`` (``metrics.SupervisorMetrics``) and the ``node/events.py``
  dashboard line.

Wiring: ``--hasher auto`` (cli.py) runs the startup probe and installs the
supervised committer; ``TurboCommitter(backend="auto")`` routes through
:class:`SupervisedBackend`.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from .. import tracing

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# the same tiny program bench.py always probed with: device discovery plus
# one trivial jit round trip — enough to catch a wedged tunnel, cheap
# enough to run on re-probe timers
PROBE_CODE = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "y = jax.jit(lambda a: a ^ (a << 1))(jnp.arange(256, dtype=jnp.uint32))\n"
    "y.block_until_ready()\n"
    "print('PROBE_OK', d[0].platform, flush=True)\n"
)


class DeviceDispatchError(RuntimeError):
    """A supervised device call failed or exceeded its watchdog budget."""


class InjectedWedge(DeviceDispatchError):
    """Fault injection wedged this dispatch (RETH_TPU_FAULT_WEDGE_EVERY)."""


class InjectedDeviceWedge(DeviceDispatchError):
    """Fault injection wedged ONE SPECIFIC mesh device
    (RETH_TPU_FAULT_DEVICE_WEDGE) — carries the device index so the
    per-device breaker can attribute the failure and shrink the mesh
    around it instead of tripping the whole-device route."""

    def __init__(self, device_index: int, msg: str):
        super().__init__(msg)
        self.device_index = device_index


class InjectedPipelineAbort(RuntimeError):
    """Fault injection killed the rebuild pipeline at a window boundary
    (RETH_TPU_FAULT_PIPELINE_ABORT) — the in-process analogue of a crash
    mid-queue. Deliberately NOT a DeviceDispatchError: it must abort the
    whole chunk (so resume-from-progress is exercised), not fail over."""


class ProbeResult:
    __slots__ = ("ok", "latency", "diag")

    def __init__(self, ok: bool, latency: float, diag: str | None = None):
        self.ok = ok
        self.latency = latency
        self.diag = diag

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "ok" if self.ok else f"FAIL ({self.diag})"
        return f"ProbeResult({state}, {self.latency:.3f}s)"


def probe_device(budget: float | None = None, *, code: str = PROBE_CODE,
                 injector: "FaultInjector | None" = None,
                 cache_dir: str | None = None) -> ProbeResult:
    """One fail-fast health probe: run ``code`` in a subprocess under a hard
    wall-clock ``budget``. Returns a :class:`ProbeResult`; never raises and
    never blocks past the budget — a wedged tunnel wedges the CHILD.

    NOTE: the default probe deliberately runs WITHOUT a
    ``jax_compilation_cache_dir`` — the persistent compile cache has
    deadlocked the first jit over the axon tunnel (measured round 2; see
    bench.py). ``cache_dir`` opts IN to cache validation: the child runs
    with the persistent cache configured, so the warm-up manager
    (``ops/warmup.py``) can prove a cache directory loads before wiring it
    into the live process — a wedged cache wedges the child, never the
    node.
    """
    if budget is None:
        budget = float(os.environ.get("RETH_TPU_PROBE_TIMEOUT", "120"))
    if cache_dir is not None:
        code = (
            "import jax\n"
            f"jax.config.update('jax_compilation_cache_dir', {cache_dir!r})\n"
            "jax.config.update('jax_persistent_cache_min_compile_time_secs', 0)\n"
            "jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)\n"
            + code
        )
    t0 = time.monotonic()
    if injector is not None and not injector.on_probe():
        tracing.fault_event("RETH_TPU_FAULT_PROBE_FAIL",
                            target="ops::supervisor")
        return ProbeResult(False, time.monotonic() - t0,
                           "injected probe failure (RETH_TPU_FAULT_PROBE_FAIL)")
    try:
        r = subprocess.run(
            [sys.executable, "-u", "-c", code],
            capture_output=True, text=True, timeout=budget,
        )
    except subprocess.TimeoutExpired:
        diag = f"device probe exceeded {budget}s (wedged tunnel?)"
        tracing.event("ops::supervisor", "probe", ok=False,
                      latency_s=round(time.monotonic() - t0, 3), diag=diag)
        return ProbeResult(False, time.monotonic() - t0, diag)
    except OSError as e:  # pragma: no cover - exec failure
        return ProbeResult(False, time.monotonic() - t0, f"probe spawn failed: {e}")
    latency = time.monotonic() - t0
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        tracing.event("ops::supervisor", "probe", ok=True,
                      latency_s=round(latency, 3))
        return ProbeResult(True, latency)
    tail = (r.stderr or r.stdout).strip().splitlines()[-1:] or ["no output"]
    diag = f"device probe failed rc={r.returncode}: {tail[0][:300]}"
    tracing.event("ops::supervisor", "probe", ok=False,
                  latency_s=round(latency, 3), diag=diag)
    return ProbeResult(False, latency, diag)


def probe_device_retrying(budget: float | None = None, attempts: int | None = None,
                          gap: float | None = None, *,
                          injector: "FaultInjector | None" = None,
                          on_attempt=None) -> ProbeResult:
    """Retry wrapper around :func:`probe_device` — the bench startup policy
    (N attempts spread over the watchdog window so one wedged minute doesn't
    kill a round). ``on_attempt(i, attempts)`` is the bench's phase hook."""
    if attempts is None:
        attempts = int(os.environ.get("RETH_TPU_PROBE_ATTEMPTS", "4"))
    if gap is None:
        gap = float(os.environ.get("RETH_TPU_PROBE_GAP", "45"))
    result = ProbeResult(False, 0.0, "no probe attempts ran")
    for i in range(1, max(attempts, 1) + 1):
        if on_attempt is not None:
            on_attempt(i, attempts)
        result = probe_device(budget, injector=injector)
        if result.ok:
            return result
        if i < attempts:
            time.sleep(gap)
    return result


class FaultInjector:
    """Dispatch/probe fault policies (``engine/util.py`` EngineSkip style).

    ``wedge_every``: every Nth supervised device dispatch raises
    :class:`InjectedWedge` (counts as a watchdog trip). ``wedge_every=1``
    wedges EVERY dispatch — the full-failover drill.
    ``delay``: fixed seconds added to every dispatch — with a delay above
    the watchdog budget this exercises the REAL timeout path.
    ``probe_fail``: the first N health probes report failure (negative =
    all probes fail forever), so breaker recovery is testable.
    ``pipeline_abort``: the Nth rebuild-pipeline window raises
    :class:`InjectedPipelineAbort` — kills the chunk mid-queue so the
    chunked rebuild's resume-from-progress path is testable in-process.
    ``compile_wedge``: the first N warm-up shape compiles wedge past their
    watchdog budget (negative = every compile, until the field is cleared)
    — the ``ops/warmup.py`` degraded-serving / backoff-retry drill.
    ``device_wedge``: a set of MESH DEVICE indices — any sharded dispatch
    whose live mesh still contains one of them raises
    :class:`InjectedDeviceWedge` (attributed), so the per-device breaker
    + shrunken-mesh replay ladder is testable without hardware. Wedging
    every index drills the final CPU rung.

    Env form (read by :meth:`from_env`, also settable via CLI):
    ``RETH_TPU_FAULT_WEDGE_EVERY`` / ``RETH_TPU_FAULT_DELAY`` /
    ``RETH_TPU_FAULT_PROBE_FAIL`` / ``RETH_TPU_FAULT_PIPELINE_ABORT`` /
    ``RETH_TPU_FAULT_COMPILE_WEDGE`` / ``RETH_TPU_FAULT_DEVICE_WEDGE``
    (comma-separated device indices, e.g. ``"2"`` or ``"0,3,5"``).
    """

    def __init__(self, wedge_every: int = 0, delay: float = 0.0,
                 probe_fail: int = 0, pipeline_abort: int = 0,
                 compile_wedge: int = 0, device_wedge=()):
        self.wedge_every = wedge_every
        self.delay = delay
        self.probe_fail = probe_fail
        self.pipeline_abort = pipeline_abort
        self.compile_wedge = compile_wedge
        self.device_wedge = frozenset(int(i) for i in device_wedge)
        self.dispatch_count = 0
        self.wedged = 0
        self.probes_failed = 0
        self.windows = 0
        self.compiles_wedged = 0
        self.devices_wedged = 0
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env=None) -> "FaultInjector | None":
        """Build from env knobs; None when no fault policy is set."""
        env = os.environ if env is None else env
        wedge = int(env.get("RETH_TPU_FAULT_WEDGE_EVERY", "0") or 0)
        delay = float(env.get("RETH_TPU_FAULT_DELAY", "0") or 0)
        probe = int(env.get("RETH_TPU_FAULT_PROBE_FAIL", "0") or 0)
        pabort = int(env.get("RETH_TPU_FAULT_PIPELINE_ABORT", "0") or 0)
        cwedge = int(env.get("RETH_TPU_FAULT_COMPILE_WEDGE", "0") or 0)
        raw = env.get("RETH_TPU_FAULT_DEVICE_WEDGE", "") or ""
        dwedge = tuple(int(x) for x in raw.split(",") if x.strip())
        if not (wedge or delay or probe or pabort or cwedge or dwedge):
            return None
        return cls(wedge_every=wedge, delay=delay, probe_fail=probe,
                   pipeline_abort=pabort, compile_wedge=cwedge,
                   device_wedge=dwedge)

    def active(self) -> bool:
        return bool(self.wedge_every or self.delay or self.probe_fail
                    or self.pipeline_abort or self.compile_wedge
                    or self.device_wedge)

    def on_mesh_dispatch(self, device_indices) -> None:
        """Called before every mesh-sharded dispatch with the live device
        indices. If a wedged device still participates, the dispatch
        fails ATTRIBUTED to that device — exactly the failure shape a
        per-device breaker needs to shrink the mesh around it."""
        if not self.device_wedge:
            return
        hit = sorted(self.device_wedge.intersection(device_indices))
        if not hit:
            return
        with self._lock:
            self.devices_wedged += 1
        tracing.fault_event("RETH_TPU_FAULT_DEVICE_WEDGE",
                            target="parallel::mesh", device=hit[0],
                            live=list(device_indices))
        raise InjectedDeviceWedge(
            hit[0], f"injected wedge on mesh device {hit[0]} "
                    f"(live mesh {list(device_indices)})")

    def on_compile(self, budget: float) -> None:
        """Called inside every warm-up compile worker. A wedged "compile"
        sleeps well past the caller's watchdog ``budget`` in the (abandoned)
        worker thread, so the REAL join-timeout path is exercised."""
        with self._lock:
            if self.compile_wedge == 0:
                return
            if self.compile_wedge > 0:
                self.compile_wedge -= 1
            self.compiles_wedged += 1
        tracing.fault_event("RETH_TPU_FAULT_COMPILE_WEDGE",
                            target="ops::warmup",
                            compile=self.compiles_wedged)
        time.sleep(min(budget * 3 + 1, budget + 60))

    def on_pipeline_window(self) -> None:
        """Called by the rebuild pipeline before dispatching each packed
        window; the Nth call aborts the commit."""
        if not self.pipeline_abort:
            return
        with self._lock:
            self.windows += 1
            n = self.windows
        if n == self.pipeline_abort:
            tracing.fault_event("RETH_TPU_FAULT_PIPELINE_ABORT",
                                target="trie::pipeline", window=n)
            raise InjectedPipelineAbort(
                f"injected pipeline abort at window #{n} "
                f"(RETH_TPU_FAULT_PIPELINE_ABORT={self.pipeline_abort})")

    def on_dispatch(self) -> None:
        """Called before every supervised device call."""
        with self._lock:
            self.dispatch_count += 1
            n = self.dispatch_count
        if self.delay:
            time.sleep(self.delay)
        if self.wedge_every and n % self.wedge_every == 0:
            with self._lock:
                self.wedged += 1
            tracing.fault_event("RETH_TPU_FAULT_WEDGE_EVERY",
                                target="ops::supervisor", dispatch=n)
            raise InjectedWedge(
                f"injected wedge on dispatch #{n} "
                f"(every {self.wedge_every})")

    def on_probe(self) -> bool:
        """True = let the probe run; False = injected probe failure."""
        with self._lock:
            if self.probe_fail < 0:
                self.probes_failed += 1
                return False
            if self.probes_failed < self.probe_fail:
                self.probes_failed += 1
                return False
        return True


class CircuitBreaker:
    """closed → open → half-open breaker with exponential backoff.

    While CLOSED, failures accumulate; at ``failure_threshold`` consecutive
    failures the breaker OPENS for ``reset_timeout`` seconds (doubling per
    re-trip up to ``max_reset_timeout``). Once the cooldown elapses the
    breaker is HALF_OPEN: one trial (a health probe) decides — success
    closes and resets the backoff, failure re-opens with doubled backoff.
    """

    def __init__(self, failure_threshold: int = 3, reset_timeout: float = 30.0,
                 max_reset_timeout: float = 600.0, clock=time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.base_reset_timeout = reset_timeout
        self.max_reset_timeout = max_reset_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self.failures = 0          # consecutive, while closed
        self.trips = 0             # times the breaker opened
        self._timeout = reset_timeout
        self._open_until = 0.0
        self.transitions: list[str] = [CLOSED]  # state history (tests/events)

    def _set_state(self, state: str) -> None:
        if state != self.state:
            prev, self.state = self.state, state
            self.transitions.append(state)
            if state == OPEN:
                # the device route just went dark: this is exactly the
                # moment a postmortem needs the recent span history
                # (fault_event = event + rate-limited JSONL snapshot)
                tracing.fault_event("breaker_open", target="ops::supervisor",
                                    state=state, previous=prev,
                                    trips=self.trips)
            else:
                tracing.event("ops::supervisor", "breaker",
                              state=state, previous=prev, trips=self.trips)

    def allow(self) -> bool:
        """May a device call proceed right now? OPEN past its cooldown
        moves to HALF_OPEN (the caller should then run a trial probe)."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN and self._clock() >= self._open_until:
                self._set_state(HALF_OPEN)
            return self.state == HALF_OPEN

    def record_failure(self) -> bool:
        """Count one failure; returns True when this call opened the
        breaker (HALF_OPEN failure re-opens with doubled backoff)."""
        with self._lock:
            if self.state == HALF_OPEN:
                self.trips += 1
                self._timeout = min(self._timeout * 2, self.max_reset_timeout)
                self._open_until = self._clock() + self._timeout
                self._set_state(OPEN)
                return True
            self.failures += 1
            if self.state == CLOSED and self.failures >= self.failure_threshold:
                self.trips += 1
                self._open_until = self._clock() + self._timeout
                self._set_state(OPEN)
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            if self.state != CLOSED:
                self._timeout = self.base_reset_timeout
                self._set_state(CLOSED)

    def force_open(self) -> None:
        """Open immediately (startup probe failed: no point counting)."""
        with self._lock:
            if self.state != OPEN:
                self.trips += 1
                self._open_until = self._clock() + self._timeout
                self._set_state(OPEN)


class DeviceBreakerBoard:
    """Per-device circuit breakers over a ``parallel/mesh.py`` HashMesh —
    the MIDDLE rung of the degradation ladder (device → sub-mesh → CPU
    twin). One :class:`CircuitBreaker` per mesh device; a trip sheds that
    device from the mesh's health mask (shardings re-form over the
    survivors, the in-flight batch replays there) instead of routing the
    whole node to the CPU twin. The full CPU failover — the supervisor's
    existing all-or-nothing breaker — only fires once EVERY device has
    tripped (:meth:`exhausted`).

    Recovery is trial-by-fire: :meth:`poll` re-admits a device whose open
    cooldown elapsed (the breaker's HALF_OPEN transition); the next
    successful dispatch that includes it closes the breaker, the next
    attributed failure re-opens it with doubled backoff. There is no
    per-virtual-device subprocess probe — a mesh device's only meaningful
    health signal is a dispatch that includes it.
    """

    def __init__(self, mesh, failure_threshold: int | None = None,
                 reset_timeout: float | None = None, clock=time.monotonic):
        if failure_threshold is None:
            failure_threshold = int(
                os.environ.get("RETH_TPU_DEVICE_BREAKER_TRIPS", "3"))
        if reset_timeout is None:
            reset_timeout = float(
                os.environ.get("RETH_TPU_DEVICE_BREAKER_RESET", "30"))
        self.mesh = mesh
        self.breakers = [
            CircuitBreaker(failure_threshold=failure_threshold,
                           reset_timeout=reset_timeout, clock=clock)
            for _ in range(mesh.n_devices)
        ]
        self.trips = 0

    def record_failure(self, idx: int, attributed: bool = False) -> bool:
        """Count one failure against device ``idx``; an ATTRIBUTED failure
        (the error names the device — injected wedge, per-device XLA
        diagnostic) opens immediately, an unattributed one counts toward
        the threshold like any collective-participant suspicion. Returns
        True when this call shed the device from the mesh."""
        b = self.breakers[idx]
        if attributed:
            b.force_open()
        else:
            b.record_failure()
        if b.state == OPEN and self.mesh.is_healthy(idx):
            self.trips += 1
            return self.mesh.mark_unhealthy(
                idx, reason="attributed wedge" if attributed
                else "unattributed dispatch failures")
        return False

    def record_success(self, indices) -> None:
        """A dispatch over ``indices`` completed: clear their failure
        counts (and close any HALF_OPEN breaker that just survived its
        trial dispatch)."""
        for i in indices:
            self.breakers[i].record_success()

    def poll(self) -> int:
        """Re-admit devices whose open cooldown elapsed (``allow()`` moves
        OPEN past its deadline to HALF_OPEN). Returns how many devices
        rejoined the mesh; call before each mesh dispatch so recovery
        needs no extra thread."""
        rejoined = 0
        for i, b in enumerate(self.breakers):
            if not self.mesh.is_healthy(i) and b.allow():
                if self.mesh.mark_healthy(i):
                    rejoined += 1
        return rejoined

    def exhausted(self) -> bool:
        """True when no device remains healthy — the caller must take the
        final rung (CPU twin)."""
        return self.mesh.healthy_count == 0

    def snapshot(self) -> dict:
        states = [b.state for b in self.breakers]
        return {
            "devices": len(states),
            "open": sum(1 for s in states if s == OPEN),
            "half_open": sum(1 for s in states if s == HALF_OPEN),
            "trips": self.trips,
            "states": states,
        }


class DeviceSupervisor:
    """Owns every device dispatch on the state-commitment path.

    ``route()`` answers "device or numpy, right now" — consulting the
    breaker and, when the open-state cooldown has elapsed, running ONE
    half-open health probe whose outcome closes or re-opens it.
    ``run_guarded(fn, *args)`` executes a device call in a worker thread
    under ``dispatch_budget`` seconds; a timeout abandons the (wedged)
    thread and raises :class:`DeviceDispatchError` after informing the
    breaker. The supervisor never raises out of ``route()``: a sick device
    degrades to the CPU route, it does not take the node down.
    """

    def __init__(self, dispatch_budget: float | None = None,
                 probe_budget: float | None = None,
                 breaker: CircuitBreaker | None = None,
                 injector: FaultInjector | None = None,
                 probe_fn=None, registry=None):
        if dispatch_budget is None:
            dispatch_budget = float(
                os.environ.get("RETH_TPU_DISPATCH_BUDGET", "120"))
        self.dispatch_budget = dispatch_budget
        self.probe_budget = probe_budget
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=int(os.environ.get("RETH_TPU_BREAKER_TRIPS", "3")),
            reset_timeout=float(os.environ.get("RETH_TPU_BREAKER_RESET", "30")),
        )
        self.injector = injector if injector is not None else FaultInjector.from_env()
        self._probe_fn = probe_fn or probe_device
        from ..metrics import SupervisorMetrics

        self.metrics = SupervisorMetrics(registry)
        self.failovers = 0
        self.dispatch_timeouts = 0
        self.dispatch_errors = 0
        self.last_probe: ProbeResult | None = None
        self._probe_lock = threading.Lock()
        # warm-up manager attachment (ops/warmup.py): per-shape readiness
        # states ride here so committers/bench/events reach them through
        # the supervisor they already hold
        self.warmup = None
        self._publish()

    # -- shared instance (one supervisor per process, like REGISTRY) -------

    _shared: "DeviceSupervisor | None" = None
    _shared_lock = threading.Lock()

    @classmethod
    def shared(cls) -> "DeviceSupervisor":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        with cls._shared_lock:
            cls._shared = None

    # -- probes ------------------------------------------------------------

    def _probe(self) -> ProbeResult:
        result = self._probe_fn(self.probe_budget, injector=self.injector)
        self.last_probe = result
        self.metrics.record_probe(result.ok, result.latency)
        return result

    def startup(self) -> bool:
        """Startup health probe (``--hasher auto``): an unhealthy device
        opens the breaker immediately, so the node boots on the CPU route
        instead of wedging on its first commit."""
        result = self._probe()
        if result.ok:
            self.breaker.record_success()
        else:
            self.breaker.force_open()
            self.metrics.record_trip()
        self._publish()
        return result.ok

    # -- routing -----------------------------------------------------------

    def route(self) -> str:
        """"device" | "numpy" — where hashing should run right now. A
        HALF_OPEN breaker runs one trial probe inline; its outcome decides
        the route AND the breaker's next state."""
        if not self.breaker.allow():
            self._publish()
            return "numpy"
        if self.breaker.state == HALF_OPEN:
            with self._probe_lock:
                # re-check under the lock: another thread's probe may have
                # already closed or re-opened the breaker
                if self.breaker.state == HALF_OPEN:
                    if self._probe().ok:
                        self.breaker.record_success()
                        if self.warmup is not None:
                            # the device just came back: promote any
                            # compile-FAILED shapes in the background
                            self.warmup.on_device_recovered()
                    else:
                        self.breaker.record_failure()
                        self.metrics.record_trip()
            self._publish()
            return "device" if self.breaker.state == CLOSED else "numpy"
        return "device"

    def allows_device(self) -> bool:
        return self.route() == "device"

    def warmup_allows_device(self) -> bool:
        """Commit-level warm-up gate (fused path): a fused commit's
        resident digest buffer can't hop backends at a shape boundary, so
        the whole commit stays on the CPU twin until every menu shape is
        warm. True when no warm-up manager is attached."""
        return self.warmup is None or self.warmup.device_ready()

    # -- watchdog-bounded dispatch ----------------------------------------

    def run_guarded(self, fn, *args, what: str = "dispatch",
                    budget: float | None = None):
        """Run ``fn(*args)`` under the wall-clock ``budget`` in a worker
        thread. On timeout the wedged thread is abandoned (a stuck device
        call cannot be cancelled — the breaker keeps further work away
        from it) and :class:`DeviceDispatchError` is raised; any exception
        from ``fn`` is re-raised wrapped. Both count as breaker failures."""
        if budget is None:
            budget = self.dispatch_budget
        try:
            box: list = [None, None]  # [result, exception]
            injector = self.injector

            def _call():
                try:
                    if injector is not None:
                        # inside the worker so an injected DELAY above the
                        # budget exercises the REAL join-timeout path
                        injector.on_dispatch()
                    box[0] = fn(*args)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    box[1] = e

            t = threading.Thread(target=_call, daemon=True,
                                 name=f"supervised-{what}")
            t.start()
            t.join(budget)
            if t.is_alive():
                self.dispatch_timeouts += 1
                self.metrics.record_timeout()
                tracing.fault_event("watchdog_timeout",
                                    target="ops::supervisor",
                                    what=what, budget_s=budget)
                raise DeviceDispatchError(
                    f"device {what} exceeded {budget}s watchdog budget")
            if box[1] is not None:
                raise DeviceDispatchError(
                    f"device {what} failed: {box[1]}") from box[1]
        except DeviceDispatchError:
            self.dispatch_errors += 1
            if self.breaker.record_failure():
                self.metrics.record_trip()
            self._publish()
            raise
        self.breaker.record_success()
        return box[0]

    def record_failover(self) -> None:
        self.failovers += 1
        self.metrics.record_failover()
        self._publish()

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        """State for the events dashboard and bench triage."""
        lp = self.last_probe
        return {
            "breaker": self.breaker.state,
            "trips": self.breaker.trips,
            "failures": self.breaker.failures,
            "failovers": self.failovers,
            "dispatch_timeouts": self.dispatch_timeouts,
            "dispatch_errors": self.dispatch_errors,
            "probe_ok": None if lp is None else lp.ok,
            "probe_latency": None if lp is None else round(lp.latency, 3),
            "fault_injection": (self.injector.active()
                                if self.injector is not None else False),
            "warmup": (None if self.warmup is None
                       else self.warmup.overall_state()),
        }

    def _publish(self) -> None:
        self.metrics.set_state(self.breaker.state)


class SupervisedBackend:
    """Turbo array-protocol backend: device engine under the watchdog with
    journaled mid-commit CPU failover.

    Every dispatch's inputs are host numpy arrays (the committer is
    level-batched), so the backend journals ``(method, args)`` as it
    forwards them. When a device call trips the watchdog — or the device
    route is already broken — a fresh ``_NumpyBackend`` replays the journal
    and the commit RESUMES at the current level boundary on the CPU: the
    same commit, the same state root, no block lost. Terminal calls
    (``finish`` / ``fetch_slots``) are guarded too, since an async-dispatch
    engine often only blocks at its sync point.
    """

    def __init__(self, supervisor: DeviceSupervisor, device_factory,
                 arena=None):
        self.sup = supervisor
        self._factory = device_factory
        self._arena = arena  # resident DigestArena for the CPU twin
        self._journal: list[tuple[str, tuple]] = []
        self._device = None
        self._cpu = None
        self.failed_over = False

    @property
    def effective_kind(self) -> str:
        return "numpy" if self._cpu is not None else "device"

    def _failover(self, mid_commit: bool) -> None:
        from ..trie.turbo import _NumpyBackend

        self._device = None
        self._cpu = _NumpyBackend(arena=self._arena)
        if mid_commit and not self.failed_over:
            self.failed_over = True
            self.sup.record_failover()
        for name, args in self._journal:
            getattr(self._cpu, name)(*args)

    def _call(self, name: str, *args):
        if self._device is not None:
            try:
                out = self.sup.run_guarded(
                    getattr(self._device, name), *args, what=name)
                self._journal.append((name, args))
                return out
            except DeviceDispatchError:
                # replays the journal: the commit resumes HERE, at the
                # current level boundary, on the CPU twin
                self._failover(mid_commit=True)
        elif self._cpu is None:
            # breaker already open before the commit started: plain CPU
            # routing, not a mid-commit failover
            self._failover(mid_commit=False)
        self._journal.append((name, args))
        return getattr(self._cpu, name)(*args)

    # -- array protocol (turbo backends + FusedLevelEngine callers) --------

    def begin(self, max_slots: int) -> None:
        self._journal = []
        self._device, self._cpu = None, None
        self.failed_over = False
        # warm-up gate first (cheap, no probe): a commit started during
        # warm-up serves on the CPU twin — degraded mode, not a failover
        if self.sup.warmup_allows_device() and self.sup.route() == "device":
            try:
                self._device = self.sup.run_guarded(
                    self._factory, what="engine init")
            except DeviceDispatchError:
                # the commit was headed for the device and fell over —
                # counts as a failover even though no level ran yet
                self._failover(mid_commit=True)
        self._call("begin", max_slots)

    def alloc_slot(self) -> int:
        """Host-side counter on whichever twin is live; journaled so a
        replayed CPU twin's counter stays in sync (no watchdog — this
        never touches the device)."""
        self._journal.append(("alloc_slot", ()))
        live = self._device if self._device is not None else self._cpu
        return live.alloc_slot()

    def ensure(self, max_slots: int) -> None:
        """Arena-growth protocol (pipelined rebuild): guarded on the device
        and journaled, so a replayed CPU twin re-grows to the same capacity
        before the journal's later dispatches land."""
        self._call("ensure", max_slots)

    def dispatch_level(self, bucket):
        """Committer bucket protocol (TrieCommitter fused hash phase)."""
        self._call("dispatch_level", bucket)

    def dispatch_packed(self, flat, row_off, row_len, slots, holes, b_tier):
        self._call("dispatch_packed", flat, row_off, row_len, slots, holes,
                   b_tier)

    def dispatch_branch(self, masks, slots, children):
        self._call("dispatch_branch", masks, slots, children)

    def flush_window(self):
        """Window-boundary hook: a whole-subtrie engine executes its
        staged k-level chunks here (guarded + journaled like any device
        call — a wedge mid-window replays the journal on the CPU twin);
        per-level engines don't expose it and defer to finish."""
        if self._device is not None and not hasattr(self._device,
                                                    "flush_window"):
            return
        self._call("flush_window")

    def fetch_slots(self, slots):
        return self._call("fetch_slots", slots)

    def finish(self):
        return self._call("finish")


class SupervisedHasher:
    """``hash_batch``-protocol wrapper: device keccak under the watchdog,
    numpy fallback. Hashing is stateless, so failover is simply re-running
    the batch on the CPU — no journal needed. This is what the live-tip
    paths (``TrieCommitter``, ``engine/sparse_root.py``,
    ``engine/pipelined_root.py``) call, so a wedged tunnel mid-block
    degrades the block's root job to the CPU instead of hanging the node.
    """

    def __init__(self, supervisor: DeviceSupervisor, device_hasher=None,
                 cpu_hasher=None, min_tier: int = 1024, warmup=None):
        self.sup = supervisor
        self._device = device_hasher
        self._min_tier = min_tier
        self._warmup = warmup
        if cpu_hasher is None:
            from ..primitives.keccak import keccak256_batch_np

            cpu_hasher = keccak256_batch_np
        self._cpu = cpu_hasher

    def _device_hasher(self):
        if self._device is None:
            from .keccak_jax import KeccakDevice

            # the warm-up manager (explicit, or attached to the supervisor
            # after construction) gates each bucket: un-warm shapes hash on
            # the CPU twin instead of compiling mid-commit
            warmup = self._warmup if self._warmup is not None else self.sup.warmup
            self._device = KeccakDevice(
                min_tier=self._min_tier, block_tier=4,
                warmup=warmup).hash_batch
        return self._device

    def __call__(self, msgs):
        if self.sup.route() == "device":
            try:
                return self.sup.run_guarded(
                    self._device_hasher(), msgs, what="hash_batch")
            except DeviceDispatchError:
                pass
        return self._cpu(msgs)
