"""Pallas TPU kernel: fused single-block keccak-256.

Reference analogue: the asm-keccak fast path, but as a hand-written TPU
kernel. Versus the XLA lowering in ``keccak_jax``, the whole
absorb+24-round permutation runs as ONE Pallas kernel: the 50 uint32
lane-halves live in registers/VMEM for the entire permutation (zero
intermediate HBM traffic), with the batch dimension mapped onto the
VPU's 128-lane axis and a grid over batch tiles.

Layout: inputs (34, N) uint32 — word-major so each of the 34 message
words is one VPU row; outputs (8, N). Batch tiles of 256 lanes.

Use ``RETH_TPU_PALLAS=1`` to route KeccakDevice's single-block bucket
through this kernel (falls back to the XLA path on failure).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..primitives.keccak import RC, ROT

LANES = 256  # batch tile width (multiple of the VPU's 128 lanes)

_RC_LO = [rc & 0xFFFFFFFF for rc in RC]
_RC_HI = [rc >> 32 for rc in RC]


def _rotl_pair(lo, hi, r: int):
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r > 32:
        lo, hi = hi, lo
        r -= 32
    rr = 32 - r
    return ((lo << r) | (hi >> rr), (hi << r) | (lo >> rr))


def _keccak_kernel(in_ref, rc_lo_ref, rc_hi_ref, out_ref):
    """One batch tile: absorb one rate block + keccak-f[1600] + squeeze.

    Rounds run under ``lax.fori_loop`` (compact program; the VPU still sees
    static per-lane rotations in the body — only the round constant is
    dynamically indexed).
    """
    zero = jnp.zeros((LANES,), dtype=jnp.uint32)
    alo = [in_ref[2 * i, :] if i < 17 else zero for i in range(25)]
    ahi = [in_ref[2 * i + 1, :] if i < 17 else zero for i in range(25)]

    def round_fn(rnd, state):
        alo, ahi = list(state[0]), list(state[1])
        clo = [alo[x] ^ alo[x + 5] ^ alo[x + 10] ^ alo[x + 15] ^ alo[x + 20] for x in range(5)]
        chi = [ahi[x] ^ ahi[x + 5] ^ ahi[x + 10] ^ ahi[x + 15] ^ ahi[x + 20] for x in range(5)]
        for x in range(5):
            rl, rh = _rotl_pair(clo[(x + 1) % 5], chi[(x + 1) % 5], 1)
            dlo = clo[(x - 1) % 5] ^ rl
            dhi = chi[(x - 1) % 5] ^ rh
            for y in range(5):
                alo[x + 5 * y] = alo[x + 5 * y] ^ dlo
                ahi[x + 5 * y] = ahi[x + 5 * y] ^ dhi
        blo = [None] * 25
        bhi = [None] * 25
        for x in range(5):
            for y in range(5):
                rl, rh = _rotl_pair(alo[x + 5 * y], ahi[x + 5 * y], ROT[x][y])
                dst = y + 5 * ((2 * x + 3 * y) % 5)
                blo[dst] = rl
                bhi[dst] = rh
        for x in range(5):
            for y in range(5):
                i1 = (x + 1) % 5 + 5 * y
                i2 = (x + 2) % 5 + 5 * y
                alo[x + 5 * y] = blo[x + 5 * y] ^ (~blo[i1] & blo[i2])
                ahi[x + 5 * y] = bhi[x + 5 * y] ^ (~bhi[i1] & bhi[i2])
        alo[0] = alo[0] ^ rc_lo_ref[rnd]
        ahi[0] = ahi[0] ^ rc_hi_ref[rnd]
        return (tuple(alo), tuple(ahi))

    alo, ahi = jax.lax.fori_loop(0, 24, round_fn, (tuple(alo), tuple(ahi)))
    # squeeze 32 bytes = lanes 0..3
    for i in range(4):
        out_ref[2 * i, :] = alo[i]
        out_ref[2 * i + 1, :] = ahi[i]


@partial(jax.jit, static_argnums=1)
def keccak256_pallas_wordsT(wordsT, interpret: bool = False):
    """Single-block keccak over word-major input.

    ``wordsT``: (34, N) uint32, N a multiple of LANES. Returns (8, N).
    """
    from jax.experimental.pallas import tpu as pltpu

    n = wordsT.shape[1]
    grid = (n // LANES,)
    rc_lo = jnp.asarray(_RC_LO, dtype=jnp.uint32)
    rc_hi = jnp.asarray(_RC_HI, dtype=jnp.uint32)
    if interpret:
        rc_specs = [pl.BlockSpec((24,), lambda i: (0,))] * 2
    else:
        rc_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
    return pl.pallas_call(
        _keccak_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        grid=grid,
        in_specs=[pl.BlockSpec((34, LANES), lambda i: (0, i))] + rc_specs,
        out_specs=pl.BlockSpec((8, LANES), lambda i: (0, i)),
        interpret=interpret,
    )(wordsT, rc_lo, rc_hi)


def keccak256_pallas_words(words, interpret: bool = False):
    """Drop-in for ``keccak256_jax_words(words, 1)``: (N, 34) → (N, 8).

    Pads the batch up to a LANES multiple; transposes at the boundary
    (cheap relative to the permutation).
    """
    n = words.shape[0]
    tiles = -(-n // LANES)
    padded = tiles * LANES
    w = jnp.asarray(words, dtype=jnp.uint32)
    if padded != n:
        w = jnp.concatenate(
            [w, jnp.zeros((padded - n, 34), dtype=jnp.uint32)], axis=0
        )
    out = keccak256_pallas_wordsT(w.T, interpret)
    return out.T[:n]
