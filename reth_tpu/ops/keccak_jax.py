"""Batched Keccak-256 as a JAX/XLA kernel — the TPU hashing data plane.

This replaces the reference's CPU keccak hot loops (`asm-keccak` sha3-asm,
rayon chunks in AccountHashingStage — reference
crates/stages/stages/src/stages/hashing_account.rs:29-32 — and the
sparse-trie `update_subtrie_hashes` keccak loop — reference
crates/trie/sparse/src/arena/mod.rs:2500-2548) with a single batched,
shape-stable device program.

TPU-first design notes:
- 64-bit lanes are emulated as (hi, lo) uint32 pairs: the TPU VPU is a
  32-bit vector ISA; all keccak ops are XOR/AND/NOT/rot so the emulation
  is exact and cheap. Rotation amounts are compile-time constants, so each
  lane's rotate lowers to static shifts.
- Lane-major layout ``(25, N)``: each lane is a contiguous vector over the
  batch; every op is elementwise over N and vectorises onto the 8x128 VPU.
  No gathers, no dynamic shapes.
- 24 rounds via ``lax.fori_loop`` (round constants indexed dynamically) —
  traced once, compiled once per (num_blocks, N-tier).
- Variable-length messages are bucketed by 136-byte rate-block count and
  padded to power-of-two batch tiers, so the number of distinct compiled
  programs is O(#block-buckets x #tiers), not O(#shapes).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..primitives.keccak import RC, ROT, pad_batch, bucketed_hash

# Round constants as (24, 2) uint32: [:, 0] = lo, [:, 1] = hi.
_RC_WORDS = np.array([[rc & 0xFFFFFFFF, rc >> 32] for rc in RC], dtype=np.uint32)


def _rotl_pair(lo, hi, r: int):
    """Rotate a 64-bit lane (as uint32 lo/hi) left by static r."""
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r > 32:
        lo, hi = hi, lo
        r -= 32
    rr = 32 - r
    new_lo = (lo << r) | (hi >> rr)
    new_hi = (hi << r) | (lo >> rr)
    return new_lo, new_hi


def keccak_f1600_jax(lo, hi):
    """keccak-f[1600] over a batch. ``lo``/``hi``: (25, N) uint32 arrays."""
    rc = jnp.asarray(_RC_WORDS)

    def round_fn(i, state):
        slo, shi = state
        alo = [slo[j] for j in range(25)]
        ahi = [shi[j] for j in range(25)]
        # theta
        clo = [alo[x] ^ alo[x + 5] ^ alo[x + 10] ^ alo[x + 15] ^ alo[x + 20] for x in range(5)]
        chi_ = [ahi[x] ^ ahi[x + 5] ^ ahi[x + 10] ^ ahi[x + 15] ^ ahi[x + 20] for x in range(5)]
        for x in range(5):
            rl, rh = _rotl_pair(clo[(x + 1) % 5], chi_[(x + 1) % 5], 1)
            dlo = clo[(x - 1) % 5] ^ rl
            dhi = chi_[(x - 1) % 5] ^ rh
            for y in range(5):
                alo[x + 5 * y] = alo[x + 5 * y] ^ dlo
                ahi[x + 5 * y] = ahi[x + 5 * y] ^ dhi
        # rho + pi
        blo = [None] * 25
        bhi = [None] * 25
        for x in range(5):
            for y in range(5):
                rl, rh = _rotl_pair(alo[x + 5 * y], ahi[x + 5 * y], ROT[x][y])
                dst = y + 5 * ((2 * x + 3 * y) % 5)
                blo[dst] = rl
                bhi[dst] = rh
        # chi
        for x in range(5):
            for y in range(5):
                i1 = (x + 1) % 5 + 5 * y
                i2 = (x + 2) % 5 + 5 * y
                alo[x + 5 * y] = blo[x + 5 * y] ^ (~blo[i1] & blo[i2])
                ahi[x + 5 * y] = bhi[x + 5 * y] ^ (~bhi[i1] & bhi[i2])
        # iota
        alo[0] = alo[0] ^ rc[i, 0]
        ahi[0] = ahi[0] ^ rc[i, 1]
        return jnp.stack(alo), jnp.stack(ahi)

    return lax.fori_loop(0, 24, round_fn, (lo, hi))


def _squeeze256(lo, hi):
    """First 4 lanes -> (N, 8) uint32 digest words [lo0,hi0,lo1,hi1,...]."""
    return jnp.stack([lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]], axis=1)


def absorb_single_block(words):
    """Single-rate-block keccak-256: (N, 34) uint32 words → (N, 8) digests.

    The canonical one-block absorb — the mesh/sharding layer and the graft
    entry build on this exact function so the lane layout lives in one place.
    """
    n = words.shape[0]
    w = words.reshape(n, 17, 2).transpose(1, 2, 0)  # (17, 2, N)
    lo = jnp.zeros((25, n), dtype=jnp.uint32).at[:17].set(w[:, 0, :])
    hi = jnp.zeros((25, n), dtype=jnp.uint32).at[:17].set(w[:, 1, :])
    lo, hi = keccak_f1600_jax(lo, hi)
    return _squeeze256(lo, hi)


@partial(jax.jit, static_argnums=1)
def keccak256_jax_words(words, num_blocks: int):
    """Keccak-256 over pre-padded messages, all with the same block count.

    ``words``: (N, num_blocks*34) uint32 — little-endian 32-bit words of the
    padded message (as produced by ``primitives.keccak.pad_batch`` viewed as
    '<u4'); even indices are lane-lo, odd are lane-hi.
    Returns (N, 8) uint32 — the 32-byte digests as little-endian words.

    The absorb loop is a ``fori_loop`` (not a Python unroll), so trace size
    is constant in ``num_blocks``; XLA still compiles one program per
    distinct (num_blocks, N) shape — the batching front-end bounds both.
    """
    n = words.shape[0]
    w = words.reshape(n, num_blocks, 17, 2).transpose(1, 2, 3, 0)  # (B, 17, 2, N)

    def absorb(blk, state):
        lo, hi = state
        blkw = lax.dynamic_index_in_dim(w, blk, axis=0, keepdims=False)
        lo = lo.at[:17].set(lo[:17] ^ blkw[:, 0, :])
        hi = hi.at[:17].set(hi[:17] ^ blkw[:, 1, :])
        return keccak_f1600_jax(lo, hi)

    zero = jnp.zeros((25, n), dtype=jnp.uint32)
    lo, hi = lax.fori_loop(0, num_blocks, absorb, (zero, zero))
    return _squeeze256(lo, hi)


def masked_absorb_words(words, max_blocks: int, counts):
    """Non-jitted masked-absorb core shared by the batch front-end and the
    fused level committer (``ops.fused_commit``): messages of differing block
    counts in one batch, each padded at its OWN final rate block and
    zero-extended to ``max_blocks``. Blocks at index >= ``counts[i]`` leave
    message i's state untouched. Returns (N, 8) uint32 digests."""
    n = words.shape[0]
    w = words.reshape(n, max_blocks, 17, 2).transpose(1, 2, 3, 0)

    def absorb(blk, state):
        lo, hi = state
        blkw = lax.dynamic_index_in_dim(w, blk, axis=0, keepdims=False)
        nlo = lo.at[:17].set(lo[:17] ^ blkw[:, 0, :])
        nhi = hi.at[:17].set(hi[:17] ^ blkw[:, 1, :])
        nlo, nhi = keccak_f1600_jax(nlo, nhi)
        live = (blk < counts)[None, :]  # (1, N) broadcast over lanes
        return jnp.where(live, nlo, lo), jnp.where(live, nhi, hi)

    zero = jnp.zeros((25, n), dtype=jnp.uint32)
    lo, hi = lax.fori_loop(0, max_blocks, absorb, (zero, zero))
    return _squeeze256(lo, hi)


@partial(jax.jit, static_argnums=1)
def keccak256_jax_words_masked(words, max_blocks: int, counts=None):
    """Jitted wrapper over :func:`masked_absorb_words` (one program per
    (max_blocks, N) shape tier — the batching front-end bounds both)."""
    return masked_absorb_words(words, max_blocks, counts)


def _next_tier(n: int, min_tier: int = 8, max_tier: int | None = None) -> int:
    """Pow2 tier ladder from ``min_tier``; ``max_tier`` clamps growth to a
    declared ceiling (the warm-up shape menu, ops/warmup.py) — callers must
    chunk batches above it rather than minting an unbounded new tier."""
    t = min_tier
    while t < n:
        t *= 2
    if max_tier is not None and t > max_tier:
        return max_tier
    return t


# one shared sentinel bucket for messages above the declared block-tier
# ceiling: they hash on the CPU twin instead of minting a fresh program
_CPU_BUCKET = 1 << 30


def _to_u32(words: np.ndarray, batch_tier: int) -> np.ndarray:
    """(n, W) uint64 padded words → (batch_tier, 2W) uint32, zero row-padded."""
    n, w = words.shape
    if batch_tier != n:
        words = np.vstack([words, np.zeros((batch_tier - n, w), dtype=np.uint64)])
    return np.ascontiguousarray(words).view("<u4").reshape(batch_tier, 2 * w)


class KeccakDevice:
    """Host-side batching front-end for the device keccak kernel.

    This is the host↔device marshalling layer — the analogue of the
    reference's rayon worker-chunk boundary (the "NCCL boundary" of this
    single-chip design, see SURVEY.md §5). Callers hand over lists of
    byte-strings; it buckets by block count, pads batches to power-of-two
    tiers (shape-stable → bounded number of XLA compilations), runs the
    kernel, and returns digests in order.
    """

    # Block counts <= this get their own exactly-sized program; larger
    # messages (contract bytecode etc.) share masked programs at
    # power-of-two block tiers so compilation count stays bounded.
    MAX_EXACT_BLOCKS = 8
    # Declared menu ceilings (ops/warmup.py default_menu): batches above
    # MAX_BATCH_TIER are chunked; messages above MAX_BLOCK_TIER rate blocks
    # hash on the CPU twin — either way no request can mint a program shape
    # outside the warm-up menu (and trigger a fresh compile) mid-commit.
    MAX_BATCH_TIER = 16384
    MAX_BLOCK_TIER = 32

    def __init__(self, min_tier: int = 8, block_tier: int | None = None,
                 warmup=None, max_batch_tier: int | None = None,
                 max_block_tier: int | None = None):
        """``block_tier``: if set, ALL messages up to that many rate blocks
        share one masked program per batch tier (compile-count-minimal mode
        for workloads with a known size ceiling, e.g. trie nodes <= 4
        blocks); larger messages still fall back to pow2 tiers above it.
        ``warmup``: an ``ops/warmup.py`` WarmupManager — buckets whose
        (program, block_tier, batch_tier) shape is not warm yet hash on the
        CPU twin instead of compiling inside a live dispatch.
        """
        self.min_tier = min_tier
        self.block_tier = block_tier
        self.warmup = warmup
        if max_block_tier is None:
            max_block_tier = self.MAX_BLOCK_TIER
        self.max_block_tier = max_block_tier
        if max_batch_tier is None:
            max_batch_tier = self.MAX_BATCH_TIER
        # keep the ceiling ON the pow2 ladder from min_tier, so the chunk
        # cap can never round up past it inside _hash_bucket
        cap = min_tier
        while cap * 2 <= max_batch_tier:
            cap *= 2
        self.max_batch_tier = cap

    def hash_batch(self, msgs: list[bytes]) -> list[bytes]:
        cap = self.max_batch_tier
        if len(msgs) > cap:
            # one huge request never mints a tier above the menu ceiling:
            # dispatch ceiling-sized chunks (order preserved)
            out: list[bytes] = []
            for lo in range(0, len(msgs), cap):
                out.extend(bucketed_hash(msgs[lo:lo + cap],
                                         self._hash_bucket,
                                         bucket_key=self._bucket_key))
            return out
        return bucketed_hash(msgs, self._hash_bucket, bucket_key=self._bucket_key)

    def _bucket_key(self, nb: int) -> int:
        """Exact program for small block counts; shared pow2 tier above —
        clamped at the menu ceiling (over-ceiling messages share the CPU
        bucket)."""
        if nb > self.max_block_tier:
            return _CPU_BUCKET
        if self.block_tier is not None:
            if nb <= self.block_tier:
                return self.block_tier
            return _next_tier(nb, 2 * self.block_tier)
        if nb <= self.MAX_EXACT_BLOCKS:
            return nb
        return _next_tier(nb, 2 * self.MAX_EXACT_BLOCKS)

    @staticmethod
    def _cpu_bucket(sub: list[bytes], counts: np.ndarray) -> np.ndarray:
        """CPU-twin bucket: same row-viewable digest contract as the device
        paths (rows ``.tobytes()`` == the 32-byte digest)."""
        from ..primitives.keccak import keccak256_words_masked_np

        words = pad_batch(sub, counts)
        return keccak256_words_masked_np(words, int(counts.max()), counts)

    def _hash_bucket(self, sub: list[bytes], key: int, counts: np.ndarray) -> np.ndarray:
        """Hash one bucket; returns (n, 8) uint32 digests. Every dispatch
        reports its (program kind, block count, batch tier) shape and wall
        to the compile tracker: the FIRST call of a shape is its XLA
        compile, so compile storms show up split from steady-state
        dispatch instead of masquerading as slow hashing."""
        import os
        import time as _time

        from ..metrics import compile_tracker

        n = len(sub)
        batch_tier = _next_tier(n, self.min_tier, self.max_batch_tier)
        if key == _CPU_BUCKET:
            # over the declared block-tier ceiling: CPU twin, no new program
            return self._cpu_bucket(sub, counts)
        if self.warmup is not None:
            kind = ("keccak.exact"
                    if self.block_tier is None and key <= self.MAX_EXACT_BLOCKS
                    else "keccak.masked")
            if not self.warmup.route_bucket(kind, key, batch_tier):
                # shape not warm yet (degraded-mode serving): hash this
                # bucket on the CPU twin; it promotes to the device the
                # moment the warm-up manager marks the shape WARM
                return self._cpu_bucket(sub, counts)
        if key == 1 and os.environ.get("RETH_TPU_PALLAS"):
            # hand-written fused kernel for the dominant single-block bucket;
            # any lowering failure falls back to the XLA path below
            try:
                from .keccak_pallas import keccak256_pallas_words

                w32 = _to_u32(pad_batch(sub, 1), batch_tier)
                t0 = _time.perf_counter()
                out = np.asarray(keccak256_pallas_words(w32))[:n]
                compile_tracker.record("keccak.pallas", (1, batch_tier),
                                       _time.perf_counter() - t0)
                return out
            except Exception:
                pass
        t0 = _time.perf_counter()
        if self.block_tier is None and key <= self.MAX_EXACT_BLOCKS:
            kind = "keccak.exact"
            w32 = _to_u32(pad_batch(sub, key), batch_tier)
            digests = keccak256_jax_words(jnp.asarray(w32), key)
        else:
            kind = "keccak.masked"
            words = pad_batch(sub, counts, pad_to_blocks=key)
            w32 = _to_u32(words, batch_tier)
            cnt = np.zeros((batch_tier,), dtype=np.int32)
            cnt[:n] = counts
            digests = keccak256_jax_words_masked(jnp.asarray(w32), key, counts=jnp.asarray(cnt))
        out = np.asarray(digests)[:n]  # D2H sync point: wall is honest here
        compile_tracker.record(kind, (key, batch_tier),
                               _time.perf_counter() - t0)
        return out

    def hash_one(self, msg: bytes) -> bytes:
        return self.hash_batch([msg])[0]


def keccak256_batch_jax(msgs: list[bytes]) -> list[bytes]:
    """One-shot convenience wrapper around a default ``KeccakDevice``."""
    return _DEFAULT_DEVICE.hash_batch(msgs)


_DEFAULT_DEVICE = KeccakDevice()
